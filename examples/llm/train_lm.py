"""Long-context LM training — the parallelism-suite showcase.

No reference counterpart (the reference's model zoo stops at CNNs /
wide-and-deep; SURVEY.md §5.7): this example exists because long-context and
model parallelism are first-class in the TPU build.  A decoder-only
transformer trains over a mesh combining data (dp), tensor (tp, Megatron
layouts) and sequence (sp, ring attention over ICI neighbours) parallelism;
on TPU the attention runs the Pallas flash kernel when sp=1.

Runs standalone on whatever devices are visible:

  # 8 virtual CPU devices, ring attention over sp=2:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python train_lm.py --tp 2 --sp 2 --seq-len 512 --steps 10

  # 1F1B pipeline over pp=2 stages, dp over the remaining devices:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python train_lm.py --pp 2 --batch 16 --seq-len 512 --steps 10

  # single real TPU chip, Pallas flash attention:
  python train_lm.py --seq-len 2048 --steps 20
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def synthetic_ids(batch, seq_len, vocab, seed=0):
    """Zipf-ish token stream: enough structure for the loss to move."""
    import numpy as np

    rng = np.random.RandomState(seed)
    base = rng.zipf(1.5, size=(batch, seq_len)).astype("int64")
    return (base % vocab).astype("int32")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--vocab-size", type=int, default=4096)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-experts", type=int, default=0, help=">0 enables MoE over ep")
    p.add_argument("--seq-len", type=int, default=1024)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--ep", type=int, default=1)
    p.add_argument("--pp", type=int, default=1,
                   help=">1 trains the blocks as a 1F1B pipeline over pp "
                        "stages (embed + loss head outside the pipe, "
                        "O(stages) activation memory); requires tp=sp=ep=1")
    p.add_argument("--pp-microbatches", type=int, default=4)
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--vocab-chunk", type=int, default=0,
                   help=">0 fuses the lm_head into a blockwise cross-entropy "
                        "(ops/xent.py) — never materializes [B,S,V] logits; "
                        "use with tp=1")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize per-block activations (jax.checkpoint)"
                        " — O(1) residuals per block for ~1/3 extra FLOPs")
    p.add_argument("--accum-steps", type=int, default=1,
                   help=">1 splits each batch into microbatches and "
                        "accumulates gradients before the optimizer update")
    p.add_argument("--generate", type=int, default=0,
                   help=">0 greedily decodes this many tokens after training "
                        "(KV-cache serving loop)")
    p.add_argument("--profile-dir", default="",
                   help="write a jax profiler trace of the steady state here")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tensorflowonspark_tpu import profiling
    from tensorflowonspark_tpu.models import transformer as tfm
    from tensorflowonspark_tpu.parallel import dp as dplib
    from tensorflowonspark_tpu.parallel import mesh as meshlib
    from tensorflowonspark_tpu.parallel import tp as tplib

    if args.pp > 1:
        _train_pipelined(args)
        return

    mesh = meshlib.make_mesh(dp=-1, tp=args.tp, sp=args.sp, ep=args.ep)
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))} "
          f"on {jax.default_backend()}")

    attn_impl = "ring" if args.sp > 1 else "auto"
    model = tfm.Transformer(
        vocab_size=args.vocab_size, d_model=args.d_model,
        n_layers=args.n_layers, n_heads=args.n_heads,
        n_experts=args.n_experts, attn_impl=attn_impl, mesh=mesh,
        compute_dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        remat=args.remat)

    ids = jnp.asarray(synthetic_ids(args.batch, args.seq_len, args.vocab_size))
    # init traces the model too, so the init batch must satisfy the same
    # mesh divisibility as training batches (the ring-attention shard_map).
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    n_params = sum(x.size for x in jax.tree.leaves(params))
    print(f"model: {n_params/1e6:.2f}M params, attn={attn_impl}")

    optimizer = optax.adamw(args.lr)
    with jax.set_mesh(mesh):
        shardings = tplib.rule_shardings(mesh, params,
                                         tplib.TRANSFORMER_TP_RULES)
        shardings = tplib.compose_fsdp(mesh, params, shardings)
        params = meshlib.shard_tree(mesh, params, shardings)
        state = dplib.TrainState.create(params, optimizer)
        step = dplib.make_train_step(
            tfm.make_loss_fn(model, vocab_chunk=args.vocab_chunk), optimizer,
            accum_steps=args.accum_steps)
        batch = meshlib.shard_batch(mesh, {"input_ids": np.asarray(ids)})

        state, metrics = step(state, batch)  # compile
        print(f"step 0: loss={float(metrics['loss']):.4f}")

        def one_step():
            nonlocal state
            state, m = step(state, batch)
            return m

        t0 = time.perf_counter()
        if args.profile_dir:
            # warmup already happened (the compile step above), so the timed
            # window covers exactly args.steps executions.
            metrics = profiling.profile_steps(args.profile_dir, one_step,
                                              warmup=0, steps=args.steps)
        else:
            for _ in range(args.steps):
                metrics = one_step()
        loss = float(metrics["loss"])  # fetch = sync
        dt = time.perf_counter() - t0

        tokens = args.batch * args.seq_len * args.steps
        print(f"step {args.steps}: loss={loss:.4f} "
              f"({tokens / dt:,.0f} tokens/sec)")
        params_host = jax.device_get(state.params) if args.generate else None

    if args.generate:
        # Outside the mesh context: decode is a batch-1 single-device loop,
        # and the model's activation-sharding hints no-op without a mesh.
        # Re-place the host snapshot once: handing numpy params to the jitted
        # decode step would re-transfer the full weight tree host->device on
        # EVERY generated token.
        decode_params = jax.device_put(params_host, jax.devices()[0])
        prompt = np.asarray(ids[:1, :8])
        out = tfm.greedy_generate(model.clone(mesh=None, attn_impl="xla"),
                                  decode_params, jnp.asarray(prompt),
                                  max_new_tokens=args.generate)
        print(f"generated: {out[0].tolist()}")


def _train_pipelined(args) -> None:
    """1F1B pipeline-parallel LM training (--pp N).

    Blocks are the pipeline stages (``n_layers / pp`` per stage); the
    embedding and the loss head (final norm + lm_head + shifted
    cross-entropy) live outside the pipe and train through
    ``pipeline_1f1b``'s ``head_params`` / ``with_input_grad`` paths — every
    parameter gets the sequential gradient (tests/test_parallel_pp.py).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.models import transformer as tfm
    from tensorflowonspark_tpu.parallel import mesh as meshlib
    from tensorflowonspark_tpu.parallel import pp as pplib

    if args.tp != 1 or args.sp != 1 or args.ep != 1 or args.n_experts:
        raise SystemExit("--pp composes with dp only; set tp=sp=ep=1, "
                         "n_experts=0")
    if args.generate or args.accum_steps != 1:
        raise SystemExit("--pp does not support --generate/--accum-steps "
                         "(decode uses the non-pp path; 1F1B already "
                         "microbatches every step)")
    if args.remat:
        raise SystemExit("--remat is implicit under --pp: 1F1B saves only "
                         "stage inputs and recomputes stage forwards")
    if args.n_layers % args.pp:
        raise SystemExit(f"--n-layers {args.n_layers} not divisible by "
                         f"--pp {args.pp}")
    if len(jax.devices()) < args.pp:
        raise SystemExit(f"--pp {args.pp} needs {args.pp} devices, have "
                         f"{len(jax.devices())}")

    # dp over whatever devices remain: each dp row runs its own pipeline on
    # its batch shard, grads averaged (pipeline_1f1b's data_axis path).
    mesh = meshlib.make_mesh(dp=-1, pp=args.pp)
    dp_size = mesh.shape["dp"]
    m = args.pp_microbatches
    if args.batch % (dp_size * m):
        raise SystemExit(f"--batch {args.batch} not divisible by dp x "
                         f"--pp-microbatches = {dp_size} x {m}")
    per_stage = args.n_layers // args.pp
    bubble = (args.pp - 1) / (m + args.pp - 1)
    print(f"mesh: dp={dp_size} pp={args.pp} on {jax.default_backend()}; "
          f"{per_stage} blocks/stage, {m} microbatches/row, "
          f"bubble {bubble:.0%}")

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    model = tfm.Transformer(
        vocab_size=args.vocab_size, d_model=args.d_model,
        n_layers=args.n_layers, n_heads=args.n_heads, attn_impl="xla",
        compute_dtype=dtype)
    ids = jnp.asarray(synthetic_ids(args.batch, args.seq_len,
                                    args.vocab_size))
    full = model.init(jax.random.PRNGKey(0), ids)["params"]
    n_params = sum(x.size for x in jax.tree.leaves(full))
    print(f"model: {n_params/1e6:.2f}M params, 1F1B pipeline")

    block = tfm.Block(n_heads=args.n_heads,
                      d_head=args.d_model // args.n_heads,
                      d_ff=4 * args.d_model, attn_impl="xla",
                      compute_dtype=dtype)

    def stage_tree(i):
        return jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *(full[f"block_{i * per_stage + j}"] for j in range(per_stage)))

    stacked = pplib.stack_stages([stage_tree(i) for i in range(args.pp)])
    stacked = jax.device_put(stacked, pplib.stage_shardings(mesh, stacked))
    head = {"final_norm": full["final_norm"], "lm_head": full["lm_head"]}
    emb = full["embed"]

    def stage_fn(p, h):
        for j in range(per_stage):
            h = block.apply({"params": jax.tree.map(lambda a: a[j], p)}, h)
        return h

    import flax.linen as nn

    from tensorflowonspark_tpu.ops import xent

    def head_loss(hp, h, tgt_ids):
        final = tfm.RMSNorm().apply({"params": hp["final_norm"]}, h)
        tgt = tgt_ids[:, 1:]
        if args.vocab_chunk:
            # fused blockwise head: never materializes [mb, S, V] logits
            nll = xent.blockwise_cross_entropy(
                final[:, :-1].reshape(-1, args.d_model),
                hp["lm_head"]["kernel"], tgt.reshape(-1),
                chunk=args.vocab_chunk)
            return jnp.mean(nll)
        logits = nn.Dense(args.vocab_size, use_bias=False, dtype=dtype).apply(
            {"params": hp["lm_head"]}, final).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits[:, :-1])
        return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], axis=-1))

    optimizer = optax.adamw(args.lr)
    params = (stacked, head, emb)
    opt_state = optimizer.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def pp_step(params, opt_state, ids):
        stacked, head, emb = params
        x = emb["embedding"][ids].astype(dtype)
        loss, g_s, g_h, dx = pplib.pipeline_1f1b(
            stage_fn, stacked, x, head_loss, mesh=mesh, n_microbatches=m,
            targets=ids, head_params=head, with_input_grad=True)
        g_e = {"embedding": jax.grad(
            lambda e: jnp.sum(e[ids].astype(jnp.float32) * dx))(
                emb["embedding"])}
        updates, opt_state = optimizer.update((g_s, g_h, g_e), opt_state,
                                              params)
        return optax.apply_updates(params, updates), opt_state, loss

    params, opt_state, loss = pp_step(params, opt_state, ids)  # compile
    print(f"step 0: loss={float(loss):.4f}")

    def one_step():
        nonlocal params, opt_state
        params, opt_state, loss = pp_step(params, opt_state, ids)
        return loss

    t0 = time.perf_counter()
    if args.profile_dir:
        from tensorflowonspark_tpu import profiling

        loss = profiling.profile_steps(args.profile_dir, one_step,
                                       warmup=0, steps=args.steps)
    else:
        for _ in range(args.steps):
            loss = one_step()
    loss = float(loss)  # fetch = sync
    dt = time.perf_counter() - t0
    tokens = args.batch * args.seq_len * args.steps
    print(f"step {args.steps}: loss={loss:.4f} "
          f"({tokens / dt:,.0f} tokens/sec)")


if __name__ == "__main__":
    main()
