"""Criteo wide-and-deep through the ML pipeline — parity config 4
(BASELINE.json:10: the reference ran ``TFEstimator.fit`` →
``TFModel.transform`` over Spark DataFrames; ``examples/criteo/``).

End to end: rows → ``TPUEstimator.fit`` boots a real multi-process cluster,
streams partitions into each node's DataFeed, trains the wide-and-deep CTR
model sync-SPMD over each node's mesh, the chief exports a bundle →
``TPUModel.transform`` scores a dataset partition-by-partition (ordered,
exactly-count) from the cached bundle.

By default generates synthetic Criteo-shaped rows; pass --data-tsv pointing
at real Criteo TSV (label \t 13 ints \t 26 hex cats) to use it.

  JAX_PLATFORMS=cpu python criteo_wide_deep.py --num-executors 2 --epochs 2
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def train_fn(args, ctx):
    """Runs on every node: stream rows, SPMD train, chief exports bundle."""
    import jax
    import optax

    from tensorflowonspark_tpu.checkpoint import export_bundle
    from tensorflowonspark_tpu.models import wide_deep
    from tensorflowonspark_tpu.parallel import dp as dplib
    from tensorflowonspark_tpu.parallel import mesh as meshlib

    config = {"model": "wide_deep",
              "vocab_size": int(args.get("vocab_size", 100_003)),
              "embed_dim": int(args.get("embed_dim", 16)),
              "hidden": (256, 128, 64),
              "bf16": bool(args.get("bf16", True))}
    model = wide_deep.build_wide_deep(config)
    params = wide_deep.init_params(model, jax.random.PRNGKey(0))
    optimizer = optax.adagrad(float(args.get("lr", 0.01)))
    mesh = ctx.make_mesh(dp=-1)
    state = dplib.TrainState.create(dplib.replicate(params, mesh), optimizer)
    step_fn = dplib.make_train_step(wide_deep.make_loss_fn(model), optimizer)

    feed = ctx.get_data_feed(train_mode=True)
    batches = dplib.make_batch_iterator(
        feed, int(args.get("batch_size", 512)), wide_deep.batch_to_arrays,
        mesh=mesh, ctx=ctx, max_steps=args.get("steps"))
    step = loss = None
    for batch, _n in batches:
        state, metrics = step_fn(state, batch)
        step = int(jax.device_get(state.step))
        loss = float(metrics["loss"])
        if step % 50 == 0:
            print(f"node {ctx.executor_id} step {step}: loss={loss:.4f}")
    if ctx.executor_id == 0:
        export_bundle(args.export_dir, jax.device_get(state.params), config)
        print(f"chief exported bundle to {args.export_dir} "
              f"(final step {step}, loss {loss})")
    ctx.barrier("export")  # nobody exits before the bundle exists


def load_tsv(path: str):
    """Real Criteo TSV → row dicts matching wide_deep.batch_to_arrays."""
    rows = []
    with open(path) as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            label = float(parts[0])
            numeric = [float(v) if v else 0.0 for v in parts[1:14]]
            cats = [int(v, 16) if v else 0 for v in parts[14:40]]
            rows.append({"features": numeric + cats, "label": label})
    return rows


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--num-executors", type=int, default=2)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--rows", type=int, default=4096, help="synthetic row count")
    p.add_argument("--vocab-size", type=int, default=100_003)
    p.add_argument("--data-tsv", default="", help="real Criteo TSV path")
    p.add_argument("--export-dir", default="/tmp/criteo_bundle")
    p.add_argument("--log-dir", default="/tmp/criteo_logs")
    args = p.parse_args()

    from tensorflowonspark_tpu import pipeline
    from tensorflowonspark_tpu.cluster import InputMode
    from tensorflowonspark_tpu.data import PartitionedDataset
    from tensorflowonspark_tpu.models import wide_deep

    rows = (load_tsv(args.data_tsv) if args.data_tsv
            else wide_deep.synthetic_criteo(args.rows, seed=0))
    data = PartitionedDataset.from_iterable(rows, args.num_executors * 2)

    estimator = pipeline.TPUEstimator(
        train_fn,
        tf_args={"vocab_size": args.vocab_size, "lr": 0.01, "bf16": False},
        batch_size=args.batch_size,
        epochs=args.epochs,
        num_executors=args.num_executors,
        input_mode=InputMode.STREAMING,
        export_dir=args.export_dir,
        log_dir=args.log_dir,
    )
    model = estimator.fit(data)

    scored = model.transform(PartitionedDataset.from_iterable(rows[:256], 4))
    out = list(scored)
    pos = sum(1 for r in out if r["prediction"] > 0.5)
    print(f"scored {len(out)} rows; {pos} predicted positive; "
          f"sample: {out[0]['prediction']:.4f} (label {rows[0]['label']})")


if __name__ == "__main__":
    main()
