"""Distributed CIFAR-10 ResNet training, direct input mode — the TPU
counterpart of the reference's ``examples/cifar10`` family
(multi-GPU CNN training, InputMode.TENSORFLOW reading CIFAR files).

Each node reads its TFRecord shards (strided by executor id), trains a
CIFAR-size ResNet (bottleneck blocks, 3x3 stem) with the sync-SPMD
BatchNorm train step — cross-replica BN falls out of GSPMD sharding, where
the reference's multi-GPU tower setup averaged tower losses by hand.

Usage: python cifar10_train.py --prepare   # writes synthetic shards
       python cifar10_train.py --num-executors 2 --epochs 2
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:  # allow running straight from a checkout
    sys.path.insert(0, _REPO)

import numpy as np


def synthetic_cifar(n: int, seed: int = 0) -> list[tuple[np.ndarray, int]]:
    """Deterministic learnable synthetic CIFAR: class k brightens channel
    stripe k (hermetic — no dataset download in this environment)."""
    rng = np.random.RandomState(seed)
    samples = []
    for i in range(n):
        label = i % 10
        img = rng.rand(32, 32, 3).astype(np.float32) * 0.2
        img[label * 3 : label * 3 + 3, :, label % 3] += 1.0
        samples.append((img, label))
    return samples


def prepare_data(output_dir: str, samples: int = 2000, partitions: int = 8) -> None:
    """Write synthetic CIFAR TFRecord shards (uint8 image bytes — the same
    compact wire idiom real CIFAR/ImageNet TFRecords use)."""
    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu.data import PartitionedDataset

    rows = [
        {"image_raw": (img * 255).astype(np.uint8).tobytes(), "label": label}
        for img, label in synthetic_cifar(samples)
    ]
    dfutil.save_as_tfrecords(PartitionedDataset.from_iterable(rows, partitions),
                             output_dir)


def batch_to_arrays(items: list) -> dict:
    """uint8 HWC bytes -> f32 batch (normalization happens on device)."""
    images = np.stack([
        np.frombuffer(raw, np.uint8).reshape(32, 32, 3).astype(np.float32) / 255.0
        for raw, _ in items])
    labels = np.asarray([l for _, l in items], np.int32)
    return {"image": images, "label": labels}


def main_fun(args, ctx):
    import jax
    import optax

    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu.checkpoint import export_bundle
    from tensorflowonspark_tpu.feeding import IteratorFeed
    from tensorflowonspark_tpu.models import resnet
    from tensorflowonspark_tpu.parallel import dp as dplib
    from tensorflowonspark_tpu.parallel import mesh as meshlib

    model_config = {"model": "resnet_cifar", "num_classes": 10,
                    "depth_blocks": args.get("depth_blocks", 3),
                    "width": args.get("width", 16),
                    "bf16": bool(args.get("bf16", True))}
    model = resnet.build_resnet_cifar(model_config)
    variables = resnet.init_variables(model, jax.random.PRNGKey(0), image_size=32)
    optimizer = optax.sgd(args.get("lr", 0.1), momentum=0.9, nesterov=True)

    mesh = ctx.make_mesh(dp=-1)
    params = meshlib.shard_tree(mesh, variables["params"])
    batch_stats = meshlib.shard_tree(
        mesh, variables["batch_stats"],
        jax.tree.map(lambda _: meshlib.replicated(mesh), variables["batch_stats"]))
    state = dplib.BNTrainState.create(params, batch_stats, optimizer)
    step = dplib.make_bn_train_step(
        resnet.make_loss_fn(model, weight_decay=1e-4), optimizer)

    my_shards = dfutil.shard_files(args["data_dir"])[ctx.executor_id :: ctx.num_data_nodes]
    schema = dfutil.read_schema(args["data_dir"])

    def samples():
        for _epoch in range(args.get("epochs", 1)):
            for shard in my_shards:
                for row in dfutil.read_shard(shard, schema,
                                             binary_features={"image_raw"}):
                    yield (row["image_raw"], int(row["label"]))

    feed = IteratorFeed(samples())
    last = {}
    for batch, _n in dplib.make_batch_iterator(
        feed, args.get("batch_size", 128), batch_to_arrays, mesh, ctx
    ):
        state, last = step(state, batch)

    if ctx.executor_id == 0:
        print(f"final: loss={float(last['loss']):.4f} "
              f"acc={float(last['accuracy']):.3f} step={int(state.step)}")
        if args.get("export_dir"):
            export_bundle(args["export_dir"],
                          jax.device_get({"params": state.params,
                                          "batch_stats": state.batch_stats}),
                          model_config)


def main() -> None:
    import tensorflowonspark_tpu as tos

    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", default="/tmp/cifar10_tfr")
    p.add_argument("--export-dir", default="/tmp/cifar10_export")
    p.add_argument("--num-executors", type=int, default=2)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--depth-blocks", type=int, default=3,
                   help="n bottleneck blocks per stage (9n+2 layers)")
    p.add_argument("--prepare", action="store_true", help="write synthetic shards first")
    a = p.parse_args()

    if a.prepare:
        prepare_data(a.data_dir)
        print(f"shards written to {a.data_dir}")
        return
    args = {"data_dir": a.data_dir, "export_dir": a.export_dir,
            "epochs": a.epochs, "batch_size": a.batch_size,
            "depth_blocks": a.depth_blocks}
    cluster = tos.run(main_fun, args, num_executors=a.num_executors,
                      input_mode=tos.InputMode.DIRECT)
    cluster.shutdown(timeout=600)
    print(f"trained from {a.data_dir}; bundle in {a.export_dir}")


if __name__ == "__main__":
    main()
