"""Distributed MNIST, streaming input mode — parity config 1
(reference ``examples/mnist/spark/mnist_dist.py``: InputMode.SPARK,
BASELINE.json:7).  The driver streams partitions of (image, label) samples
into each node's DataFeed; nodes run a sync SPMD train step over their local
mesh, with control-plane ``all_done`` consensus replacing the reference's
tolerance for uneven async-PS partition exhaustion (SURVEY.md §7.3-1).

Run directly:  python mnist_dist.py --num-executors 2 --epochs 1
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:  # allow running straight from a checkout
    sys.path.insert(0, _REPO)

import jax
import optax


def _model_config(args) -> dict:
    return {"model": "mnist_cnn", "num_classes": 10, "bf16": bool(args.get("bf16")),
            "features": list(args.get("features", (32, 64))),
            "dense": args.get("dense", 256)}


def _evaluator_loop(args, ctx):
    """The evaluator role (reference: the ``evaluator`` job in the cluster
    template, ``TFCluster.py:~290-330``): sidecar node that periodically
    loads the newest checkpoint, scores a held-out set, and writes eval
    scalars.  Excluded from the data feed and from training collectives
    (``ctx.num_data_nodes``); exits once training is done (the chief drops a
    ``TRAINING_DONE`` marker after the final coordinated save) and the last
    checkpoint has been evaluated — or on a driver stop signal.
    """
    import numpy as np

    from tensorflowonspark_tpu.checkpoint import latest_step_dir, restore_checkpoint
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.models.mnist import synthetic_mnist
    from tensorflowonspark_tpu.summary import SummaryWriter
    from tensorflowonspark_tpu.utils.paths import resolve_uri

    model = mnist.build_mnist(_model_config(args))
    batch = mnist.batch_to_arrays(
        list(synthetic_mnist(args.get("eval_samples", 128),
                             seed=args.get("eval_seed", 1))))
    apply_fn = jax.jit(lambda p, x: model.apply({"params": p}, x))
    writer = (SummaryWriter(os.path.join(args["log_dir"], "eval"))
              if args.get("log_dir") else None)
    done_marker = os.path.join(resolve_uri(args["model_dir"]), "TRAINING_DONE")
    interval = float(args.get("eval_interval", 10.0))
    last_step, evals, fails = -1, [], 0
    try:
        while True:
            # read the marker BEFORE the checkpoint listing: a marker that
            # was already present when we saw the latest step means no newer
            # checkpoint can appear after this evaluation
            training_done = os.path.exists(done_marker)
            path = latest_step_dir(args["model_dir"])
            step_no = int(path.rsplit("_", 1)[1]) if path is not None else None
            if step_no is not None and step_no > last_step:
                try:
                    params = restore_checkpoint(path)["params"]
                except Exception:  # noqa: BLE001 - keep-K GC race: the
                    # chief may delete step_N while we read it; a newer
                    # step exists in that case — retry next poll.  NOT
                    # `continue` (that would skip the exit check and the
                    # interval wait below, busy-spinning forever on a
                    # persistently unreadable checkpoint); instead count
                    # consecutive failures so the exit path can give up on
                    # an unreadable FINAL checkpoint after a few polls.
                    params = None
                    fails += 1
                if params is not None:
                    fails = 0
                    logits = jax.device_get(apply_fn(params, batch["image"]))
                    labels = np.asarray(batch["label"])
                    acc = float((np.asarray(logits).argmax(-1) == labels).mean())
                    if writer is not None:
                        writer.add_scalar("eval/accuracy", acc, step_no)
                    evals.append({"step": step_no, "accuracy": acc})
                    ctx.update_meta({"evals": evals})
                    last_step = step_no
            # honor training_done only once the NEWEST checkpoint was scored
            # (or retried past its bound): a transient restore failure on the
            # final step must not skip the final evaluation.
            caught_up = step_no is None or last_step >= step_no or fails >= 3
            if (training_done and caught_up) or ctx.stop_requested.is_set():
                return
            ctx.stop_requested.wait(interval if fails == 0 else min(interval, 2.0))
    finally:
        if writer is not None:
            writer.close()


def main_fun(args, ctx):
    """map_fun executed on every node (reference signature: main_fun(args, ctx))."""
    from tensorflowonspark_tpu.checkpoint import CheckpointManager, chief_save, export_bundle
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.parallel.dp import TrainState, make_batch_iterator, make_train_step, replicate
    from tensorflowonspark_tpu.summary import SummaryWriter

    # A restart into the same model_dir must not leave last run's
    # TRAINING_DONE marker behind (the evaluator would exit immediately):
    # the chief clears it and EVERY node — evaluator included — waits on the
    # barrier before proceeding, so the evaluator can never see a stale one.
    if args.get("model_dir"):
        if ctx.executor_id == 0:
            import contextlib

            from tensorflowonspark_tpu.utils.paths import resolve_uri

            os.makedirs(resolve_uri(args["model_dir"]), exist_ok=True)
            with contextlib.suppress(FileNotFoundError):
                os.remove(os.path.join(resolve_uri(args["model_dir"]),
                                       "TRAINING_DONE"))
        ctx.barrier("marker-clear", timeout=120.0)

    if ctx.job_name == "evaluator":
        return _evaluator_loop(args, ctx)

    model_config = _model_config(args)
    model = mnist.build_mnist(model_config)
    params = mnist.init_params(model, jax.random.PRNGKey(args.get("seed", 0)))
    optimizer = optax.sgd(args.get("lr", 0.05), momentum=0.9)

    mesh = ctx.make_mesh(dp=-1)
    state = TrainState.create(params, optimizer)
    manager = CheckpointManager(args["model_dir"]) if args.get("model_dir") else None
    # Whole-job restart picks up the latest checkpoint — FULL train state, so
    # momentum and the step counter survive the restart (the reference's
    # recovery contract: fail-fast + restart from checkpoint, SURVEY.md §5.3).
    if manager is not None:
        restored = manager.restore_latest(state._asdict())
        if restored is not None:
            tree, _step_no = restored
            state = TrainState(**tree)
    state = replicate(state, mesh)
    step = make_train_step(mnist.make_loss_fn(model), optimizer)

    is_chief = ctx.executor_id == 0
    writer = None
    if is_chief and args.get("log_dir"):
        writer = SummaryWriter(os.path.join(args["log_dir"], "train"))

    feed = ctx.get_data_feed(train_mode=True)
    last_metrics = {}
    ckpt_every = int(args.get("checkpoint_every", 0) or 0)
    for batch, _n in make_batch_iterator(
        feed, args.get("batch_size", 64), mnist.batch_to_arrays, mesh, ctx,
        max_steps=args.get("steps"),
    ):
        state, metrics = step(state, batch)
        step_no = int(state.step)
        if writer and step_no % args.get("log_every", 10) == 0:
            writer.add_scalars({k: float(v) for k, v in metrics.items()}, step_no)
        # Periodic saves are chief-local and async — no barrier: under
        # STREAMING feeds nodes step at different rates, so a mid-loop
        # collective would deadlock.  The coordinated chief_save below runs
        # after the all_done consensus, where every node is aligned.
        if manager is not None and is_chief and ckpt_every and step_no % ckpt_every == 0:
            manager.save(step_no, jax.device_get(state)._asdict())
        last_metrics = metrics

    if manager is not None:
        chief_save(ctx, manager, int(state.step), jax.device_get(state)._asdict())
        if is_chief:
            # committed AFTER the final save: the evaluator exits once it
            # has both seen this marker and scored the newest checkpoint
            from tensorflowonspark_tpu.utils.paths import resolve_uri

            open(os.path.join(resolve_uri(args["model_dir"]),
                              "TRAINING_DONE"), "w").close()
    if is_chief:
        if args.get("export_dir"):
            export_bundle(args["export_dir"], state.params, model_config)
        if writer:
            for k, v in last_metrics.items():
                writer.add_scalar(f"final/{k}", float(v), int(state.step))
            writer.close()


def inference_fun(args, ctx):
    """Streaming inference map_fun (parity config 5's shape): items in,
    predictions out — ordered, exactly-count (SURVEY.md §3.3)."""
    import numpy as np

    from tensorflowonspark_tpu.checkpoint import load_bundle_cached
    from tensorflowonspark_tpu.models import mnist, registry

    params, _config, apply_fn = load_bundle_cached(args["export_dir"], registry.build_apply)
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        items = feed.next_batch(args.get("batch_size", 64))
        if not items:
            continue
        batch = mnist.batch_to_arrays([(i, 0) if not isinstance(i, tuple) else i for i in items])
        logits = apply_fn(params, batch["image"])
        preds = np.asarray(jax.device_get(logits)).argmax(-1)
        feed.batch_results([int(p) for p in preds[: len(items)]])


def main() -> None:
    import tensorflowonspark_tpu as tos
    from tensorflowonspark_tpu.models.mnist import synthetic_mnist

    p = argparse.ArgumentParser()
    p.add_argument("--num-executors", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--samples", type=int, default=2000)
    p.add_argument("--partitions", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--model-dir", default="/tmp/mnist_model")
    p.add_argument("--export-dir", default="/tmp/mnist_export")
    p.add_argument("--log-dir", default="/tmp/mnist_logs")
    p.add_argument("--tensorboard", action="store_true")
    p.add_argument("--eval", action="store_true",
                   help="add an evaluator node that periodically scores the "
                        "latest checkpoint (one extra executor)")
    p.add_argument("--eval-interval", type=float, default=10.0)
    p.add_argument("--checkpoint-every", type=int, default=50)
    a = p.parse_args()

    args = {
        "batch_size": a.batch_size, "lr": a.lr, "model_dir": a.model_dir,
        "export_dir": a.export_dir, "log_dir": a.log_dir,
        "eval_interval": a.eval_interval, "checkpoint_every": a.checkpoint_every,
    }
    data = tos.PartitionedDataset.from_iterable(synthetic_mnist(a.samples), a.partitions)
    cluster = tos.run(
        main_fun, args,
        num_executors=a.num_executors + (1 if a.eval else 0),
        eval_node=a.eval,
        input_mode=tos.InputMode.STREAMING, tensorboard=a.tensorboard,
        log_dir=a.log_dir,
    )
    cluster.train(data, num_epochs=a.epochs)
    cluster.shutdown()
    print(f"training done; model in {a.model_dir}, bundle in {a.export_dir}")


if __name__ == "__main__":
    main()
