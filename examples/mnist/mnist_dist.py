"""Distributed MNIST, streaming input mode — parity config 1
(reference ``examples/mnist/spark/mnist_dist.py``: InputMode.SPARK,
BASELINE.json:7).  The driver streams partitions of (image, label) samples
into each node's DataFeed; nodes run a sync SPMD train step over their local
mesh, with control-plane ``all_done`` consensus replacing the reference's
tolerance for uneven async-PS partition exhaustion (SURVEY.md §7.3-1).

Run directly:  python mnist_dist.py --num-executors 2 --epochs 1
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:  # allow running straight from a checkout
    sys.path.insert(0, _REPO)

import jax
import optax


def main_fun(args, ctx):
    """map_fun executed on every node (reference signature: main_fun(args, ctx))."""
    from tensorflowonspark_tpu.checkpoint import CheckpointManager, chief_save, export_bundle
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.parallel.dp import TrainState, make_batch_iterator, make_train_step, replicate
    from tensorflowonspark_tpu.summary import SummaryWriter

    model_config = {"model": "mnist_cnn", "num_classes": 10, "bf16": bool(args.get("bf16")),
                    "features": list(args.get("features", (32, 64))),
                    "dense": args.get("dense", 256)}
    model = mnist.build_mnist(model_config)
    params = mnist.init_params(model, jax.random.PRNGKey(args.get("seed", 0)))
    optimizer = optax.sgd(args.get("lr", 0.05), momentum=0.9)

    mesh = ctx.make_mesh(dp=-1)
    state = TrainState.create(params, optimizer)
    manager = CheckpointManager(args["model_dir"]) if args.get("model_dir") else None
    # Whole-job restart picks up the latest checkpoint — FULL train state, so
    # momentum and the step counter survive the restart (the reference's
    # recovery contract: fail-fast + restart from checkpoint, SURVEY.md §5.3).
    if manager is not None:
        restored = manager.restore_latest(state._asdict())
        if restored is not None:
            tree, _step_no = restored
            state = TrainState(**tree)
    state = replicate(state, mesh)
    step = make_train_step(mnist.make_loss_fn(model), optimizer)

    is_chief = ctx.executor_id == 0
    writer = None
    if is_chief and args.get("log_dir"):
        writer = SummaryWriter(os.path.join(args["log_dir"], "train"))

    feed = ctx.get_data_feed(train_mode=True)
    last_metrics = {}
    ckpt_every = int(args.get("checkpoint_every", 0) or 0)
    for batch, _n in make_batch_iterator(
        feed, args.get("batch_size", 64), mnist.batch_to_arrays, mesh, ctx,
        max_steps=args.get("steps"),
    ):
        state, metrics = step(state, batch)
        step_no = int(state.step)
        if writer and step_no % args.get("log_every", 10) == 0:
            writer.add_scalars({k: float(v) for k, v in metrics.items()}, step_no)
        # Periodic saves are chief-local and async — no barrier: under
        # STREAMING feeds nodes step at different rates, so a mid-loop
        # collective would deadlock.  The coordinated chief_save below runs
        # after the all_done consensus, where every node is aligned.
        if manager is not None and is_chief and ckpt_every and step_no % ckpt_every == 0:
            manager.save(step_no, jax.device_get(state)._asdict())
        last_metrics = metrics

    if manager is not None:
        chief_save(ctx, manager, int(state.step), jax.device_get(state)._asdict())
    if is_chief:
        if args.get("export_dir"):
            export_bundle(args["export_dir"], state.params, model_config)
        if writer:
            for k, v in last_metrics.items():
                writer.add_scalar(f"final/{k}", float(v), int(state.step))
            writer.close()


def inference_fun(args, ctx):
    """Streaming inference map_fun (parity config 5's shape): items in,
    predictions out — ordered, exactly-count (SURVEY.md §3.3)."""
    import numpy as np

    from tensorflowonspark_tpu.checkpoint import load_bundle_cached
    from tensorflowonspark_tpu.models import mnist, registry

    params, _config, apply_fn = load_bundle_cached(args["export_dir"], registry.build_apply)
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        items = feed.next_batch(args.get("batch_size", 64))
        if not items:
            continue
        batch = mnist.batch_to_arrays([(i, 0) if not isinstance(i, tuple) else i for i in items])
        logits = apply_fn(params, batch["image"])
        preds = np.asarray(jax.device_get(logits)).argmax(-1)
        feed.batch_results([int(p) for p in preds[: len(items)]])


def main() -> None:
    import tensorflowonspark_tpu as tos
    from tensorflowonspark_tpu.models.mnist import synthetic_mnist

    p = argparse.ArgumentParser()
    p.add_argument("--num-executors", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--samples", type=int, default=2000)
    p.add_argument("--partitions", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--model-dir", default="/tmp/mnist_model")
    p.add_argument("--export-dir", default="/tmp/mnist_export")
    p.add_argument("--log-dir", default="/tmp/mnist_logs")
    p.add_argument("--tensorboard", action="store_true")
    a = p.parse_args()

    args = {
        "batch_size": a.batch_size, "lr": a.lr, "model_dir": a.model_dir,
        "export_dir": a.export_dir, "log_dir": a.log_dir,
    }
    data = tos.PartitionedDataset.from_iterable(synthetic_mnist(a.samples), a.partitions)
    cluster = tos.run(
        main_fun, args, num_executors=a.num_executors,
        input_mode=tos.InputMode.STREAMING, tensorboard=a.tensorboard,
        log_dir=a.log_dir,
    )
    cluster.train(data, num_epochs=a.epochs)
    cluster.shutdown()
    print(f"training done; model in {a.model_dir}, bundle in {a.export_dir}")


if __name__ == "__main__":
    main()
