"""Distributed MNIST, direct (framework-reads-files) input mode — parity
config 2 (reference ``examples/mnist/tf/mnist_dist.py``: InputMode.TENSORFLOW
reading TFRecords from HopsFS, BASELINE.json:8).

Each node reads the TFRecord shards assigned to it (strided by executor id —
the same shard-ownership scheme ``tf.data`` auto-sharding gave the
reference), trains the shared sync-SPMD step, and agrees on a global stop
via control-plane consensus.

Usage: first write shards with ``prepare_data()``, then run the cluster.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:  # allow running straight from a checkout
    sys.path.insert(0, _REPO)


def prepare_data(output_dir: str, samples: int = 2000, partitions: int = 8) -> None:
    """Write synthetic MNIST TFRecord shards (stand-in for the reference's
    mnist_data_setup.py, which downloaded and converted the real set)."""
    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu.data import PartitionedDataset
    from tensorflowonspark_tpu.models.mnist import synthetic_mnist

    rows = [{"image": img.ravel().tolist(), "label": label} for img, label in synthetic_mnist(samples)]
    dfutil.save_as_tfrecords(PartitionedDataset.from_iterable(rows, partitions), output_dir)


def main_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu.checkpoint import export_bundle
    from tensorflowonspark_tpu.feeding import IteratorFeed
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.parallel.dp import (
        TrainState, make_batch_iterator, make_train_step, replicate,
    )

    model_config = {"model": "mnist_cnn", "num_classes": 10,
                    "features": list(args.get("features", (32, 64))),
                    "dense": args.get("dense", 256)}
    model = mnist.build_mnist(model_config)
    params = mnist.init_params(model, jax.random.PRNGKey(args.get("seed", 0)))
    optimizer = optax.sgd(args.get("lr", 0.05), momentum=0.9)
    mesh = ctx.make_mesh(dp=-1)
    state = replicate(TrainState.create(params, optimizer), mesh)
    step = make_train_step(mnist.make_loss_fn(model), optimizer)

    # Shard ownership: files strided over data nodes by executor id (the
    # tf.data auto-shard analogue the reference relied on).
    my_shards = dfutil.shard_files(args["data_dir"])[ctx.executor_id :: ctx.num_data_nodes]
    schema = dfutil.read_schema(args["data_dir"])
    readers = int(args.get("readers", 1) or 1)

    def shard_reader(shard):
        def it():
            for row in dfutil.read_shard(shard, schema):
                yield (np.asarray(row["image"], np.float32).reshape(28, 28, 1),
                       int(row["label"]))
        return it

    def samples():
        # `readers` Param: background reader threads overlap shard IO/decode
        # with the train step (tf.data parallel-interleave analogue).
        from tensorflowonspark_tpu.data import interleave

        for _epoch in range(args.get("epochs", 1)):
            yield from interleave([shard_reader(s) for s in my_shards], readers)

    feed = IteratorFeed(samples())
    for batch, _n in make_batch_iterator(
        feed, args.get("batch_size", 64), mnist.batch_to_arrays, mesh, ctx,
        max_steps=args.get("steps"),
    ):
        state, metrics = step(state, batch)

    if ctx.executor_id == 0 and args.get("export_dir"):
        export_bundle(args["export_dir"], state.params, model_config)


def main() -> None:
    import tensorflowonspark_tpu as tos

    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", default="/tmp/mnist_tfr")
    p.add_argument("--export-dir", default="/tmp/mnist_export")
    p.add_argument("--num-executors", type=int, default=2)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--prepare", action="store_true", help="write synthetic shards first")
    a = p.parse_args()

    if a.prepare:
        prepare_data(a.data_dir)
    args = {"data_dir": a.data_dir, "export_dir": a.export_dir,
            "epochs": a.epochs, "batch_size": a.batch_size}
    cluster = tos.run(main_fun, args, num_executors=a.num_executors,
                      input_mode=tos.InputMode.DIRECT)
    cluster.shutdown(timeout=600)
    print(f"trained from {a.data_dir}; bundle in {a.export_dir}")


if __name__ == "__main__":
    main()
