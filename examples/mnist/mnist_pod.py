"""Multi-host (pod) MNIST: streaming feed + one global SPMD train step.

The reference's defining deployment — Spark-streamed partitions feeding a
multi-worker synchronized TF cluster (``InputMode.SPARK`` +
``TF_CONFIG``/MWMS wiring, ``TFSparkNode.py:~260-300``/``:~430-510``) — as
one ``jax.distributed`` job: ``TPUPodLauncher`` places one node process per
host, the driver streams DISJOINT partitions to each node's feed, and
``mesh.shard_batch`` assembles the per-host batches into ONE global batch
(``jax.make_array_from_process_local_data``) consumed by a single jitted
train step spanning every chip on every host.  Checkpoints are collective
(every data node serializes its addressable shards; see
``checkpoint.chief_save``).

Local demo (2 simulated "hosts" on this machine, CPU devices):

    python mnist_pod.py --hosts localhost,localhost --transport local \
        --simulate-chips 2

Real pod: ``--hosts tpu-vm-0,tpu-vm-1`` (passwordless ssh; the package must
be importable on each host) and drop ``--simulate-chips``.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main_fun(args, ctx):
    import jax
    import optax

    from tensorflowonspark_tpu.checkpoint import CheckpointManager, chief_save
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.parallel.dp import (
        TrainState, make_batch_iterator, make_train_step, replicate,
    )

    model_config = {"model": "mnist_cnn", "num_classes": 10,
                    "features": list(args.get("features", (32, 64))),
                    "dense": args.get("dense", 256)}
    model = mnist.build_mnist(model_config)
    optimizer = optax.sgd(args.get("lr", 0.05), momentum=0.9)

    # The mesh spans EVERY host's devices (jax.distributed was bootstrapped
    # by the launcher); state is created host-side then placed globally.
    mesh = ctx.make_mesh(dp=-1)
    state = TrainState.create(
        mnist.init_params(model, jax.random.PRNGKey(args.get("seed", 0))),
        optimizer)
    manager = CheckpointManager(args["model_dir"]) if args.get("model_dir") else None
    if manager is not None:
        restored = manager.restore_latest(state._asdict())
        if restored is not None:
            state = TrainState(**restored[0])
    state = replicate(state, mesh)
    step = make_train_step(mnist.make_loss_fn(model), optimizer)

    feed = ctx.get_data_feed(train_mode=True)
    for batch, _n in make_batch_iterator(
            feed, args.get("batch_size", 64), mnist.batch_to_arrays, mesh, ctx,
            max_steps=args.get("steps")):
        state, metrics = step(state, batch)
        if ctx.executor_id == 0 and int(state.step) % args.get("log_every", 10) == 0:
            print(f"[global step {int(state.step)}] "
                  f"loss={float(metrics['loss']):.4f} "
                  f"acc={float(metrics['accuracy']):.3f}", flush=True)
    if manager is not None:
        # collective save: every data node serializes its addressable shards
        chief_save(ctx, manager, int(jax.device_get(state.step)), state._asdict())


def main() -> None:
    import tensorflowonspark_tpu as tos
    from tensorflowonspark_tpu.launcher import TPUPodLauncher
    from tensorflowonspark_tpu.models.mnist import synthetic_mnist

    p = argparse.ArgumentParser()
    p.add_argument("--hosts", required=True,
                   help="comma-separated pod host names (one node per host)")
    p.add_argument("--transport", default="ssh", choices=["ssh", "local"])
    p.add_argument("--simulate-chips", type=int, default=None,
                   help="use N virtual CPU devices per host (local demo)")
    p.add_argument("--batch-size", type=int, default=64,
                   help="PER-HOST batch; the global batch is hosts x this")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--samples", type=int, default=2000)
    p.add_argument("--partitions", type=int, default=8)
    p.add_argument("--model-dir", default="/tmp/mnist_pod_model")
    p.add_argument("--log-dir", default="/tmp/mnist_pod_logs")
    a = p.parse_args()

    hosts = a.hosts.split(",")
    pod = TPUPodLauncher(
        hosts=hosts, transport=a.transport,
        platform="cpu" if a.simulate_chips else "tpu",
        simulate_chips=a.simulate_chips)
    cluster = tos.run(
        main_fun,
        {"batch_size": a.batch_size, "model_dir": a.model_dir},
        num_executors=len(hosts),
        input_mode=tos.InputMode.STREAMING,
        launcher=pod,                      # forces jax_distributed
        log_dir=a.log_dir,
    )
    data = tos.PartitionedDataset.from_iterable(
        synthetic_mnist(a.samples), a.partitions)
    cluster.train(data, num_epochs=a.epochs)
    cluster.shutdown()
    print(f"pod training done; checkpoints in {a.model_dir}")


if __name__ == "__main__":
    main()
