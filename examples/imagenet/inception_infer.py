"""Inception-v3 streaming inference — parity config 5
(reference ``examples/imagenet/inception`` batch-inference via
``TFCluster.inference`` RDD→GPU; BASELINE.json:11).

Images stream from the driver through node feeds onto the TPU in static
padded batches; results come back ordered, exactly one per image.

Run:  python inception_infer.py --num-executors 1 --images 64 --image-size 299
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import tensorflowonspark_tpu as tos
from tensorflowonspark_tpu.inference import bundle_inference_loop


def export_random_bundle(export_dir: str, image_size: int) -> None:
    """Export a randomly-initialized Inception-v3 bundle (stand-in for a
    trained checkpoint; the reference example downloaded a pretrained one)."""
    import jax

    from tensorflowonspark_tpu.checkpoint import export_bundle
    from tensorflowonspark_tpu.models import inception

    config = {"model": "inception_v3", "num_classes": 1001, "bf16": True}
    model = inception.build_inception_v3(config)
    variables = inception.init_variables(model, jax.random.PRNGKey(0), image_size)
    export_bundle(export_dir, jax.device_get(dict(variables)), config)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--num-executors", type=int, default=1)
    p.add_argument("--images", type=int, default=64)
    p.add_argument("--image-size", type=int, default=299)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--partitions", type=int, default=4)
    p.add_argument("--export-dir", default="")
    p.add_argument("--log-dir", default=os.path.join(tempfile.gettempdir(), "inception_logs"))
    args = p.parse_args()

    export_dir = args.export_dir or os.path.join(tempfile.gettempdir(), "inception_bundle")
    if not os.path.exists(os.path.join(export_dir, "bundle.json")):
        print("exporting random-init bundle to", export_dir)
        export_random_bundle(export_dir, args.image_size)

    from tensorflowonspark_tpu.models import inception

    images = inception.synthetic_images(args.images, args.image_size)
    data = tos.PartitionedDataset.from_iterable(images, args.partitions)

    cluster = tos.run(
        bundle_inference_loop,
        {"export_dir": export_dir, "batch_size": args.batch_size, "postprocess": "argmax"},
        num_executors=args.num_executors,
        input_mode=tos.InputMode.STREAMING,
        log_dir=args.log_dir,
    )
    try:
        preds = cluster.inference(data)
    finally:
        cluster.shutdown()
    assert len(preds) == args.images, (len(preds), args.images)
    print(f"scored {len(preds)} images; first 10 class ids: {preds[:10]}")


if __name__ == "__main__":
    main()
