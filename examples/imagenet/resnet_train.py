"""ResNet-50 / ImageNet data-parallel training — parity config 3
(BASELINE.json:9: the reference ran TF-Keras ResNet-50 under
``MultiWorkerMirroredStrategy``, NCCL all-reduce, one executor per GPU).

TPU-native: one jitted SPMD train step over a ``(dp, fsdp)`` mesh; gradient
all-reduce and cross-replica BatchNorm fall out of GSPMD sharding.  Uses
synthetic ImageNet-shaped data by default (the benchmark configuration —
bench.py measures the same step); point --tfrecord-dir at real ImageNet
TFRecords to train on data read through the framework's TFRecord bridge.

  python resnet_train.py --steps 50 --batch 256
"""

from __future__ import annotations

import argparse
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--model-dir", default="")
    p.add_argument("--tfrecord-dir", default="",
                   help="directory of ImageNet TFRecords (else synthetic)")
    p.add_argument("--profile-dir", default="")
    args = p.parse_args()

    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import profiling
    from tensorflowonspark_tpu.checkpoint import CheckpointManager
    from tensorflowonspark_tpu.models import resnet
    from tensorflowonspark_tpu.parallel import dp as dplib
    from tensorflowonspark_tpu.parallel import mesh as meshlib

    mesh = meshlib.make_mesh(dp=-1, fsdp=args.fsdp)
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))} "
          f"on {jax.default_backend()}")

    model = resnet.build_resnet50({"num_classes": args.num_classes, "bf16": True})
    variables = resnet.init_variables(model, jax.random.PRNGKey(0),
                                      args.image_size)
    optimizer = optax.sgd(args.lr, momentum=0.9, nesterov=True)

    params = meshlib.shard_tree(mesh, variables["params"])
    batch_stats = meshlib.shard_tree(
        mesh, variables["batch_stats"],
        jax.tree.map(lambda _: meshlib.replicated(mesh), variables["batch_stats"]))
    state = dplib.BNTrainState.create(params, batch_stats, optimizer)

    ckpt = CheckpointManager(args.model_dir) if args.model_dir else None
    if ckpt is not None:
        # Full train state: a restart resumes with momentum and the true
        # step counter, not just weights (SURVEY.md §5.4).
        restored = ckpt.restore_latest(state._asdict())
        if restored is not None:
            tree, step_no = restored
            # Restore hands back host arrays; re-place every leaf under the
            # sharding the live state already has (fsdp params must go back
            # sharded, not materialize full-size on every device).
            placed = jax.tree.map(
                lambda x, live: jax.device_put(np.asarray(x), live.sharding),
                tree, state._asdict())
            state = dplib.BNTrainState(**placed)
            print(f"restored checkpoint at step {step_no}")

    step_fn = dplib.make_bn_train_step(
        resnet.make_loss_fn(model, weight_decay=1e-4), optimizer)

    if args.tfrecord_dir:
        # Rows with 'image' (float list, H*W*3) and 'label' (int) features,
        # as written by dfutil.save_as_tfrecords — the reference's TFRecord
        # path (parity config 2 uses the same bridge for MNIST).
        from tensorflowonspark_tpu import dfutil

        dataset, _ = dfutil.load_tfrecords(args.tfrecord_dir)
        shape = (args.image_size, args.image_size, 3)

        def batch_stream():
            rows = []
            while True:  # cycle the dataset forever
                for row in dataset:
                    rows.append(row)
                    if len(rows) == args.batch:
                        yield {
                            "image": np.stack([
                                np.asarray(r["image"], np.float32)
                                .reshape(shape) for r in rows]),
                            "label": np.asarray(
                                [r["label"] for r in rows], np.int32),
                        }
                        rows = []

        batches = batch_stream()
    else:
        rng = np.random.RandomState(0)
        fixed = {
            "image": rng.rand(args.batch, args.image_size, args.image_size, 3)
                        .astype(np.float32),
            "label": (np.arange(args.batch) % args.num_classes).astype(np.int32),
        }
        batches = iter(lambda: fixed, None)

    with mesh:
        it = iter(batches)

        def one_step():
            nonlocal state
            batch = meshlib.shard_batch(mesh, next(it))
            state, m = step_fn(state, batch)
            return m

        metrics = one_step()  # compile + warmup: outside the timed window
        print(f"step 0: loss={float(metrics['loss']):.4f}")
        t0 = time.perf_counter()
        if args.profile_dir:
            metrics = profiling.profile_steps(args.profile_dir, one_step,
                                              warmup=0, steps=args.steps)
        else:
            for _ in range(args.steps):
                metrics = one_step()
        loss = float(metrics["loss"])
        dt = time.perf_counter() - t0
        imgs = args.batch * args.steps / dt
        print(f"step {args.steps}: loss={loss:.4f} "
              f"({imgs:,.0f} images/sec, {imgs / mesh.size:,.0f}/chip)")
        if ckpt is not None:
            ckpt.save(int(jax.device_get(state.step)),
                      jax.device_get(state)._asdict())
            ckpt.wait()
            print("checkpoint saved")


if __name__ == "__main__":
    main()
