"""Sharded-embedding bench: synthetic-Criteo train rows/s + gateway qps.

The number this bench exists to produce (ISSUE 19 / BENCH_r19): the
**sparse-vs-dense exchanged-bytes ratio** of the embedding tier.  A
wide-and-deep table at paper scale (26 slots x ~100k hashed vocab x
(16+1) fused float32 columns) is ~177 MB; replicating it and averaging
its dense gradient every step costs each node a ``2(W-1)/W x table``
all-reduce — ~177 MB/step/node at W=2 — while the sharded tier exchanges
only the rows a step actually touches (unique-id CSR frames: requests,
gathered rows, scattered gradient rows), metered on the wire by
``collective.tx_bytes``.  Same model, same data, three-orders-of-magnitude
fewer bytes: that ratio is the algorithmic headline; rows/s (train) and
qps (gateway serve over resident shards) are the throughput context on a
single box.

Phases, one run:

- **train** — a real W=2 cluster (``SubprocessLauncher`` node processes,
  collective wire on each node's data port) runs the sharded
  wide-and-deep loop: fused-table lookup (two sparse all-to-alls), jitted
  dense grad step (ring all-reduce), sparse reduce-scatter of gradient
  rows.  Per node: step wall, measured tx bytes, table exchange stats.
- **serve** — the chief's sharded export (dense bundle + per-node shard
  files) serves through a fresh 2-replica cluster: shards resident on the
  replicas, the gateway's router fanning unique-id lookups over the
  dedicated embed queue pair, then one wrapped scoring round.  Closed-loop
  client threads measure sustained qps.

Usage::

    python bench_embedding.py                    # full run, markdown + JSON
    python bench_embedding.py --smoke            # tiny config (CI smoke)
    python bench_embedding.py --json BENCH_r19.json
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import threading
import time

import numpy as np


def _train_node(args, ctx):
    """Node body: the sharded wide-and-deep sync-training loop, timed.

    Publishes per-node wall time, the table's exchange stats, and the
    MEASURED collective tx bytes (CSR frames + dense grad ring, everything
    that rode the wire) via ``update_meta``.
    """
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import telemetry
    from tensorflowonspark_tpu.checkpoint import export_bundle
    from tensorflowonspark_tpu.embedding import (
        EmbeddingShard,
        ShardedTable,
        ShardPlan,
    )
    from tensorflowonspark_tpu.embedding.serve import (
        export_sharded_shard,
        sharded_config_block,
    )
    from tensorflowonspark_tpu.models import wide_deep

    config = dict(args["model_config"])
    steps = int(args["steps"])
    bsz = int(args["batch_size"])
    lr = 0.125
    group = ctx.collective_group(name="bench_embed")
    group.form()
    dim = int(config["embed_dim"]) + 1
    plan = ShardPlan.even("wide_deep", wide_deep.table_total_rows(config),
                          dim, group.world)
    shard = EmbeddingShard.create(plan, group.rank, seed=11,
                                  zero_cols=(dim - 1,))
    table = ShardedTable(shard, group)
    model = wide_deep.build_wide_deep_dense(config)
    params = wide_deep.init_dense_params(model, jax.random.PRNGKey(0))
    grad_fn = wide_deep.make_sharded_grad_fn(model)
    optimizer = optax.sgd(lr)
    opt_state = optimizer.init(params)
    dense_reduce = group.grad_fn()
    vocab = int(config["vocab_size"])

    def one_step(step):
        rows_src = wide_deep.synthetic_criteo(
            bsz, seed=group.rank * 10007 + step)
        batch = wide_deep.batch_to_arrays(rows_src)
        ids = wide_deep.flat_categorical_ids(batch["features"], vocab)
        rows = table.lookup(ids)
        nonlocal params, opt_state
        (_loss, _aux), (dg, rg) = grad_fn(params, rows, batch)
        dg = dense_reduce(dg)
        updates, opt_state = optimizer.update(dg, opt_state, params)
        params = optax.apply_updates(params, updates)
        table.apply_gradients(ids, np.asarray(jax.device_get(rg)), lr=lr,
                              scale=1.0 / group.world)

    one_step(0)  # warmup: jit compile + first exchanges, untimed
    group.barrier()
    tx0 = telemetry.counter("collective.tx_bytes").value()
    t0 = time.monotonic()
    for step in range(1, steps + 1):
        one_step(step)
    group.barrier()
    wall = time.monotonic() - t0
    tx = telemetry.counter("collective.tx_bytes").value() - tx0
    if args.get("export_dir"):
        export_sharded_shard(args["export_dir"], plan, group.rank,
                             shard.rows, steps)
        group.barrier()
        if group.rank == 0:
            export_bundle(args["export_dir"], jax.device_get(params),
                          {**config, "sharded_embedding":
                           sharded_config_block(plan, steps)})
        ctx.barrier("export")
    ctx.update_meta({"bench": {
        "rank": group.rank, "world": group.world, "wall_secs": wall,
        "tx_bytes": int(tx), "stats": dict(table.stats),
        "table_rows": plan.total_rows, "dim": dim,
    }})
    group.close()


def bench_train(model_config: dict, steps: int, batch_size: int,
                world: int = 2, export_dir: str | None = None,
                log_dir: str | None = None) -> dict:
    """Run the W-node sharded training phase; returns the train metrics
    plus the sparse-vs-dense exchanged-bytes comparison."""
    from tensorflowonspark_tpu import cluster as tcluster
    from tensorflowonspark_tpu.launcher import SubprocessLauncher

    cluster = tcluster.run(
        _train_node,
        {"model_config": model_config, "steps": steps,
         "batch_size": batch_size, "export_dir": export_dir},
        num_executors=world, input_mode=tcluster.InputMode.STREAMING,
        launcher=SubprocessLauncher(), log_dir=log_dir or "",
        reservation_timeout=120.0)
    cluster.shutdown(timeout=600.0)
    metas = [m.get("bench") for m in cluster.coordinator.cluster_info()]
    assert all(m is not None for m in metas), metas
    wall = max(m["wall_secs"] for m in metas)
    total_rows = metas[0]["table_rows"]
    dim = metas[0]["dim"]
    table_bytes = total_rows * dim * 4
    # the dense alternative: replicate the table, ring-all-reduce its full
    # gradient every step — 2(W-1)/W x table bytes per node per step
    dense_alt = int(steps * 2 * (world - 1) / world * table_bytes)
    sparse_measured = max(m["tx_bytes"] for m in metas)
    return {
        "world": world, "steps": steps, "batch_size": batch_size,
        "vocab_size": model_config["vocab_size"],
        "embed_dim": model_config["embed_dim"],
        "table_rows": total_rows, "table_mb": round(table_bytes / 2**20, 1),
        "train_rows_per_s": round(steps * batch_size * world / wall, 1),
        "step_ms": round(1e3 * wall / steps, 1),
        "sparse_tx_bytes_per_node": sparse_measured,
        "dense_alt_bytes_per_node": dense_alt,
        "dense_vs_sparse_x": round(dense_alt / max(1, sparse_measured), 1),
        "stats": metas[0]["stats"],
    }


def bench_serve(export_dir: str, requests: int, rows_per_request: int,
                clients: int = 4, log_dir: str | None = None) -> dict:
    """Serve the sharded export through the gateway; closed-loop client
    threads measure sustained qps + row throughput."""
    from tensorflowonspark_tpu import cluster as tcluster
    from tensorflowonspark_tpu import serving
    from tensorflowonspark_tpu.launcher import SubprocessLauncher
    from tensorflowonspark_tpu.models import wide_deep

    cluster = tcluster.run(
        serving.serving_loop, {"export_dir": export_dir, "max_batch": 16},
        num_executors=2, input_mode=tcluster.InputMode.STREAMING,
        queues=("input", "output", "error", "embed", "embed_out"),
        launcher=SubprocessLauncher(), log_dir=log_dir or "",
        heartbeat_interval=0.5, reservation_timeout=120.0)
    try:
        gw = cluster.serve(export_dir, max_batch=16, max_delay_ms=2.0,
                           reload_poll_secs=0)
        pool = [np.asarray(r["features"], np.float32)
                for r in wide_deep.synthetic_criteo(64, seed=77)]
        gw.predict(pool[:rows_per_request], timeout=120.0)  # warmup
        done = [0] * clients
        errors = []

        def client(ci):
            for i in range(requests // clients):
                rows = [pool[(ci + i + k) % len(pool)]
                        for k in range(rows_per_request)]
                try:
                    out = gw.predict(rows, timeout=120.0)
                    assert len(out) == rows_per_request
                    done[ci] += 1
                except Exception as e:  # noqa: BLE001 - recorded, re-raised
                    errors.append(repr(e))
                    return
        t0 = time.monotonic()
        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        assert not errors, errors
        n = sum(done)
        return {"serve_qps": round(n / wall, 1),
                "serve_rows_per_s": round(n * rows_per_request / wall, 1),
                "requests": n, "rows_per_request": rows_per_request,
                "clients": clients}
    finally:
        cluster.shutdown(timeout=300.0)


def bench(smoke: bool = False, world: int = 2) -> dict:
    """Full bench: train phase + serve phase over the train export."""
    if smoke:
        model_config = {"model": "wide_deep_dense", "vocab_size": 1009,
                        "embed_dim": 4, "hidden": (16, 8), "bf16": False}
        steps, batch, requests, rows_per_req = 3, 16, 12, 4
    else:
        model_config = {"model": "wide_deep_dense", "vocab_size": 100_003,
                        "embed_dim": 16, "hidden": (256, 128, 64),
                        "bf16": False}
        steps, batch, requests, rows_per_req = 10, 256, 120, 4
    with tempfile.TemporaryDirectory() as tmp:
        export = os.path.join(tmp, "export")
        results = {"scenario": "r19", "smoke": smoke}
        results["train"] = bench_train(model_config, steps, batch,
                                       world=world, export_dir=export,
                                       log_dir=tmp)
        results["serve"] = bench_serve(export, requests, rows_per_req,
                                       log_dir=tmp)
    return results


def markdown_table(results: dict) -> str:
    t, s = results["train"], results["serve"]
    lines = [
        "| metric | value |",
        "|---|---|",
        f"| table ({t['table_rows']} rows x {t['embed_dim']}+1 cols) "
        f"| {t['table_mb']} MB |",
        f"| train rows/s (W={t['world']}, batch {t['batch_size']}) "
        f"| {t['train_rows_per_s']} |",
        f"| step wall | {t['step_ms']} ms |",
        f"| sparse wire bytes/node ({t['steps']} steps) "
        f"| {t['sparse_tx_bytes_per_node']} |",
        f"| dense-replication alternative bytes/node "
        f"| {t['dense_alt_bytes_per_node']} |",
        f"| **dense vs sparse exchanged-bytes** "
        f"| **{t['dense_vs_sparse_x']}x** |",
        f"| serve qps ({s['rows_per_request']} rows/req, "
        f"{s['clients']} clients) | {s['serve_qps']} |",
        f"| serve rows/s | {s['serve_rows_per_s']} |",
    ]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", "--quick", action="store_true", dest="smoke",
                    help="tiny config (CI smoke)")
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--json", default=None, help="write results JSON here")
    args = ap.parse_args(argv)
    results = bench(smoke=args.smoke, world=args.world)
    print(markdown_table(results))
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(results, f, indent=2)
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
