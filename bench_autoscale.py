"""Elastic-autoscaling bench: serving replicas follow a stepped load.

The closed loop under measurement (ISSUE 9 / BENCH_r11): a serving
cluster starts at 1 replica, offered load steps **1x -> 4x -> 1x**, and
``cluster.autoscale`` + ``QueueDepthBandPolicy`` must move the fleet with
it — scale-out while the 4x step holds, scale back in after it passes —
with **zero failed requests that are not 503s** across both transitions
(scale-out rendezvous, scale-in drain).

Load shape: C closed-loop client threads per phase against a
``max_batch=1`` gateway.  One-row-per-round serialization makes the
admission-queue depth track the offered concurrency itself (depth ~=
clients - replicas-in-service, whatever the box's service rate), so the
queue-depth band responds to the *step*, not to how fast this machine's
linear model happens to be — the bench is about the control loop, not
model throughput.

Recorded per phase: qps/p50/p99, request + error counts, replica count at
entry/exit.  Recorded globally: a sampled replica/queue-depth trajectory,
the autoscaler's full decision trail (every ``scale_out`` / ``scale_in``
/ ``cooldown_hold`` with the stats snapshot that justified it), and the
acceptance verdict.

Acceptance gate (r11): replicas rise above 1 during the 4x phase, return
to 1 by the end of the final 1x phase (inside policy cooldowns — the
tail phase budgets K scale-in windows + cooldown per step down), and no
request fails with anything but ``ServeQueueFull`` (the 503).

Usage::

    python bench_autoscale.py                  # full run, markdown + JSON
    python bench_autoscale.py --quick          # short phases (CI smoke)
    python bench_autoscale.py --json out.json

Run on an otherwise idle box.
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import threading
import time


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class _Loader:
    """One closed-loop client thread; latencies and classified errors are
    read after ``stop()``."""

    def __init__(self, gateway, feature_dim: int):
        from tensorflowonspark_tpu.serving import ServeQueueFull

        import numpy as np

        self._gateway = gateway
        self._rows = [np.arange(feature_dim, dtype=np.float32)]
        self._503 = ServeQueueFull
        self._stop = threading.Event()
        self.latencies: list[float] = []
        self.errors_503 = 0
        self.errors_other: list[str] = []
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            t0 = time.perf_counter()
            try:
                self._gateway.predict(self._rows, timeout=60.0)
                self.latencies.append(time.perf_counter() - t0)
            except self._503:
                self.errors_503 += 1
                time.sleep(0.01)  # a real client would back off on a 503
            except Exception as e:  # noqa: BLE001 - the acceptance gate counts these
                self.errors_other.append(f"{type(e).__name__}: {e}")
                return

    def stop(self, timeout: float = 60.0) -> None:
        self._stop.set()
        self._thread.join(timeout)


def _drain_counts(loaders: list[_Loader]) -> tuple[list[float], int, list[str]]:
    lats = sorted(x for ld in loaders for x in ld.latencies)
    e503 = sum(ld.errors_503 for ld in loaders)
    other = [e for ld in loaders for e in ld.errors_other]
    return lats, e503, other


def run_step_scenario(cluster, gateway, scaler, *, feature_dim: int,
                      phases: list[tuple[str, int, float]],
                      sample_secs: float = 0.25) -> dict:
    """Drive the load steps against a live autoscaled cluster.

    ``phases`` is ``[(label, clients, duration_s), ...]``; client threads
    are added or stopped at each boundary (the mid-run population change
    IS the step).  A sampler records ``(t, replicas, queue_depth)``
    throughout, so the trajectory shows the fleet following the load, not
    just phase-end snapshots.
    """
    from tensorflowonspark_tpu import telemetry

    trajectory: list[dict] = []
    stop_sampling = threading.Event()
    t_start = time.perf_counter()

    def _sampler() -> None:
        depth_gauge = telemetry.gauge("serve.queue_depth")
        while not stop_sampling.wait(sample_secs):
            trajectory.append({
                "t": round(time.perf_counter() - t_start, 2),
                "replicas": cluster.num_feedable(),
                "healthy": len(gateway.healthy_replicas()),
                "queue_depth": depth_gauge.value(),
            })

    sampler = threading.Thread(target=_sampler, daemon=True)
    sampler.start()
    loaders: list[_Loader] = []
    retired: list[_Loader] = []
    phase_rows: list[dict] = []
    try:
        for label, clients, duration in phases:
            # step DOWN first (stop the excess), then top up to the target
            while len(loaders) > clients:
                ld = loaders.pop()
                ld.stop()
                retired.append(ld)
            while len(loaders) < clients:
                loaders.append(_Loader(gateway, feature_dim))
            entered = cluster.num_feedable()
            before = sum(len(ld.latencies) for ld in (*loaders, *retired))
            t0 = time.perf_counter()
            time.sleep(duration)
            elapsed = time.perf_counter() - t0
            after = sum(len(ld.latencies) for ld in (*loaders, *retired))
            window = [s for s in trajectory
                      if t0 - t_start <= s["t"] <= t0 - t_start + elapsed]
            lats = sorted(x for ld in loaders for x in ld.latencies)
            phase_rows.append({
                "phase": label,
                "clients": clients,
                "duration_s": round(elapsed, 2),
                "requests": after - before,
                "qps": round((after - before) / elapsed, 1),
                "p50_ms": round(_percentile(lats, 0.50) * 1e3, 2),
                "p99_ms": round(_percentile(lats, 0.99) * 1e3, 2),
                "replicas_entry": entered,
                "replicas_exit": cluster.num_feedable(),
                "replicas_max": max((s["replicas"] for s in window),
                                    default=entered),
            })
    finally:
        for ld in loaders:
            ld.stop()
        stop_sampling.set()
        sampler.join(10.0)
    lats, e503, other = _drain_counts(loaders + retired)
    return {
        "phases": phase_rows,
        "trajectory": trajectory,
        "requests_total": len(lats),
        "errors_503": e503,
        "errors_other": other,
        "decisions": scaler.report(),
    }


def bench(quick: bool = False) -> dict:
    """One autoscaled serving cluster through the 1x -> 4x -> 1x step.

    The final 1x phase budgets the scale-in path explicitly: each step
    down needs ``scale_in_ticks`` consecutive under-band windows plus a
    cooldown, so its duration is ~(max_nodes - 1) such cycles — replicas
    must be back at 1 before it ends for the gate to pass.
    """
    from tensorflowonspark_tpu import cluster as tcluster
    from tensorflowonspark_tpu import serving, telemetry
    from tensorflowonspark_tpu.autoscale import QueueDepthBandPolicy
    from tensorflowonspark_tpu.checkpoint import export_bundle
    from tensorflowonspark_tpu.models import linear as linmod

    feature_dim = 8
    base_clients = 2
    max_nodes = 2 if quick else 3
    tick = 0.4 if quick else 1.0
    cooldown = 1.0 if quick else 3.0
    scale_in_ticks = 2 if quick else 3
    phases = [("1x", base_clients, 3.0 if quick else 8.0),
              ("4x", base_clients * 4, 6.0 if quick else 15.0),
              ("1x", base_clients, 12.0 if quick else 30.0)]
    config = {"model": "linear", "in_dim": feature_dim,
              "out_dim": feature_dim}
    telemetry.reset()
    results: dict = {
        "mode": "autoscale-step",
        "base_clients": base_clients,
        "bounds": [1, max_nodes],
        "tick_secs": tick,
        "cooldown_secs": cooldown,
        "scale_in_ticks": scale_in_ticks,
    }
    with tempfile.TemporaryDirectory() as tmp:
        export = os.path.join(tmp, "bundle")
        export_bundle(export, linmod.init_params(config, scale=2.0), config)
        cluster = tcluster.run(
            serving.serving_loop,
            {"export_dir": export, "max_batch": 1},
            num_executors=1,
            input_mode=tcluster.InputMode.STREAMING,
            heartbeat_interval=0.5,
            reservation_timeout=120.0,
            elastic=True,
        )
        try:
            # max_batch=1 serializes replica rounds: the admission queue
            # holds exactly the offered concurrency the fleet can't seat,
            # which is the signal the band policy reads (see module doc)
            gateway = cluster.serve(export, max_batch=1, max_delay_ms=1.0,
                                    queue_limit=256, listen=False,
                                    reload_poll_secs=0)
            # warmup OUTSIDE the measured phases: compile the first
            # replica's jitted apply so phase-1 p99 is steady-state
            warm = _Loader(gateway, feature_dim)
            time.sleep(0.5)
            warm.stop()
            scaler = cluster.autoscale(
                QueueDepthBandPolicy(low=1.0, high=4.0),
                min_nodes=1, max_nodes=max_nodes, tick_secs=tick,
                cooldown_secs=cooldown, scale_in_ticks=scale_in_ticks,
                window=max(2.0 * tick, 1.5))
            results["policy"] = scaler.policy.describe()
            results.update(run_step_scenario(
                cluster, gateway, scaler, feature_dim=feature_dim,
                phases=phases))
        finally:
            cluster.shutdown(timeout=120.0)
    rows = {r["phase"]: r for r in results["phases"]}
    last = results["phases"][-1]
    results["acceptance"] = {
        "scaled_out_on_step": rows["4x"]["replicas_max"] > 1,
        "scaled_back_in": last["replicas_exit"] == 1,
        "errors_other": len(results["errors_other"]),
        "errors_503": results["errors_503"],
    }
    return results


def markdown_table(results: dict) -> str:
    lines = [f"### autoscaled serving, load step 1x -> 4x -> 1x "
             f"(bounds={results['bounds']}, tick={results['tick_secs']}s, "
             f"cooldown={results['cooldown_secs']}s, "
             f"K={results['scale_in_ticks']})",
             "| phase | clients | dur s | requests | qps | p50 ms | p99 ms |"
             " replicas in/max/out |",
             "|---|---|---|---|---|---|---|---|"]
    for r in results["phases"]:
        lines.append(
            f"| {r['phase']} | {r['clients']} | {r['duration_s']} | "
            f"{r['requests']:,} | {r['qps']:,.0f} | {r['p50_ms']} | "
            f"{r['p99_ms']} | {r['replicas_entry']}/{r['replicas_max']}"
            f"/{r['replicas_exit']} |")
    counts = results["decisions"]["counts"]
    lines.append("")
    lines.append(f"decisions: {counts.get('scale_out', 0)} scale_out, "
                 f"{counts.get('scale_in', 0)} scale_in, "
                 f"{counts.get('cooldown_hold', 0)} cooldown_hold, "
                 f"{counts.get('resize_failures', 0)} resize failures; "
                 f"{results['requests_total']:,} requests, "
                 f"{results['errors_503']} x 503, "
                 f"{len(results['errors_other'])} hard failures")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="short phases / tight ticks (smoke test)")
    ap.add_argument("--json", default="",
                    help="also write the raw results to this JSON file")
    args = ap.parse_args(argv)
    results = bench(quick=args.quick)
    print(markdown_table(results))
    acc = results["acceptance"]
    ok = (acc["scaled_out_on_step"] and acc["scaled_back_in"]
          and acc["errors_other"] == 0)
    print(f"acceptance r11 (replicas follow 1x->4x->1x within policy "
          f"cooldowns, zero non-503 failures): {'PASS' if ok else 'MISS'} "
          f"(out={acc['scaled_out_on_step']}, in={acc['scaled_back_in']}, "
          f"hard failures={acc['errors_other']})")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
        print(f"raw results -> {args.json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
