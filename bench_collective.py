"""Cross-host collective bench: bucketed ring vs naive gather-broadcast.

The number this bench exists to produce (ISSUE 12 / BENCH_r13): aggregate
all-reduce bandwidth over the cluster wire for the two algorithms, same
run, same payload, same node processes —

- ``ring``: the production path (``TOS_COLLECTIVE_ALGO=ring``) — chunked
  ring all-reduce (reduce-scatter + all-gather), every node moving
  ``2(W-1)/W x N`` bytes with all links active concurrently, transfers
  sub-chunked at ``TOS_COLLECTIVE_BUCKET_BYTES`` so accumulate overlaps
  the wire.
- ``naive``: the gather-broadcast control — every rank ships its whole
  array to rank 0, the root reduces and ships the result back.  Identical
  TOTAL wire bytes at any world size (``2(W-1) x N``), but the root
  serializes them: first the whole gather, then the whole broadcast, one
  peer at a time.

Every round VERIFIES the reduced result exactly (rank r contributes
``full(r+1)``; the result must equal ``W(W+1)/2`` everywhere) — a wrong
sum fails the bench, it never just skews the MB/s.

Topology per node process: ``FeedQueues + DataServer`` (the collective
wire rides the node's data port, exactly as in a real cluster),
``CoordinatorClient`` registration for identity, and a ``CollectiveGroup``
formed through the driver's ``CoordinatorServer`` rendezvous.

Headline metric: ``agg_mb_per_s = W x payload_bytes / t`` — every node
reduced its full payload in ``t`` seconds (t = the slowest node's wall
time for the round, medianed over rounds).  The acceptance ratio is
``ring_vs_naive_x = naive_t / ring_t`` on >= 64 MB payloads.

Round 15 (ISSUE 13) adds the CONTROL-PLANE numbers: ``--scenario r14``
measures the write-ahead journal's rendezvous-latency cost (interleaved
journal-on vs journal-off barrier/reduce round-trips on twin coordinators —
every control-plane mutation now pays an fsync'd append) and the measured
coordinator RECOVERY TIME: crash -> journal replay -> rebind -> first
post-failover rendezvous completing, the window a `kill_coordinator` chaos
run actually rides out.

Round 17 (ISSUE 15) adds the GRAY-FAILURE numbers: ``--scenario r16``
measures (a) the stall -> suspicion -> quorum-eviction -> degraded-world
resume latency with one member wedged mid-all-reduce (the number that
replaces "ride out TOS_COLLECTIVE_TIMEOUT and thrash"), and (b) the
steady-state cost of the per-peer contribution-timing bookkeeping the
detection rides on (interleaved detect-on/off rounds in one run; the bar
is <= 2%).

Usage::

    python bench_collective.py                      # full run, markdown + JSON
    python bench_collective.py --quick              # tiny sizes (CI smoke)
    python bench_collective.py --json BENCH_r13.json
    python bench_collective.py --scenario r14 --json BENCH_r14.json
    python bench_collective.py --scenario r16 --json BENCH_r16.json
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import statistics
import tempfile
import threading
import time

import numpy as np

ALGOS = ("ring", "naive")


def _node_main(conn, coord_addr, authkey: bytes, world: int,
               payload_elems: int, repeats: int, algos, bucket_bytes: int,
               timeout: float) -> None:
    """Child process: one collective member — DataServer (the peer wire) +
    coordinator registration + a CollectiveGroup running timed rounds."""
    from tensorflowonspark_tpu.collective import CollectiveGroup
    from tensorflowonspark_tpu.coordinator import CoordinatorClient
    from tensorflowonspark_tpu.dataserver import DataServer
    from tensorflowonspark_tpu.feeding import FeedQueues

    queues = FeedQueues(capacity=8)
    server = DataServer(queues, authkey, feed_timeout=timeout)
    port = server.start()
    client = CoordinatorClient(coord_addr, authkey=authkey)
    ident = client.register({"host": "127.0.0.1", "data_port": port,
                             "pid": os.getpid()})
    eid = int(ident["executor_id"])
    client.set_identity(eid, int(ident.get("incarnation", 0)))
    group = CollectiveGroup(coord_addr, authkey, eid, world,
                            "127.0.0.1", port, name="bench", timeout=timeout,
                            bucket_bytes=bucket_bytes)
    try:
        group.form()
        arr = np.full(payload_elems, float(eid + 1), np.float32)
        expect = np.float32(world * (world + 1) / 2.0)
        results: dict[str, list[float]] = {}
        for algo in algos:
            # warmup: one FULL-SIZE untimed round — dials + attaches, page
            # faults on the big buffers, and TCP buffer/congestion-window
            # autotune growth (which small writes take several rounds to
            # finish; measured: the first 1-2 cold rounds run ~2x slow)
            group.all_reduce(arr, algo=algo)
            times = []
            for _ in range(repeats):
                group.barrier()  # rounds start aligned across nodes
                t0 = time.perf_counter()
                out = group.all_reduce(arr, algo=algo)
                dt = time.perf_counter() - t0
                if out.shape != arr.shape or not np.all(out == expect):
                    raise RuntimeError(
                        f"{algo}: corrupted all-reduce result on rank "
                        f"{group.rank} (expected {expect})")
                times.append(dt)
            results[algo] = times
        conn.send({"eid": eid, "rank": group.rank, "results": results})
    except BaseException as e:  # noqa: BLE001 - surfaced driver-side
        conn.send(RuntimeError(f"bench node failed: {e!r}"))
        raise
    finally:
        group.close()
        client.close()
        server.stop()


def bench_once(world: int, payload_bytes: int, repeats: int,
               algos=ALGOS, bucket_bytes: int = 4 << 20,
               timeout: float = 120.0) -> dict:
    """One measured comparison: ``world`` real node processes, both
    algorithms, same payload, interleaved in one run."""
    from tensorflowonspark_tpu.coordinator import CoordinatorServer

    payload_elems = max(1, payload_bytes // 4)
    payload_bytes = payload_elems * 4
    authkey = b"bench-collective"
    coord = CoordinatorServer(world, authkey=authkey)
    addr = coord.start("127.0.0.1")
    ctx = mp.get_context("fork")
    procs, conns = [], []
    try:
        for _ in range(world):
            parent, child = ctx.Pipe()
            p = ctx.Process(target=_node_main,
                            args=(child, addr, authkey, world, payload_elems,
                                  repeats, tuple(algos), bucket_bytes,
                                  timeout),
                            daemon=True)
            p.start()
            procs.append(p)
            conns.append(parent)
        reports = []
        for conn in conns:
            got = conn.recv()
            if isinstance(got, BaseException):
                raise got
            reports.append(got)
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
        coord.stop()
    out: dict = {"world": world, "payload_mb": round(payload_bytes / 1e6, 2),
                 "payload_bytes": payload_bytes, "repeats": repeats,
                 "bucket_bytes": bucket_bytes}
    for algo in algos:
        # a round is only done when its SLOWEST node is done
        round_times = [max(r["results"][algo][i] for r in reports)
                       for i in range(repeats)]
        t = statistics.median(round_times)
        out[algo] = {
            "seconds_median": round(t, 4),
            "round_seconds": [round(x, 4) for x in round_times],
            # W nodes each had their N-byte array fully reduced in t
            "agg_mb_per_s": round(world * payload_bytes / t / 1e6, 1),
            # the classic algbw framing (payload / time)
            "alg_mb_per_s": round(payload_bytes / t / 1e6, 1),
        }
    if "ring" in out and "naive" in out:
        out["ring_vs_naive_x"] = round(
            out["naive"]["seconds_median"] / out["ring"]["seconds_median"], 2)
    return out


def bench(quick: bool = False, world: int | None = None,
          payload_mb: float | None = None, repeats: int | None = None,
          bucket_bytes: int = 4 << 20) -> dict:
    world = world or (2 if quick else 3)
    payload_bytes = int((payload_mb or (1 if quick else 64)) * (1 << 20))
    repeats = repeats or (2 if quick else 5)
    return bench_once(world, payload_bytes, repeats,
                      bucket_bytes=bucket_bytes)


def bench_r13(repeats: int = 7, payload_mb: float = 64.0,
              bucket_bytes: int = 4 << 20) -> dict:
    """The BENCH_r13 scenario: the acceptance comparison at W=3 (ring's
    bandwidth optimality vs the root-serialized control) plus the W=2
    minimal ring as context — both on the same >=64 MB payload."""
    payload = int(payload_mb * (1 << 20))
    return {
        "schema": "tos-bench-collective-r13",
        "w3": bench_once(3, payload, repeats, bucket_bytes=bucket_bytes),
        "w2": bench_once(2, payload, repeats, bucket_bytes=bucket_bytes),
    }


def _timed_rendezvous(server, clients, name: str,
                      resilient: bool = False) -> float:
    """Wall seconds for one count=2 reduce to complete for BOTH
    participants (two threads, joined) — the sync-training control-plane
    primitive the journal taxes.  ``resilient=True`` follows the failover
    caller contract (the recovery cell's first post-crash rendezvous rides
    a reconnect): re-enter on CoordinatorRestarted, like group.form does."""
    t0 = time.perf_counter()

    def _one(c, v):
        deadline = time.monotonic() + 30.0
        while True:
            try:
                return c.reduce(name, v, kind="sum", count=2, timeout=30.0)
            except (RuntimeError, ConnectionError):
                if not resilient or time.monotonic() > deadline:
                    raise
                time.sleep(0.01)

    t = threading.Thread(target=_one, args=(clients[1], 2), daemon=True)
    t.start()
    _one(clients[0], 1)
    t.join()
    return time.perf_counter() - t0


def _journal_pair(journal_path: str | None, slots: int = 2,
                  stats_interval: float = 1.0):
    from tensorflowonspark_tpu.coordinator import (
        CoordinatorClient,
        CoordinatorServer,
    )

    srv = CoordinatorServer(slots, journal_path=journal_path,
                            stats_interval=stats_interval)
    addr = srv.start()
    clients = []
    for i in range(slots):
        c = CoordinatorClient(addr)
        ident = c.register({"host": f"h{i}"})
        c.set_identity(ident["executor_id"], ident["incarnation"])
        clients.append(c)
    return srv, clients


def bench_journal_compare(rounds: int = 300) -> dict:
    """Interleaved journal-on/off rendezvous-latency compare: twin
    coordinators (one journaled, one not), each serving the same 2-client
    count=2 reduce, measured alternately round by round so box drift hits
    both cells equally.  The delta IS the fsync'd ``rdv_open``+``rdv_close``
    appends on the journaled path."""
    with tempfile.TemporaryDirectory() as td:
        cells = {"journal_off": _journal_pair(None),
                 "journal_on": _journal_pair(os.path.join(td, "j"))}
        times: dict[str, list[float]] = {k: [] for k in cells}
        try:
            for key, (srv, clients) in cells.items():
                _timed_rendezvous(srv, clients, "warmup")  # dials + caches
            for i in range(rounds):
                order = list(cells) if i % 2 == 0 else list(cells)[::-1]
                for key in order:
                    srv, clients = cells[key]
                    times[key].append(
                        _timed_rendezvous(srv, clients, f"r{i}"))
        finally:
            for srv, clients in cells.values():
                for c in clients:
                    c.close()
                srv.stop()
    out: dict = {"rounds": rounds}
    for key, ts in times.items():
        out[key] = {"p50_us": round(statistics.median(ts) * 1e6, 1),
                    "p99_us": round(sorted(ts)[int(0.99 * len(ts))] * 1e6, 1)}
    off, on = out["journal_off"]["p50_us"], out["journal_on"]["p50_us"]
    out["journal_cost_us_p50"] = round(on - off, 1)
    out["journal_overhead_pct_p50"] = round(100.0 * (on - off) / off, 1)
    return out


def bench_recovery(slots: int = 8, tail_records: int = 512,
                   repeats: int = 5) -> dict:
    """Measured coordinator recovery time: crash -> journal replay (snapshot
    + ``tail_records`` rendezvous-record tail) -> same-port rebind -> the
    FIRST post-failover rendezvous completing for both participants.  This
    is the control-plane blackout a ``kill_coordinator`` chaos run rides
    out (client reconnect backoff excluded: clients here re-dial eagerly,
    so the number isolates the server-side cost)."""
    samples = {"restore_ms": [], "first_rendezvous_ms": []}
    replayed = 0
    for _ in range(repeats):
        with tempfile.TemporaryDirectory() as td:
            # `slots` registered members; clients[0:2] run the rendezvous,
            # the rest are idle registered slots the replay must rebuild.
            # A huge stats interval pins the periodic snapshot off: the
            # fill below must stay a journal TAIL, or restore_ms would
            # measure replay of a freshly-truncated (near-empty) journal.
            srv, clients = _journal_pair(os.path.join(td, "j"), slots=slots,
                                         stats_interval=3600.0)
            try:
                # grow a realistic journal tail: rendezvous open/close pairs
                for i in range(tail_records // 2):
                    _timed_rendezvous(srv, clients, f"fill{i}")
                from tensorflowonspark_tpu.journal import replay as _replay

                srv.crash()
                tail_len = len(_replay(os.path.join(td, "j"))[1])
                t0 = time.perf_counter()
                srv.restore()
                restore_s = time.perf_counter() - t0
                rdv_s = _timed_rendezvous(srv, clients, "post",
                                          resilient=True)
                samples["restore_ms"].append(round(restore_s * 1e3, 3))
                samples["first_rendezvous_ms"].append(
                    round((restore_s + rdv_s) * 1e3, 3))
                replayed = len(srv.cluster_info())
            finally:
                for c in clients:
                    c.close()
                srv.stop()
    return {"slots": slots, "tail_records": tail_records, "repeats": repeats,
            "replayed_tail_records": tail_len,
            "replayed_slots": replayed,
            "restore_ms_median": statistics.median(samples["restore_ms"]),
            "crash_to_first_rendezvous_ms_median":
                statistics.median(samples["first_rendezvous_ms"]),
            "samples": samples}


def _gray_node_main(conn, coord_addr, authkey: bytes, world: int,
                    payload_elems: int, rounds: int, stall_round: int,
                    stall_secs: float, timeout: float) -> None:
    """Child for the r16 eviction-latency cell: every member runs `rounds`
    all-reduces with reform-on-abort; the member ASSIGNED eid 1 goes gray
    (sleeps) at `stall_round`.  Survivors report the wall time from the
    stalled round's start to their first COMPLETED degraded-world
    all-reduce — the stall -> detect -> evict -> resume window."""
    import time as _time

    from tensorflowonspark_tpu.collective import (
        CollectiveAborted,
        CollectiveGroup,
    )
    from tensorflowonspark_tpu.coordinator import CoordinatorClient
    from tensorflowonspark_tpu.dataserver import DataServer
    from tensorflowonspark_tpu.feeding import FeedQueues

    queues = FeedQueues(capacity=8)
    server = DataServer(queues, authkey, feed_timeout=timeout)
    port = server.start()
    client = CoordinatorClient(coord_addr, authkey=authkey)
    ident = client.register({"host": "127.0.0.1", "data_port": port,
                             "pid": os.getpid()})
    eid = int(ident["executor_id"])
    client.set_identity(eid, int(ident.get("incarnation", 0)))
    group = CollectiveGroup(coord_addr, authkey, eid, world,
                            "127.0.0.1", port, name="gray16",
                            timeout=timeout)
    victim = eid == 1
    arr = np.full(payload_elems, 1.0, np.float32)
    stall_to_resume = None
    t_stall_start = None
    done_rounds = 0
    try:
        group.form()
        r = 0
        while r < rounds:
            if victim and r == stall_round:
                _time.sleep(stall_secs)  # the gray failure
            t0 = time.perf_counter()
            try:
                out = group.all_reduce(arr)
            except CollectiveAborted:
                if t_stall_start is None:
                    t_stall_start = t0
                try:
                    group.reform(timeout=6.0)
                except CollectiveAborted:
                    break  # evicted: fenced through probation — bow out
                continue
            if not np.all(out == np.float32(group.effective_world)):
                raise RuntimeError("corrupted degraded-world all-reduce")
            if t_stall_start is not None and stall_to_resume is None:
                stall_to_resume = time.perf_counter() - t_stall_start
            done_rounds += 1
            r += 1
        conn.send({"eid": eid, "victim": victim, "rounds": done_rounds,
                   "world": group.effective_world,
                   "stall_to_resume": stall_to_resume})
    except BaseException as e:  # noqa: BLE001 - surfaced driver-side
        conn.send(RuntimeError(f"gray bench node failed: {e!r}"))
        raise
    finally:
        group.close()
        client.close()
        server.stop()


def bench_eviction_latency(world: int = 3, payload_mb: float = 4.0,
                           rounds: int = 8, stall_round: int = 3,
                           stall_secs: float = 20.0,
                           timeout: float = 120.0) -> dict:
    """The headline r16 number: one member wedges mid-run; how long until
    the survivors are training again at W-1?  The baseline this replaces:
    every round stalls the full TOS_COLLECTIVE_TIMEOUT (default 120s) and
    reform re-admits the straggler — thrash, forever."""
    from tensorflowonspark_tpu.coordinator import CoordinatorServer

    payload_elems = max(1, int(payload_mb * (1 << 20)) // 4)
    authkey = b"bench-gray"
    prior_probation = os.environ.get("TOS_COLLECTIVE_PROBATION_SECS")
    os.environ["TOS_COLLECTIVE_PROBATION_SECS"] = "600"  # victim stays out
    coord = CoordinatorServer(world, authkey=authkey)
    addr = coord.start("127.0.0.1")
    ctx = mp.get_context("fork")
    procs, conns = [], []
    try:
        for _ in range(world):
            parent, child = ctx.Pipe()
            p = ctx.Process(target=_gray_node_main,
                            args=(child, addr, authkey, world, payload_elems,
                                  rounds, stall_round, stall_secs, timeout),
                            daemon=True)
            p.start()
            procs.append(p)
            conns.append(parent)
        reports = []
        for conn in conns:
            got = conn.recv()
            if isinstance(got, BaseException):
                raise got
            reports.append(got)
    finally:
        for p in procs:
            p.join(timeout=60)
            if p.is_alive():
                p.terminate()
        coord.stop()
        if prior_probation is None:
            os.environ.pop("TOS_COLLECTIVE_PROBATION_SECS", None)
        else:
            os.environ["TOS_COLLECTIVE_PROBATION_SECS"] = prior_probation
    survivors = [r for r in reports if not r["victim"]]
    assert survivors and all(r["rounds"] == rounds for r in survivors), reports
    assert all(r["world"] == world - 1 for r in survivors), reports
    resume = max(r["stall_to_resume"] for r in survivors)
    evictions = [e["eid"] for e in coord.evictions()]
    return {
        "world": world, "payload_mb": payload_mb, "rounds": rounds,
        "stall_secs": stall_secs,
        "evicted": evictions,
        "stall_to_resume_secs": round(resume, 2),
        "baseline_timeout_thrash_secs": 120.0,
        "speedup_vs_timeout_x": round(120.0 / resume, 1),
    }


def _detect_node_main(conn, coord_addr, authkey: bytes, world: int,
                      payload_elems: int, repeats: int,
                      bucket_bytes: int, timeout: float) -> None:
    """Child for the r16 overhead cell: `repeats` all-reduce PAIRS, each
    pair one detection-ON and one detection-OFF round back to back (round
    parity toggles `tp.detect` identically on every node — no coordination
    needed), barrier-aligned so box drift hits both cells equally."""
    from tensorflowonspark_tpu.collective import CollectiveGroup
    from tensorflowonspark_tpu.coordinator import CoordinatorClient
    from tensorflowonspark_tpu.dataserver import DataServer
    from tensorflowonspark_tpu.feeding import FeedQueues

    queues = FeedQueues(capacity=8)
    server = DataServer(queues, authkey, feed_timeout=timeout)
    port = server.start()
    client = CoordinatorClient(coord_addr, authkey=authkey)
    ident = client.register({"host": "127.0.0.1", "data_port": port,
                             "pid": os.getpid()})
    eid = int(ident["executor_id"])
    client.set_identity(eid, int(ident.get("incarnation", 0)))
    group = CollectiveGroup(coord_addr, authkey, eid, world,
                            "127.0.0.1", port, name="detect16",
                            timeout=timeout, bucket_bytes=bucket_bytes)
    try:
        group.form()
        arr = np.full(payload_elems, float(eid + 1), np.float32)
        expect = np.float32(world * (world + 1) / 2.0)
        group.all_reduce(arr)  # warmup: dials, attaches, TCP autotune
        times: dict[str, list[float]] = {"detect_on": [], "detect_off": []}
        for i in range(repeats * 2):
            on = i % 2 == 0
            group._tp.detect = on
            group.barrier()
            t0 = time.perf_counter()
            out = group.all_reduce(arr)
            dt = time.perf_counter() - t0
            if not np.all(out == expect):
                raise RuntimeError("corrupted all-reduce in overhead cell")
            times["detect_on" if on else "detect_off"].append(dt)
        group._tp.detect = True
        conn.send({"eid": eid, "times": times})
    except BaseException as e:  # noqa: BLE001 - surfaced driver-side
        conn.send(RuntimeError(f"detect bench node failed: {e!r}"))
        raise
    finally:
        group.close()
        client.close()
        server.stop()


def bench_detect_compare(world: int = 2, payload_mb: float = 4.0,
                         repeats: int = 24, bucket_bytes: int = 4 << 20,
                         timeout: float = 120.0) -> dict:
    """Steady-state cost of the per-peer timing bookkeeping (detection ON
    vs OFF), interleaved round-by-round in ONE run so box drift hits both
    cells equally — the satellite bar is <= 2%."""
    from tensorflowonspark_tpu.coordinator import CoordinatorServer

    payload_elems = max(1, int(payload_mb * (1 << 20)) // 4)
    authkey = b"bench-detect"
    coord = CoordinatorServer(world, authkey=authkey)
    addr = coord.start("127.0.0.1")
    ctx = mp.get_context("fork")
    procs, conns = [], []
    try:
        for _ in range(world):
            parent, child = ctx.Pipe()
            p = ctx.Process(target=_detect_node_main,
                            args=(child, addr, authkey, world, payload_elems,
                                  repeats, bucket_bytes, timeout),
                            daemon=True)
            p.start()
            procs.append(p)
            conns.append(parent)
        reports = []
        for conn in conns:
            got = conn.recv()
            if isinstance(got, BaseException):
                raise got
            reports.append(got)
    finally:
        for p in procs:
            p.join(timeout=60)
            if p.is_alive():
                p.terminate()
        coord.stop()
    out: dict = {"world": world, "payload_mb": payload_mb,
                 "repeats": repeats}
    for cell in ("detect_on", "detect_off"):
        round_times = [max(r["times"][cell][i] for r in reports)
                       for i in range(repeats)]
        out[cell] = {
            "seconds_median": round(statistics.median(round_times), 5),
            "agg_mb_per_s": round(
                world * payload_elems * 4
                / statistics.median(round_times) / 1e6, 1),
        }
    on, off = (out["detect_on"]["seconds_median"],
               out["detect_off"]["seconds_median"])
    out["overhead_pct"] = round(100.0 * (on - off) / off, 2)
    return out


def bench_r16(payload_mb: float = 4.0, repeats: int = 24,
              stall_secs: float = 20.0) -> dict:
    """The BENCH_r16 scenario (ISSUE 15): gray-failure eviction latency +
    detection-bookkeeping overhead."""
    return {
        "schema": "tos-bench-collective-r16",
        "eviction": bench_eviction_latency(stall_secs=stall_secs),
        "detect_overhead": bench_detect_compare(payload_mb=payload_mb,
                                                repeats=repeats),
    }


def bench_r14(rounds: int = 300, tail_records: int = 512,
              repeats: int = 5) -> dict:
    """The BENCH_r14 scenario (ISSUE 13): what the write-ahead journal
    costs per rendezvous, and what a coordinator failover costs end to
    end."""
    return {
        "schema": "tos-bench-collective-r14",
        "journal_compare": bench_journal_compare(rounds),
        "recovery": bench_recovery(tail_records=tail_records,
                                   repeats=repeats),
    }


def markdown_r14(result: dict) -> str:
    jc, rec = result["journal_compare"], result["recovery"]
    return "\n".join([
        "| cell | rendezvous p50 us | p99 us |",
        "|---|---|---|",
        f"| journal off | {jc['journal_off']['p50_us']} "
        f"| {jc['journal_off']['p99_us']} |",
        f"| journal on | {jc['journal_on']['p50_us']} "
        f"| {jc['journal_on']['p99_us']} |",
        "",
        f"journal cost: +{jc['journal_cost_us_p50']} us p50 "
        f"(+{jc['journal_overhead_pct_p50']}%) over {jc['rounds']} "
        "interleaved rounds",
        f"recovery ({rec['replayed_slots']} slots, {rec['tail_records']} "
        f"tail records): restore {rec['restore_ms_median']} ms, "
        "crash -> first rendezvous "
        f"{rec['crash_to_first_rendezvous_ms_median']} ms "
        f"(median of {rec['repeats']})",
    ])


def markdown_r16(result: dict) -> str:
    ev, ov = result["eviction"], result["detect_overhead"]
    return "\n".join([
        f"gray stall (W={ev['world']}, {ev['payload_mb']} MB payload, "
        f"{ev['stall_secs']}s wedge): evicted {ev['evicted']}, "
        f"stall -> detect -> evict -> degraded resume "
        f"{ev['stall_to_resume_secs']}s "
        f"(x{ev['speedup_vs_timeout_x']} vs the "
        f"{ev['baseline_timeout_thrash_secs']:.0f}s timeout-thrash "
        "baseline)",
        "",
        "| cell | round median s | agg MB/s |",
        "|---|---|---|",
        f"| detect on | {ov['detect_on']['seconds_median']} "
        f"| {ov['detect_on']['agg_mb_per_s']} |",
        f"| detect off | {ov['detect_off']['seconds_median']} "
        f"| {ov['detect_off']['agg_mb_per_s']} |",
        "",
        f"per-peer timing bookkeeping overhead: {ov['overhead_pct']}% "
        f"({ov['repeats']} interleaved pairs, bar <= 2%)",
    ])


def markdown_table(result: dict) -> str:
    rows = [
        "| algo | median s | agg MB/s | algbw MB/s |",
        "|---|---|---|---|",
    ]
    for algo in ALGOS:
        if algo not in result:
            continue
        r = result[algo]
        rows.append(f"| {algo} | {r['seconds_median']} | {r['agg_mb_per_s']} "
                    f"| {r['alg_mb_per_s']} |")
    rows.append("")
    rows.append(f"W={result['world']}, payload {result['payload_mb']} MB, "
                f"bucket {result['bucket_bytes'] >> 20} MiB, "
                f"ring vs naive: x{result.get('ring_vs_naive_x', '?')}")
    return "\n".join(rows)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="tiny payload, 2 nodes (CI smoke)")
    ap.add_argument("--world", type=int, default=None)
    ap.add_argument("--payload-mb", type=float, default=None)
    ap.add_argument("--repeats", type=int, default=None)
    ap.add_argument("--bucket-mb", type=float, default=4.0)
    ap.add_argument("--scenario", choices=("single", "r13", "r14", "r16"),
                    default="single")
    ap.add_argument("--rounds", type=int, default=300,
                    help="r14: interleaved journal-compare rendezvous rounds")
    ap.add_argument("--tail-records", type=int, default=512,
                    help="r14: journal tail size replayed by the recovery cell")
    ap.add_argument("--stall-secs", type=float, default=20.0,
                    help="r16: how long the gray member wedges")
    ap.add_argument("--json", default=None, help="write results JSON here")
    args = ap.parse_args(argv)
    if args.scenario == "r16":
        result = bench_r16(payload_mb=args.payload_mb or 4.0,
                           repeats=args.repeats or 24,
                           stall_secs=args.stall_secs)
        print(markdown_r16(result))
    elif args.scenario == "r14":
        result = bench_r14(rounds=args.rounds,
                           tail_records=args.tail_records,
                           repeats=args.repeats or 5)
        print(markdown_r14(result))
    elif args.scenario == "r13":
        result = bench_r13(repeats=args.repeats or 7,
                           payload_mb=args.payload_mb or 64.0,
                           bucket_bytes=int(args.bucket_mb * (1 << 20)))
        for key in ("w3", "w2"):
            print(f"### {key}")
            print(markdown_table(result[key]))
            print()
    else:
        result = bench(quick=args.quick, world=args.world,
                       payload_mb=args.payload_mb, repeats=args.repeats,
                       bucket_bytes=int(args.bucket_mb * (1 << 20)))
        print(markdown_table(result))
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=2)
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
