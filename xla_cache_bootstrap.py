"""Shared persistent-XLA-cache bootstrap for the repo's entry points.

The test conftest, the driver gate (``__graft_entry__``) and the bench all
recompile identical XLA programs run after run; the persistent cache cuts
those compiles to sub-second loads.  Two subtleties this helper owns:

- the env vars must be in ``os.environ`` before *any* jax import so spawned
  child processes inherit them;
- jax snapshots env into ``jax.config`` at import, and a pytest plugin (or
  the caller) may have imported jax already — so the config is re-asserted
  afterwards, honouring any user override of the env values.

Kept as a repo-root stdlib-only module (not inside the package) because the
package ``__init__`` itself imports jax — importing a helper from there
would defeat the env-before-import requirement.
"""

from __future__ import annotations

import os


def enable_persistent_cache(default_dir: str | None = None) -> None:
    here = os.path.dirname(os.path.abspath(__file__))
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          default_dir or os.path.join(here, ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

    import jax

    want_dir = os.environ["JAX_COMPILATION_CACHE_DIR"]
    if jax.config.jax_compilation_cache_dir != want_dir:
        jax.config.update("jax_compilation_cache_dir", want_dir)
    want_min = float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"])
    if jax.config.jax_persistent_cache_min_compile_time_secs != want_min:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", want_min)
