"""Online-serving microbench: closed-loop clients vs the gateway, CPU-side.

Measures the request/response path on one box: a real 2-node cluster runs
``serving_loop`` over a tiny linear bundle and C closed-loop clients
(send, wait, repeat) hammer the gateway for a fixed duration.  Reported
per config: sustained qps, p50/p99/mean request latency, row throughput.

Three configs, all against one ``max_batch=64`` gateway:

- ``1row`` — 1-row requests through the native ``gateway.predict`` API
  (in-process client threads).  This is the **gateway capacity** number
  and the acceptance config: it measures admission → micro-batching →
  routing → node round → scatter, without the bench's own client
  processes competing for this small box's cores.
- ``1row_tcp`` — the same shape through the TCP wire endpoint, client
  processes + ``GatewayClient`` connections.  On a 2-core box the clients,
  driver, and both nodes share the CPUs, so this is a lower bound that
  mostly measures the box (recorded for honesty, not gated).
- ``64row_tcp`` — 64-row requests over TCP: each request IS a full static
  batch; the throughput-leaning shape.

Acceptance gate (ISSUE 5): the 2-node loopback gateway sustains >= 500
req/s at max_batch=64 with p99 <= 5x p50 (the ``1row`` config).

Usage::

    python bench_serving.py                  # full table, markdown + JSON
    python bench_serving.py --quick          # small sizes (CI smoke)
    python bench_serving.py --json out.json

Run on an otherwise idle box.
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import tempfile
import threading
import time


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _stats(lats: list[float], elapsed: float, request_rows: int,
           clients: int, transport: str) -> dict:
    lats = sorted(lats)
    n = len(lats)
    if not n:
        raise RuntimeError("no requests completed")
    return {
        "transport": transport,
        "request_rows": request_rows,
        "clients": clients,
        "duration_s": round(elapsed, 2),
        "requests": n,
        "qps": round(n / elapsed, 1),
        "rows_per_s": round(n * request_rows / elapsed, 1),
        "p50_ms": round(_percentile(lats, 0.50) * 1e3, 2),
        "p99_ms": round(_percentile(lats, 0.99) * 1e3, 2),
        "mean_ms": round(sum(lats) / n * 1e3, 2),
    }


# -- in-process closed loop (gateway capacity) --------------------------------


def run_inprocess(gateway, *, request_rows: int, feature_dim: int,
                  clients: int, duration: float) -> dict:
    import numpy as np

    rows = [np.arange(feature_dim, dtype=np.float32) + i
            for i in range(request_rows)]
    per_client: list[list[float]] = [[] for _ in range(clients)]
    errors: list[str] = []

    def _loop(mine: list[float]) -> None:
        try:
            deadline = time.perf_counter() + duration
            while time.perf_counter() < deadline:
                t0 = time.perf_counter()
                gateway.predict(rows, timeout=30.0)
                mine.append(time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=_loop, args=(per_client[i],))
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    if errors:
        raise RuntimeError(f"bench client failed: {errors[0]}")
    return _stats([x for lane in per_client for x in lane], elapsed,
                  request_rows, clients, "inprocess")


# -- TCP closed loop (client processes) ---------------------------------------


def _closed_loop(endpoint, authkey, request_rows: int, feature_dim: int,
                 duration: float, latencies: list[float],
                 errors: list[str]) -> None:
    import numpy as np

    from tensorflowonspark_tpu.serving import GatewayClient

    rows = [np.arange(feature_dim, dtype=np.float32) + i
            for i in range(request_rows)]
    client = GatewayClient(endpoint[0], endpoint[1], authkey)
    mine: list[float] = []
    try:
        deadline = time.perf_counter() + duration
        while time.perf_counter() < deadline:
            t0 = time.perf_counter()
            out = client.predict(rows, timeout=30.0)
            mine.append(time.perf_counter() - t0)
            if len(out) != request_rows:
                errors.append(f"short reply: {len(out)}/{request_rows}")
                return
    except Exception as e:  # noqa: BLE001 - surfaced by the caller
        errors.append(f"{type(e).__name__}: {e}")
    finally:
        latencies.extend(mine)  # one append per client: no lock needed
        try:
            client.close()
        except OSError:  # toslint: allow-silent(bench teardown; the gateway may already be closing)
            pass


def _client_proc_main(conn, endpoint, authkey, request_rows: int,
                      feature_dim: int, conns: int, duration: float) -> None:
    """Child process: ``conns`` closed-loop connections, latencies piped
    back.  TCP clients live OUTSIDE the driver process — in-process client
    threads would share the gateway's GIL, so the wire numbers would
    measure the interpreter, not the endpoint."""
    per_conn: list[list[float]] = [[] for _ in range(conns)]
    errors: list[str] = []
    threads = [
        threading.Thread(target=_closed_loop,
                         args=(endpoint, authkey, request_rows, feature_dim,
                               duration, per_conn[i], errors))
        for i in range(conns)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    conn.send(([x for lane in per_conn for x in lane], errors))


def run_tcp(cluster, gateway, *, request_rows: int, feature_dim: int,
            client_procs: int, conns_per_proc: int, duration: float) -> dict:
    """One closed-loop run against the gateway's TCP endpoint."""
    ctx = mp.get_context("fork")
    procs, pipes = [], []
    for _ in range(client_procs):
        parent, child = ctx.Pipe()
        p = ctx.Process(target=_client_proc_main,
                        args=(child, gateway.endpoint, cluster.authkey,
                              request_rows, feature_dim, conns_per_proc,
                              duration),
                        daemon=True)
        p.start()
        procs.append(p)
        pipes.append(parent)
    t0 = time.perf_counter()
    outs = [pipe.recv() for pipe in pipes]
    elapsed = time.perf_counter() - t0
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    errors = [e for _, errs in outs for e in errs]
    if errors:
        raise RuntimeError(f"bench client failed: {errors[0]}")
    return _stats([x for lane, _ in outs for x in lane], elapsed,
                  request_rows, client_procs * conns_per_proc, "tcp")


def bench(quick: bool = False, *, max_batch: int = 64,
          num_nodes: int = 2) -> dict:
    from tensorflowonspark_tpu import cluster as tcluster
    from tensorflowonspark_tpu import serving
    from tensorflowonspark_tpu.checkpoint import export_bundle
    from tensorflowonspark_tpu.models import linear as linmod

    feature_dim = 16
    duration = 2.0 if quick else 8.0
    config = {"model": "linear", "in_dim": feature_dim,
              "out_dim": feature_dim}
    results: dict = {"max_batch": max_batch, "num_nodes": num_nodes,
                     "configs": {}}
    with tempfile.TemporaryDirectory() as tmp:
        export = os.path.join(tmp, "bundle")
        export_bundle(export, linmod.init_params(config, scale=2.0), config)
        cluster = tcluster.run(
            serving.serving_loop,
            {"export_dir": export, "max_batch": max_batch},
            num_executors=num_nodes,
            input_mode=tcluster.InputMode.STREAMING,
            heartbeat_interval=1.0,
            reservation_timeout=120.0,
        )
        try:
            gateway = cluster.serve(export, max_batch=max_batch,
                                    max_delay_ms=5.0, queue_limit=1024,
                                    listen_host="127.0.0.1",
                                    reload_poll_secs=0)
            # warmup: compile both replicas' jitted apply outside the clock
            run_inprocess(gateway, request_rows=max_batch,
                          feature_dim=feature_dim, clients=num_nodes,
                          duration=1.0)
            results["configs"]["1row"] = run_inprocess(
                gateway, request_rows=1, feature_dim=feature_dim,
                clients=8 if quick else 24, duration=duration)
            results["configs"]["1row_tcp"] = run_tcp(
                cluster, gateway, request_rows=1, feature_dim=feature_dim,
                client_procs=2, conns_per_proc=4 if quick else 16,
                duration=duration)
            results["configs"]["64row_tcp"] = run_tcp(
                cluster, gateway, request_rows=max_batch,
                feature_dim=feature_dim, client_procs=2,
                conns_per_proc=1 if quick else 4, duration=duration)
        finally:
            cluster.shutdown(timeout=120.0)
    return results


def markdown_table(results: dict) -> str:
    lines = [f"### serving gateway ({results['num_nodes']} nodes, "
             f"max_batch={results['max_batch']}, loopback)",
             "| config | transport | clients | qps | rows/s | p50 ms | "
             "p99 ms | mean ms |",
             "|---|---|---|---|---|---|---|---|"]
    for label, r in results["configs"].items():
        lines.append(
            f"| {label} | {r['transport']} | {r['clients']} | "
            f"{r['qps']:,.0f} | {r['rows_per_s']:,.0f} | {r['p50_ms']} | "
            f"{r['p99_ms']} | {r['mean_ms']} |")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="short duration / few clients (smoke test)")
    ap.add_argument("--json", default="",
                    help="also write the raw results to this JSON file")
    args = ap.parse_args(argv)
    results = bench(quick=args.quick)
    print(markdown_table(results))
    one = results["configs"]["1row"]
    gate = (one["qps"] >= 500.0
            and one["p99_ms"] <= 5.0 * one["p50_ms"])
    print(f"acceptance (1row: >=500 qps, p99 <= 5x p50): "
          f"{'PASS' if gate else 'MISS'} "
          f"({one['qps']} qps, p99/p50 = {one['p99_ms'] / one['p50_ms']:.2f})")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
        print(f"raw results -> {args.json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
