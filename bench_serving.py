"""Online-serving microbench: closed-loop clients vs the gateway, CPU-side.

Measures the request/response path on one box: a real 2-node cluster runs
``serving_loop`` over a tiny linear bundle and C clients hammer the
gateway for a fixed duration.  Reported per config: sustained qps,
p50/p99/mean request latency, row throughput.

Configs, all against one ``max_batch=64`` gateway:

- ``1row`` — 1-row requests through the native ``gateway.predict`` API
  (in-process client threads).  This is the **in-process capacity**
  number: admission → micro-batching → routing → node round → scatter,
  no wire.
- ``1row_tcp`` / ``64row_tcp`` — closed-loop (one request in flight per
  connection) through the TCP reactor endpoint, client processes +
  ``GatewayClient`` connections.  The pre-pipelining shape: each request
  pays a full round-trip.
- ``1row_tcp_pipe`` / ``64row_tcp_pipe`` — **pipelined** TCP: each
  connection keeps ``depth`` requests outstanding (``predict_async``),
  replies resolved by id out of order.  ``1row_tcp_pipe`` is the ISSUE 7
  acceptance config.
- ``1row_tcp_pool`` — a ``GatewayClientPool`` shared by closed-loop
  caller threads: T callers multiplexed over ``size`` pipelined sockets.

Acceptance gate (ISSUE 7 / BENCH_r09): ``1row_tcp_pipe`` qps >= 0.8x the
``1row`` in-process qps measured in the SAME run, with p99 <= 5x p50.
(The ISSUE 5 gate — in-process >= 500 qps, p99 <= 5x p50 — still prints.)

Usage::

    python bench_serving.py                  # full table, markdown + JSON
    python bench_serving.py --quick          # small sizes (CI smoke)
    python bench_serving.py --json out.json

Run on an otherwise idle box.
"""

from __future__ import annotations

import argparse
import collections
import json
import multiprocessing as mp
import os
import tempfile
import threading
import time


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _stats(lats: list[float], elapsed: float, request_rows: int,
           clients: int, transport: str) -> dict:
    lats = sorted(lats)
    n = len(lats)
    if not n:
        raise RuntimeError("no requests completed")
    return {
        "transport": transport,
        "request_rows": request_rows,
        "clients": clients,
        "duration_s": round(elapsed, 2),
        "requests": n,
        "qps": round(n / elapsed, 1),
        "rows_per_s": round(n * request_rows / elapsed, 1),
        "p50_ms": round(_percentile(lats, 0.50) * 1e3, 2),
        "p99_ms": round(_percentile(lats, 0.99) * 1e3, 2),
        "mean_ms": round(sum(lats) / n * 1e3, 2),
    }


# -- in-process closed loop (gateway capacity) --------------------------------


def run_inprocess(gateway, *, request_rows: int, feature_dim: int,
                  clients: int, duration: float) -> dict:
    import numpy as np

    rows = [np.arange(feature_dim, dtype=np.float32) + i
            for i in range(request_rows)]
    per_client: list[list[float]] = [[] for _ in range(clients)]
    errors: list[str] = []

    def _loop(mine: list[float]) -> None:
        try:
            deadline = time.perf_counter() + duration
            while time.perf_counter() < deadline:
                t0 = time.perf_counter()
                gateway.predict(rows, timeout=30.0)
                mine.append(time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=_loop, args=(per_client[i],))
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    if errors:
        raise RuntimeError(f"bench client failed: {errors[0]}")
    return _stats([x for lane in per_client for x in lane], elapsed,
                  request_rows, clients, "inprocess")


# -- TCP client loops (client processes) --------------------------------------


def _closed_loop(endpoint, authkey, request_rows: int, feature_dim: int,
                 duration: float, latencies: list[float],
                 errors: list[str]) -> None:
    import numpy as np

    from tensorflowonspark_tpu.serving import GatewayClient

    rows = [np.arange(feature_dim, dtype=np.float32) + i
            for i in range(request_rows)]
    client = GatewayClient(endpoint[0], endpoint[1], authkey)
    mine: list[float] = []
    try:
        deadline = time.perf_counter() + duration
        while time.perf_counter() < deadline:
            t0 = time.perf_counter()
            out = client.predict(rows, timeout=30.0)
            mine.append(time.perf_counter() - t0)
            if len(out) != request_rows:
                errors.append(f"short reply: {len(out)}/{request_rows}")
                return
    except Exception as e:  # noqa: BLE001 - surfaced by the caller
        errors.append(f"{type(e).__name__}: {e}")
    finally:
        latencies.extend(mine)  # one append per client: no lock needed
        try:
            client.close()
        except OSError:  # toslint: allow-silent(bench teardown; the gateway may already be closing)
            pass


def _pipelined_loop(endpoint, authkey, request_rows: int, feature_dim: int,
                    depth: int, duration: float, latencies: list[float],
                    errors: list[str]) -> None:
    """One connection, ``depth`` requests outstanding at all times: fill
    the window with ``predict_async``, then retire the oldest future and
    send a replacement — latency is submit→resolve per request."""
    import numpy as np

    from tensorflowonspark_tpu.serving import GatewayClient

    rows = [np.arange(feature_dim, dtype=np.float32) + i
            for i in range(request_rows)]
    client = GatewayClient(endpoint[0], endpoint[1], authkey)
    mine: list[float] = []
    inflight: collections.deque = collections.deque()
    try:
        deadline = time.perf_counter() + duration
        while time.perf_counter() < deadline:
            while len(inflight) < depth:
                inflight.append((time.perf_counter(),
                                 client.predict_async(rows, timeout=30.0)))
            t0, fut = inflight.popleft()
            out = fut.result()
            mine.append(time.perf_counter() - t0)
            if len(out) != request_rows:
                errors.append(f"short reply: {len(out)}/{request_rows}")
                return
        while inflight:  # drain the window inside the measurement
            t0, fut = inflight.popleft()
            fut.result()
            mine.append(time.perf_counter() - t0)
    except Exception as e:  # noqa: BLE001 - surfaced by the caller
        errors.append(f"{type(e).__name__}: {e}")
    finally:
        latencies.extend(mine)
        try:
            client.close()
        except OSError:  # toslint: allow-silent(bench teardown; the gateway may already be closing)
            pass


def _pooled_loop(pool, request_rows: int, feature_dim: int, duration: float,
                 latencies: list[float], errors: list[str]) -> None:
    """One closed-loop caller THREAD over a shared GatewayClientPool."""
    import numpy as np

    rows = [np.arange(feature_dim, dtype=np.float32) + i
            for i in range(request_rows)]
    mine: list[float] = []
    try:
        deadline = time.perf_counter() + duration
        while time.perf_counter() < deadline:
            t0 = time.perf_counter()
            out = pool.predict(rows, timeout=30.0)
            mine.append(time.perf_counter() - t0)
            if len(out) != request_rows:
                errors.append(f"short reply: {len(out)}/{request_rows}")
                return
    except Exception as e:  # noqa: BLE001 - surfaced by the caller
        errors.append(f"{type(e).__name__}: {e}")
    finally:
        latencies.extend(mine)


def _client_proc_main(conn, endpoint, authkey, request_rows: int,
                      feature_dim: int, conns: int, duration: float,
                      mode: str, depth: int, pool_callers: int) -> None:
    """Child process: ``conns`` connections in the given mode, latencies
    piped back.  TCP clients live OUTSIDE the driver process — in-process
    client threads would share the gateway's GIL, so the wire numbers
    would measure the interpreter, not the endpoint."""
    import sys

    # caller + receiver threads hand off per reply; the 5ms default GIL
    # switch interval turns that into the client's own latency floor
    sys.setswitchinterval(0.001)
    errors: list[str] = []
    if mode == "pool":
        from tensorflowonspark_tpu.serving import GatewayClientPool

        pool = GatewayClientPool(endpoint[0], endpoint[1], authkey,
                                 size=conns)
        per_lane: list[list[float]] = [[] for _ in range(pool_callers)]
        threads = [
            threading.Thread(target=_pooled_loop,
                             args=(pool, request_rows, feature_dim,
                                   duration, per_lane[i], errors))
            for i in range(pool_callers)
        ]
    else:
        per_lane = [[] for _ in range(conns)]
        threads = [
            threading.Thread(
                target=_pipelined_loop if mode == "pipe" else _closed_loop,
                args=((endpoint, authkey, request_rows, feature_dim, depth,
                       duration, per_lane[i], errors) if mode == "pipe"
                      else (endpoint, authkey, request_rows, feature_dim,
                            duration, per_lane[i], errors)))
            for i in range(conns)
        ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if mode == "pool":
        pool.close()
    conn.send(([x for lane in per_lane for x in lane], errors))


def run_tcp(cluster, gateway, *, request_rows: int, feature_dim: int,
            client_procs: int, conns_per_proc: int, duration: float,
            mode: str = "closed", depth: int = 1,
            pool_callers: int = 0) -> dict:
    """One run against the gateway's TCP endpoint.

    ``mode``: "closed" (one request in flight per connection), "pipe"
    (``depth`` requests outstanding per connection), or "pool"
    (``pool_callers`` closed-loop threads sharing ``conns_per_proc``
    pooled pipelined connections per process).
    """
    ctx = mp.get_context("fork")
    procs, pipes = [], []
    for _ in range(client_procs):
        parent, child = ctx.Pipe()
        p = ctx.Process(target=_client_proc_main,
                        args=(child, gateway.endpoint, cluster.authkey,
                              request_rows, feature_dim, conns_per_proc,
                              duration, mode, depth, pool_callers),
                        daemon=True)
        p.start()
        procs.append(p)
        pipes.append(parent)
    t0 = time.perf_counter()
    outs = [pipe.recv() for pipe in pipes]
    elapsed = time.perf_counter() - t0
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    errors = [e for _, errs in outs for e in errs]
    if errors:
        raise RuntimeError(f"bench client failed: {errors[0]}")
    transport = {"closed": "tcp", "pipe": f"tcp pipe={depth}",
                 "pool": "tcp pool"}[mode]
    clients = client_procs * (pool_callers if mode == "pool"
                              else conns_per_proc)
    return _stats([x for lane, _ in outs for x in lane], elapsed,
                  request_rows, clients, transport)


# -- tracing: per-stage breakdown + off-vs-on overhead ------------------------


def run_trace_compare(gateway, *, request_rows: int, feature_dim: int,
                      clients: int, duration: float, rounds: int = 3) -> dict:
    """Interleaved TOS_TRACE off/on pairs (the BENCH_r06 --metrics-compare
    methodology: alternating cells cancel box drift that separate phases
    absorb), best-of-N each side.  "On" is the documented production shape
    (sample=0.01); the acceptance bar is the DISABLED path, which runs
    strictly less code than "on", so an on-overhead below the 3% noise bar
    bounds it from above."""
    from tensorflowonspark_tpu.telemetry import trace as ttrace

    offs: list[float] = []
    ons: list[float] = []
    try:
        for _ in range(rounds):
            ttrace.reset(enabled=False)
            offs.append(run_inprocess(
                gateway, request_rows=request_rows, feature_dim=feature_dim,
                clients=clients, duration=duration)["qps"])
            ttrace.reset(enabled=True, sample=0.01)
            ons.append(run_inprocess(
                gateway, request_rows=request_rows, feature_dim=feature_dim,
                clients=clients, duration=duration)["qps"])
    finally:
        ttrace.reset()
    best_off, best_on = max(offs), max(ons)
    return {"qps_off": offs, "qps_on": ons,
            "best_off": best_off, "best_on": best_on,
            "on_overhead_pct": round((best_off - best_on) / best_off * 100, 2)}


def run_witness_compare(gateway, *, request_rows: int, feature_dim: int,
                        clients: int, duration: float,
                        rounds: int = 3) -> dict:
    """Interleaved TOS_LOCK_WITNESS off/on pairs (the run_trace_compare
    methodology: alternating cells cancel box drift), best-of-N each side.
    The off cells measure the production shape — a TosLock with the
    witness disarmed is one attribute check over the raw primitive, so the
    off-path's own overhead is structural, not separately measurable here;
    the on cells carry the full held-set/order-graph/hold-histogram
    machinery on every serving-path acquire (batcher cond, router cond,
    gateway locks)."""
    from tensorflowonspark_tpu.utils import locks

    prev = locks.get_witness()
    offs: list[float] = []
    ons: list[float] = []
    try:
        for _ in range(rounds):
            locks.disable_witness()
            offs.append(run_inprocess(
                gateway, request_rows=request_rows, feature_dim=feature_dim,
                clients=clients, duration=duration)["qps"])
            locks.enable_witness(mode="raise")
            ons.append(run_inprocess(
                gateway, request_rows=request_rows, feature_dim=feature_dim,
                clients=clients, duration=duration)["qps"])
    finally:
        if prev is not None:
            locks.enable_witness(mode=prev.mode)
        else:
            locks.disable_witness()
    best_off, best_on = max(offs), max(ons)
    return {"qps_off": offs, "qps_on": ons,
            "best_off": best_off, "best_on": best_on,
            # off-cell spread = the box's noise floor for this workload
            "off_noise_pct": round((best_off - min(offs)) / best_off * 100, 2),
            "on_overhead_pct": round((best_off - best_on) / best_off * 100, 2)}


def bench_r18(quick: bool = False, *, max_batch: int = 64,
              num_nodes: int = 2) -> dict:
    """--scenario r18: lock-witness overhead (ISSUE 17) — one serving
    cluster, interleaved witness off/on cells over the full
    gateway->batcher->router->node predict path."""
    from tensorflowonspark_tpu import cluster as tcluster
    from tensorflowonspark_tpu import serving
    from tensorflowonspark_tpu.checkpoint import export_bundle
    from tensorflowonspark_tpu.models import linear as linmod

    feature_dim = 16
    duration = 1.5 if quick else 5.0
    results: dict = {"scenario": "r18", "max_batch": max_batch,
                     "num_nodes": num_nodes}
    config = {"model": "linear", "in_dim": feature_dim,
              "out_dim": feature_dim}
    with tempfile.TemporaryDirectory() as tmp:
        export = os.path.join(tmp, "bundle")
        export_bundle(export, linmod.init_params(config, scale=2.0), config)
        cluster = tcluster.run(
            serving.serving_loop,
            {"export_dir": export, "max_batch": max_batch},
            num_executors=num_nodes,
            input_mode=tcluster.InputMode.STREAMING,
            heartbeat_interval=0.5,
            reservation_timeout=120.0,
        )
        try:
            gateway = cluster.serve(export, max_batch=max_batch,
                                    max_delay_ms=5.0, queue_limit=1024,
                                    listen_host="127.0.0.1",
                                    reload_poll_secs=0)
            run_inprocess(gateway, request_rows=max_batch,
                          feature_dim=feature_dim, clients=num_nodes,
                          duration=1.0)  # warmup: compile both replicas
            results["compare"] = run_witness_compare(
                gateway, request_rows=1, feature_dim=feature_dim,
                clients=4 if quick else 16, duration=duration,
                rounds=2 if quick else 3)
        finally:
            cluster.shutdown(timeout=120.0)
    return results


def r18_table(results: dict) -> str:
    c = results["compare"]
    lines = ["| cell | qps (per round) | best |",
             "|---|---|---|"]
    lines.append("| witness off | " + ", ".join(f"{q:.0f}" for q in c["qps_off"])
                 + f" | {c['best_off']:.0f} |")
    lines.append("| witness on | " + ", ".join(f"{q:.0f}" for q in c["qps_on"])
                 + f" | {c['best_on']:.0f} |")
    lines.append(f"\nwitness-on overhead: {c['on_overhead_pct']:+.2f}% "
                 f"(off-cell noise floor {c['off_noise_pct']:.2f}%)")
    return "\n".join(lines)


_STAGE_SPANS = ("serve.request", "serve.admission", "serve.batch_fill",
                "serve.wire", "serve.node_round", "serve.node_compute",
                "serve.reply", "feed.partition_consume")


def run_trace_breakdown(cluster, gateway, *, request_rows: int,
                        feature_dim: int, clients: int,
                        duration: float) -> dict:
    """One fully-sampled run (sample=1.0), then per-stage p50/p99 from the
    assembled spans — driver stages from this process's tracer, node stages
    from the streams the nodes shipped home on heartbeats.  The wire-only
    row subtracts each round's node-side time from its driver-side wire
    span (matched by trace id).  Percentiles come from the bounded recent
    window the rings hold — a sampled view, which is the point."""
    from tensorflowonspark_tpu.telemetry import trace as ttrace

    ttrace.reset(enabled=True, sample=1.0)
    # phase isolation: the compare phase's sampled node spans are already
    # in the coordinator store and would skew this load shape's percentiles
    cluster.coordinator.clear_trace_streams()
    try:
        load = run_inprocess(gateway, request_rows=request_rows,
                             feature_dim=feature_dim, clients=clients,
                             duration=duration)
        if gateway.endpoint is not None:
            # a short wire burst so the reply stage (resolved -> frame
            # queued on the reactor; wire requests only) has samples too
            import numpy as np

            from tensorflowonspark_tpu.serving import GatewayClient

            client = GatewayClient(gateway.endpoint[0], gateway.endpoint[1],
                                   cluster.authkey)
            try:
                rows = [np.arange(feature_dim, dtype=np.float32)]
                for _ in range(100):
                    client.predict(rows, timeout=30.0)
            finally:
                client.close()
        time.sleep(1.5)  # two heartbeats: node spans ship home
        streams = cluster.coordinator.trace_streams()
    finally:
        ttrace.reset()
    spans = [s for stream in streams.values()
             for s in stream.get("spans") or ()]
    durs: dict[str, list[float]] = {}
    for s in spans:
        durs.setdefault(s["n"], []).append(float(s["d"]))
    node_rounds = {s["t"]: float(s["d"]) for s in spans
                   if s["n"] == "serve.node_round"}
    wire_net = [float(s["d"]) - node_rounds[s["t"]] for s in spans
                if s["n"] == "serve.wire" and s["t"] in node_rounds
                and float(s["d"]) >= node_rounds[s["t"]]]
    if wire_net:
        durs["wire.transport_only"] = wire_net
    stages: dict[str, dict] = {}
    for name in (*_STAGE_SPANS, "wire.transport_only"):
        vals = sorted(durs.get(name, ()))
        if not vals:
            continue
        stages[name] = {
            "n": len(vals),
            "p50_ms": round(_percentile(vals, 0.50) * 1e3, 3),
            "p99_ms": round(_percentile(vals, 0.99) * 1e3, 3),
        }
    return {"load": load, "stages": stages}


def trace_table(results: dict) -> str:
    lines = ["### per-stage breakdown (sampled run, driver+node spans)",
             "| stage | n | p50 ms | p99 ms |", "|---|---|---|---|"]
    for name, s in results["breakdown"]["stages"].items():
        lines.append(f"| {name} | {s['n']} | {s['p50_ms']} | {s['p99_ms']} |")
    cmp_ = results["compare"]
    lines.append("")
    lines.append(f"off-vs-on (interleaved best-of-{len(cmp_['qps_off'])}): "
                 f"{cmp_['best_off']:,.0f} qps off vs {cmp_['best_on']:,.0f} "
                 f"qps on (sample=0.01) = {cmp_['on_overhead_pct']:+.2f}% "
                 "overhead")
    return "\n".join(lines)


def bench_trace(quick: bool = False, *, max_batch: int = 64,
                num_nodes: int = 2) -> dict:
    """--trace-breakdown entry: one cluster, an interleaved off/on overhead
    compare, then a fully-sampled per-stage breakdown run (BENCH_r10)."""
    from tensorflowonspark_tpu import cluster as tcluster
    from tensorflowonspark_tpu import serving
    from tensorflowonspark_tpu.checkpoint import export_bundle
    from tensorflowonspark_tpu.models import linear as linmod

    feature_dim = 16
    duration = 1.5 if quick else 5.0
    config = {"model": "linear", "in_dim": feature_dim,
              "out_dim": feature_dim}
    results: dict = {"max_batch": max_batch, "num_nodes": num_nodes,
                     "mode": "trace-breakdown"}
    with tempfile.TemporaryDirectory() as tmp:
        export = os.path.join(tmp, "bundle")
        export_bundle(export, linmod.init_params(config, scale=2.0), config)
        cluster = tcluster.run(
            serving.serving_loop,
            {"export_dir": export, "max_batch": max_batch},
            num_executors=num_nodes,
            input_mode=tcluster.InputMode.STREAMING,
            heartbeat_interval=0.5,
            reservation_timeout=120.0,
            # node-side tracing armed; it records ONLY for rounds whose
            # driver batch was sampled, so the off cells cost nothing
            env={"TOS_TRACE": "1", "TOS_TRACE_SAMPLE": "1"},
        )
        try:
            gateway = cluster.serve(export, max_batch=max_batch,
                                    max_delay_ms=5.0, queue_limit=1024,
                                    listen_host="127.0.0.1",
                                    reload_poll_secs=0)
            run_inprocess(gateway, request_rows=max_batch,
                          feature_dim=feature_dim, clients=num_nodes,
                          duration=1.0)  # warmup: compile both replicas
            results["compare"] = run_trace_compare(
                gateway, request_rows=1, feature_dim=feature_dim,
                clients=4 if quick else 16, duration=duration,
                rounds=2 if quick else 3)
            results["breakdown"] = run_trace_breakdown(
                cluster, gateway, request_rows=1, feature_dim=feature_dim,
                clients=4 if quick else 8, duration=duration)
        finally:
            cluster.shutdown(timeout=120.0)
    return results


def _tenant_loop(gateway, tenant: str, rows, duration: float,
                 latencies: list[float], shed: list[float],
                 errors: list[str], pace: float) -> None:
    """One closed-loop in-process caller for one tenant; ``ServeThrottled``
    (the per-tenant 429) lands in ``shed`` and the client backs off 1ms —
    the documented retry contract, and it keeps the fairness numbers about
    the queues instead of a rejected caller busy-spinning the driver's one
    core.  Anything else is a failure."""
    from tensorflowonspark_tpu.serving import ServeThrottled

    deadline = time.perf_counter() + duration
    while time.perf_counter() < deadline:
        t0 = time.perf_counter()
        try:
            gateway.predict(rows, timeout=30.0, tenant=tenant)
            latencies.append(time.perf_counter() - t0)
        except ServeThrottled:
            shed.append(time.perf_counter() - t0)
            time.sleep(0.001)
        except Exception as e:  # noqa: BLE001 - surfaced by the caller
            errors.append(f"{tenant}: {type(e).__name__}: {e}")
            return
        if pace:
            time.sleep(pace)


def _run_tenants(gateway, specs, duration: float) -> dict:
    """Drive every (tenant, rows, pace) spec concurrently; per-tenant
    answered/shed counts + latency percentiles."""
    lanes = {t: ([], [], []) for t, _, _ in specs}  # lat, shed, errors
    threads = [threading.Thread(target=_tenant_loop,
                                args=(gateway, t, rows, duration,
                                      *lanes[t], pace))
               for t, rows, pace in specs]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    out = {}
    for t, (lat, shed, errors) in lanes.items():
        if errors:
            raise RuntimeError(f"bench tenant failed: {errors[0]}")
        vals = sorted(lat)
        total = len(lat) + len(shed)
        out[t] = {
            "requests": total,
            "answered": len(lat),
            "shed": len(shed),
            "shed_pct": round(len(shed) / total * 100, 1) if total else 0.0,
            "p50_ms": round(_percentile(vals, 0.50) * 1e3, 2),
            "p99_ms": round(_percentile(vals, 0.99) * 1e3, 2),
        }
    return out


def bench_r17(quick: bool = False, *, num_nodes: int = 2) -> dict:
    """--scenario r17: safe-rollout robustness (ISSUE 16), three phases on
    one cluster.

    1. **baseline** — tenants ``a``/``b`` uncontended closed-loop 1-row
       traffic (their own p99 floor for the fairness compare);
    2. **hot flood** — tenant ``hot`` drives 16-row requests with every
       token-bucket charge amplified 10x (the ``hot_tenant`` chaos hook),
       i.e. a sustained 10x-over-budget flood, while a/b keep their pace.
       Headline: a/b p99 under the flood vs phase 1, hot's shed rate;
    3. **canary swap mid-burst** — with the flood still running, a
       candidate bundle staged with ``bad_model`` NaN corruption rolls
       out to half the fleet (shadow mirroring on); the governor detects
       the regression and rolls the canaries back.  Headline:
       detection->restored latency (``rollback_secs``) and zero
       non-throttle request errors across all three phases.

    Same-run interleaving (flood + rollout share the burst) is the
    methodology on this box: separate phases would absorb drift.
    """
    from tensorflowonspark_tpu import cluster as tcluster
    from tensorflowonspark_tpu import faultinject, serving, telemetry
    from tensorflowonspark_tpu.checkpoint import export_bundle
    from tensorflowonspark_tpu.models import linear as linmod

    import numpy as np

    feature_dim = 8
    duration = 2.0 if quick else 6.0
    rate = 400.0
    config = {"model": "linear", "in_dim": feature_dim,
              "out_dim": feature_dim}
    results: dict = {"scenario": "r17", "num_nodes": num_nodes,
                     "tenant_rate_rows_per_s": rate, "hot_charge_mult": 10,
                     "duration_s": duration}
    telemetry.reset()
    os.environ["TOS_SERVE_TENANT_RATE"] = str(rate)
    # driver-side chaos: amplify the hot tenant's admission charge 10x
    os.environ["TOS_FAULTINJECT"] = "hot_tenant:mult=10,tenant=hot"
    faultinject.init_from_env(force=True)
    with tempfile.TemporaryDirectory() as tmp:
        export = os.path.join(tmp, "bundle")
        candidate = os.path.join(tmp, "candidate")
        export_bundle(export, linmod.init_params(config, scale=2.0), config)
        export_bundle(candidate, linmod.init_params(config, scale=2.0),
                      config)
        cluster = tcluster.run(
            serving.serving_loop,
            {"export_dir": export, "max_batch": 16},
            num_executors=num_nodes,
            input_mode=tcluster.InputMode.STREAMING,
            heartbeat_interval=0.5,
            reservation_timeout=120.0,
            # node-side chaos: candidate bundles emit NaN (fires only once
            # a replica is serving a rollout CANDIDATE — phase 3)
            env={"TOS_FAULTINJECT": "bad_model:nan=1"},
        )
        try:
            gateway = cluster.serve(export, max_batch=16, max_delay_ms=2.0,
                                    queue_limit=256, listen=False,
                                    reload_poll_secs=0)
            one = [np.arange(feature_dim, dtype=np.float32)]
            hot_rows = [np.arange(feature_dim, dtype=np.float32)] * 16
            gateway.predict(one, timeout=30.0)  # warmup: compile replicas
            results["baseline"] = _run_tenants(
                gateway, [("a", one, 0.01), ("b", one, 0.01)], duration)

            flood = [("a", one, 0.01), ("b", one, 0.01),
                     ("hot", hot_rows, 0.0)]
            lanes = {t: ([], [], []) for t, _, _ in flood}
            threads = [threading.Thread(target=_tenant_loop,
                                        args=(gateway, t, rows,
                                              duration + 2.0, *lanes[t],
                                              pace))
                       for t, rows, pace in flood]
            for th in threads:
                th.start()
            time.sleep(1.0)  # the burst is established; swap mid-burst
            t_roll = time.perf_counter()
            gov = gateway.rollout(candidate, canary_pct=50, shadow=True,
                                  window_secs=2.0)
            status = gov.wait(timeout=30.0)
            roll_wall = time.perf_counter() - t_roll
            for th in threads:
                th.join()
            out = {}
            for t, (lat, shed, errors) in lanes.items():
                if errors:
                    raise RuntimeError(f"bench tenant failed: {errors[0]}")
                vals = sorted(lat)
                total = len(lat) + len(shed)
                out[t] = {"requests": total, "answered": len(lat),
                          "shed": len(shed),
                          "shed_pct": round(len(shed) / total * 100, 1)
                          if total else 0.0,
                          "p50_ms": round(_percentile(vals, 0.50) * 1e3, 2),
                          "p99_ms": round(_percentile(vals, 0.99) * 1e3, 2)}
            results["flood"] = out
            gs = gov.status()
            results["rollout"] = {
                "status": status,
                "reason": gov.state.reason,
                "rollback_secs": gs["rollback_secs"],
                "wall_secs": round(roll_wall, 2),
                "shadow_mirrors":
                    telemetry.counter("serve.shadow_mirrors").value(),
                "rollbacks_total":
                    telemetry.counter("serve.rollbacks_total").value(),
            }
        finally:
            cluster.shutdown(timeout=120.0)
            os.environ.pop("TOS_FAULTINJECT", None)
            os.environ.pop("TOS_SERVE_TENANT_RATE", None)
            faultinject.init_from_env(force=True)
    return results


def r17_table(results: dict) -> str:
    lines = [f"### r17: hot-tenant flood + canary swap mid-burst "
             f"({results['num_nodes']} nodes, rate="
             f"{results['tenant_rate_rows_per_s']:g} rows/s/tenant, "
             f"hot charge x{results['hot_charge_mult']})",
             "| tenant | phase | requests | shed % | p50 ms | p99 ms |",
             "|---|---|---|---|---|---|"]
    for phase in ("baseline", "flood"):
        for t, r in sorted(results[phase].items()):
            lines.append(f"| {t} | {phase} | {r['requests']} | "
                         f"{r['shed_pct']} | {r['p50_ms']} | {r['p99_ms']} |")
    ro = results["rollout"]
    lines.append("")
    lines.append(f"rollout mid-burst: {ro['status']} "
                 f"(reason: {ro['reason']}); detection->restored "
                 f"{ro['rollback_secs']:.2f}s, start->resolved "
                 f"{ro['wall_secs']:.2f}s wall, "
                 f"{ro['shadow_mirrors']} shadow mirrors diffed"
                 if ro["rollback_secs"] is not None else
                 f"rollout mid-burst: {ro['status']} (reason: {ro['reason']})")
    return "\n".join(lines)


def bench(quick: bool = False, *, max_batch: int = 64,
          num_nodes: int = 2) -> dict:
    from tensorflowonspark_tpu import cluster as tcluster
    from tensorflowonspark_tpu import serving
    from tensorflowonspark_tpu.checkpoint import export_bundle
    from tensorflowonspark_tpu.models import linear as linmod

    feature_dim = 16
    duration = 2.0 if quick else 8.0
    config = {"model": "linear", "in_dim": feature_dim,
              "out_dim": feature_dim}
    results: dict = {"max_batch": max_batch, "num_nodes": num_nodes,
                     "configs": {}}
    with tempfile.TemporaryDirectory() as tmp:
        export = os.path.join(tmp, "bundle")
        export_bundle(export, linmod.init_params(config, scale=2.0), config)
        cluster = tcluster.run(
            serving.serving_loop,
            {"export_dir": export, "max_batch": max_batch},
            num_executors=num_nodes,
            input_mode=tcluster.InputMode.STREAMING,
            heartbeat_interval=1.0,
            reservation_timeout=120.0,
        )
        try:
            gateway = cluster.serve(export, max_batch=max_batch,
                                    max_delay_ms=5.0, queue_limit=1024,
                                    listen_host="127.0.0.1",
                                    reload_poll_secs=0)
            # warmup: compile both replicas' jitted apply outside the clock
            run_inprocess(gateway, request_rows=max_batch,
                          feature_dim=feature_dim, clients=num_nodes,
                          duration=1.0)
            results["configs"]["1row"] = run_inprocess(
                gateway, request_rows=1, feature_dim=feature_dim,
                clients=8 if quick else 24, duration=duration)
            results["configs"]["1row_tcp"] = run_tcp(
                cluster, gateway, request_rows=1, feature_dim=feature_dim,
                client_procs=2, conns_per_proc=4 if quick else 16,
                duration=duration)
            # pipelined: the reactor's reason to exist — depth requests
            # outstanding per socket, answered out of order by id.  One
            # connection per client process: measured on the 2-core box,
            # several pipelined lanes inside one client process convoy on
            # the client's own GIL and understate the endpoint by 2-4x
            results["configs"]["1row_tcp_pipe"] = run_tcp(
                cluster, gateway, request_rows=1, feature_dim=feature_dim,
                client_procs=2 if quick else 4, conns_per_proc=1,
                duration=duration, mode="pipe", depth=8 if quick else 32)
            results["configs"]["1row_tcp_pool"] = run_tcp(
                cluster, gateway, request_rows=1, feature_dim=feature_dim,
                client_procs=2, conns_per_proc=2,
                duration=duration, mode="pool",
                pool_callers=4 if quick else 16)
            results["configs"]["64row_tcp"] = run_tcp(
                cluster, gateway, request_rows=max_batch,
                feature_dim=feature_dim, client_procs=2,
                conns_per_proc=1 if quick else 4, duration=duration)
            results["configs"]["64row_tcp_pipe"] = run_tcp(
                cluster, gateway, request_rows=max_batch,
                feature_dim=feature_dim, client_procs=2,
                conns_per_proc=1, duration=duration,
                mode="pipe", depth=2 if quick else 4)
        finally:
            cluster.shutdown(timeout=120.0)
    return results


def markdown_table(results: dict) -> str:
    lines = [f"### serving gateway ({results['num_nodes']} nodes, "
             f"max_batch={results['max_batch']}, loopback)",
             "| config | transport | clients | qps | rows/s | p50 ms | "
             "p99 ms | mean ms |",
             "|---|---|---|---|---|---|---|---|"]
    for label, r in results["configs"].items():
        lines.append(
            f"| {label} | {r['transport']} | {r['clients']} | "
            f"{r['qps']:,.0f} | {r['rows_per_s']:,.0f} | {r['p50_ms']} | "
            f"{r['p99_ms']} | {r['mean_ms']} |")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="short duration / few clients (smoke test)")
    ap.add_argument("--json", default="",
                    help="also write the raw results to this JSON file")
    ap.add_argument("--trace-breakdown", action="store_true",
                    help="per-stage p50/p99 from a sampled traced run plus "
                         "an interleaved TOS_TRACE off-vs-on overhead "
                         "compare (BENCH_r10)")
    ap.add_argument("--scenario", default="",
                    help="named robustness scenario: 'r17' = hot-tenant "
                         "flood + canary swap mid-burst with an injected "
                         "regression -> auto-rollback (BENCH_r17); "
                         "'r18' = lock-witness off/on overhead compare "
                         "(BENCH_r18)")
    args = ap.parse_args(argv)
    if args.scenario == "r18":
        results = bench_r18(quick=args.quick)
        print(r18_table(results))
        c = results["compare"]
        # off-path: one attribute check over the raw primitive (witness
        # disarmed) — structurally within noise; measured bar: the FULL
        # witness stays under 10% on the serving hot path
        ok = c["on_overhead_pct"] <= 10.0
        print(f"acceptance r18 (witness-off is a single attribute check — "
              f"within noise by construction; witness-on overhead <= 10%): "
              f"{'PASS' if ok else 'MISS'} ({c['on_overhead_pct']:+.2f}%)")
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=2)
            print(f"raw results -> {args.json}")
        return 0
    if args.scenario:
        if args.scenario != "r17":
            ap.error(f"unknown scenario {args.scenario!r}")
        results = bench_r17(quick=args.quick)
        print(r17_table(results))
        fair_ok = all(
            results["flood"][t]["p99_ms"] <=
            max(2.0 * results["baseline"][t]["p99_ms"],
                results["baseline"][t]["p99_ms"] + 250.0)
            for t in ("a", "b"))
        fair_ok = fair_ok and results["flood"]["hot"]["shed"] > 0 and \
            not results["flood"]["a"]["shed"] and \
            not results["flood"]["b"]["shed"]
        ro = results["rollout"]
        roll_ok = (ro["status"] == "rolled_back"
                   and ro["rollback_secs"] is not None
                   and ro["rollback_secs"] <= 5.0)
        print(f"acceptance r17a (a/b p99 under hot flood <= 2x their "
              f"uncontended p99; only hot shed): "
              f"{'PASS' if fair_ok else 'MISS'}")
        print(f"acceptance r17b (injected regression auto-rolls-back, "
              f"detection->restored <= 5s): "
              f"{'PASS' if roll_ok else 'MISS'} "
              f"({ro['rollback_secs']}s, status={ro['status']})")
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=2)
            print(f"raw results -> {args.json}")
        return 0
    if args.trace_breakdown:
        results = bench_trace(quick=args.quick)
        print(trace_table(results))
        overhead = results["compare"]["on_overhead_pct"]
        ok = abs(overhead) < 3.0
        print(f"acceptance r10 (tracing off-vs-on within the 3% noise bar; "
              f"the default-off path runs strictly less code than 'on'): "
              f"{'PASS' if ok else 'MISS'} ({overhead:+.2f}%)")
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=2)
            print(f"raw results -> {args.json}")
        return 0
    results = bench(quick=args.quick)
    print(markdown_table(results))
    one = results["configs"]["1row"]
    gate5 = (one["qps"] >= 500.0
             and one["p99_ms"] <= 5.0 * one["p50_ms"])
    print(f"acceptance r07 (1row: >=500 qps, p99 <= 5x p50): "
          f"{'PASS' if gate5 else 'MISS'} "
          f"({one['qps']} qps, p99/p50 = {one['p99_ms'] / one['p50_ms']:.2f})")
    pipe = results["configs"]["1row_tcp_pipe"]
    gate7 = (pipe["qps"] >= 0.8 * one["qps"]
             and pipe["p99_ms"] <= 5.0 * pipe["p50_ms"])
    print(f"acceptance r09 (1row_tcp_pipe: >=0.8x in-process qps, "
          f"p99 <= 5x p50): {'PASS' if gate7 else 'MISS'} "
          f"({pipe['qps']} vs {one['qps']} qps = "
          f"{pipe['qps'] / one['qps']:.2f}x, "
          f"p99/p50 = {pipe['p99_ms'] / pipe['p50_ms']:.2f})")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
        print(f"raw results -> {args.json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
