"""toslint core: findings, pragmas, checker registry, tree runner, baseline.

An AST-based, stdlib-only lint framework for *this* codebase's invariants —
the locked/threaded/env-tuned discipline the elastic control and data planes
depend on (see ``tensorflowonspark_tpu/analysis/checkers.py`` for the
checkers themselves).  Modeled on the mechanically-enforced replica/fencing
discipline TF-Replicator credits for its reliability: conventions a reviewer
must remember become conventions a tier-1 test enforces.

Key design points:

- **Stable finding ids, no line numbers.**  A baseline entry must survive
  unrelated edits above it, so ids anchor on (checker, path, enclosing
  qualname, token) with an occurrence counter for exact duplicates — never
  on line numbers.
- **Committed baseline** (``analysis/baseline.json``): grandfathered
  findings are suppressed, anything new fails the gate.  Two checker
  classes (knob-discipline, dial-discipline) are *never* baselined — those
  are fixed outright (``NEVER_BASELINE``).
- **Pragmas**: ``# toslint: allow-silent(<reason>)`` blesses an intentional
  silent except (reason required); ``# toslint: disable=<checker-id>`` is
  the generic same-line suppression.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Iterable, Iterator, Sequence

PRAGMA_RE = re.compile(
    r"#\s*toslint:\s*"
    r"(?:(?P<silent>allow-silent)\((?P<reason>[^)]*)\)"
    r"|(?P<lockorder>allow-lock-order)\((?P<lockreason>[^)]*)\)"
    r"|disable=(?P<ids>[\w,-]+))")

# Checker classes whose findings must be FIXED, never grandfathered: a raw
# env read or raw dial is always a mechanical one-line migration, and a
# lock-order cycle is a latent deadlock — fixed or explained inline with
# `# toslint: allow-lock-order(<why>)`, never waved through.
NEVER_BASELINE = frozenset({"knob-discipline", "dial-discipline",
                            "lock-order"})


@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str  # checker id, e.g. "silent-except"
    path: str  # repo-relative posix path
    line: int  # 1-based line (for humans; never part of the baseline id)
    message: str
    hint: str  # one-line fix hint
    anchor: str  # stable anchor, e.g. "Class.method@token" (baseline id part)


def format_finding(f: Finding) -> str:
    return f"{f.path}:{f.line}: [{f.checker}] {f.message}\n    hint: {f.hint}"


# -- pragmas ------------------------------------------------------------------


class Pragmas:
    """Per-line ``# toslint:`` pragma index for one source file."""

    def __init__(self, lines: Sequence[str]):
        self._silent: dict[int, str] = {}  # line -> reason
        self._disabled: dict[int, set[str]] = {}  # line -> checker ids
        self.lock_order: dict[int, str] = {}  # line -> reason
        for i, text in enumerate(lines, start=1):
            m = PRAGMA_RE.search(text)
            if not m:
                continue
            if m.group("silent"):
                self._silent[i] = (m.group("reason") or "").strip()
            elif m.group("lockorder"):
                self.lock_order[i] = (m.group("lockreason") or "").strip()
            else:
                self._disabled[i] = {s.strip() for s in m.group("ids").split(",") if s.strip()}

    def allow_silent(self, *lines: int) -> bool:
        """True when any of the lines carries allow-silent WITH a reason
        (a reason-less pragma documents nothing and suppresses nothing)."""
        return any(self._silent.get(i) for i in lines)

    def allow_lock_order(self, *lines: int) -> bool:
        """True when any of the lines carries allow-lock-order WITH a
        reason (same rule as allow-silent: no reason, no suppression)."""
        return any(self.lock_order.get(i) for i in lines)

    def disabled(self, line: int, checker_id: str) -> bool:
        ids = self._disabled.get(line)
        return bool(ids) and (checker_id in ids or "all" in ids)


# -- parsed module ------------------------------------------------------------


class ImportMap:
    """Resolve local names to dotted qualnames via the module's imports.

    ``import numpy as np`` makes ``np.random.RandomState`` qualify to
    ``numpy.random.RandomState``; ``from time import monotonic as _m`` makes
    ``_m`` qualify to ``time.monotonic``.  Function-local imports count too
    (this tree imports lazily a lot); collisions across scopes over-approx,
    which is the right bias for a linter.
    """

    def __init__(self, tree: ast.AST):
        self.names: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    root = alias.name.split(".")[0]
                    self.names[alias.asname or root] = alias.name if alias.asname else root
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for alias in node.names:
                    self.names[alias.asname or alias.name] = f"{node.module}.{alias.name}"

    def qualify(self, node: ast.AST) -> str | None:
        """Dotted qualname of a Name/Attribute chain, else None."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(self.names.get(node.id, node.id))
        return ".".join(reversed(parts))


class ModuleSource:
    """One parsed file: source text, AST, pragma index, import map."""

    def __init__(self, path: str, text: str):
        self.path = path.replace("\\", "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=self.path)
        self.pragmas = Pragmas(self.lines)
        self.imports = ImportMap(self.tree)

    @property
    def basename(self) -> str:
        return self.path.rsplit("/", 1)[-1]


# -- checker registry ---------------------------------------------------------


class Checker:
    """Base checker.  One instance lives for a whole ``run_analysis`` pass:
    ``check`` runs per file (and may accumulate state), ``finalize`` runs
    once afterwards for tree-level invariants (e.g. registry/README sync)."""

    id: str = ""
    hint: str = ""

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        raise NotImplementedError

    def finalize(self, project_root: Path | None) -> Iterator[Finding]:
        return iter(())


_REGISTRY: dict[str, type[Checker]] = {}


def register_checker(cls: type[Checker]) -> type[Checker]:
    if not cls.id:
        raise ValueError(f"checker {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate checker id {cls.id!r}")
    _REGISTRY[cls.id] = cls
    return cls


def all_checker_ids() -> list[str]:
    _load_checkers()
    return sorted(_REGISTRY)


def _load_checkers() -> None:
    # registration happens at import; keep it lazy so `core` stays
    # importable from the checkers module itself without a cycle
    from tensorflowonspark_tpu.analysis import checkers  # noqa: F401


def _make_checkers(checker_ids: Iterable[str] | None) -> list[Checker]:
    _load_checkers()
    ids = sorted(_REGISTRY) if checker_ids is None else list(checker_ids)
    unknown = [i for i in ids if i not in _REGISTRY]
    if unknown:
        raise ValueError(f"unknown checker id(s) {unknown}; have {sorted(_REGISTRY)}")
    return [_REGISTRY[i]() for i in ids]


# -- running ------------------------------------------------------------------

_SORT_KEY = lambda f: (f.path, f.line, f.checker, f.anchor, f.message)  # noqa: E731


def _checked(checker: Checker, mod: ModuleSource) -> list[Finding]:
    return [f for f in checker.check(mod)
            if not mod.pragmas.disabled(f.line, f.checker)]


def analyze_source(text: str, path: str,
                   checker_ids: Iterable[str] | None = None) -> list[Finding]:
    """Per-file checks on one in-memory snippet (the unit-test surface).
    Tree-level ``finalize`` checks do not run here."""
    mod = ModuleSource(path, text)
    out: list[Finding] = []
    for checker in _make_checkers(checker_ids):
        out.extend(_checked(checker, mod))
    return sorted(out, key=_SORT_KEY)


def default_package_root() -> Path:
    return Path(__file__).resolve().parent.parent


def iter_package_files(package_root: Path) -> list[Path]:
    return sorted(p for p in package_root.rglob("*.py"))


def run_analysis(package_root: Path | None = None,
                 checker_ids: Iterable[str] | None = None) -> list[Finding]:
    """Run the registered checkers over the whole package tree."""
    package_root = Path(package_root or default_package_root()).resolve()
    project_root = package_root.parent
    checkers = _make_checkers(checker_ids)
    findings: list[Finding] = []
    for path in iter_package_files(package_root):
        rel = path.relative_to(project_root).as_posix()
        # a file that does not parse cannot be vouched for — surface it
        # through the same channel instead of crashing the whole pass
        try:
            mod = ModuleSource(rel, path.read_text(encoding="utf-8"))
        except SyntaxError as e:
            findings.append(Finding("parse-error", rel, e.lineno or 1,
                                    f"file does not parse: {e.msg}",
                                    "fix the syntax error", "<module>@syntax"))
            continue
        for checker in checkers:
            findings.extend(_checked(checker, mod))
    for checker in checkers:
        findings.extend(checker.finalize(project_root))
    return sorted(findings, key=_SORT_KEY)


# -- baseline -----------------------------------------------------------------


def finding_ids(findings: Iterable[Finding]) -> list[tuple[Finding, str]]:
    """Pair findings with their stable baseline ids, deterministically.

    Id = ``checker:path:anchor``; exact duplicates (two identical tokens in
    one scope) get ``#2``, ``#3``... in line order, so the id set is stable
    under edits that do not touch the finding's own scope.
    """
    ordered = sorted(findings, key=_SORT_KEY)
    seen: dict[str, int] = {}
    out: list[tuple[Finding, str]] = []
    for f in ordered:
        base = f"{f.checker}:{f.path}:{f.anchor}"
        n = seen.get(base, 0) + 1
        seen[base] = n
        out.append((f, base if n == 1 else f"{base}#{n}"))
    return out


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: Path | None = None) -> set[str]:
    path = Path(path or default_baseline_path())
    if not path.exists():
        return set()
    data = json.loads(path.read_text(encoding="utf-8"))
    return set(data.get("findings", []))


def write_baseline(path: Path, findings: Iterable[Finding],
                   replace_checkers: Iterable[str] | None = None) -> list[Finding]:
    """Write a deterministic baseline (sorted ids, stable formatting).

    ``NEVER_BASELINE`` classes are excluded — they must be fixed, not
    grandfathered — and returned so the caller can keep failing on them.

    ``replace_checkers`` scopes the update to those checker ids: entries of
    OTHER checkers already in the baseline are preserved (a subset run sees
    only the subset's findings; a full replace from it would silently drop
    every other checker's grandfathered entries).
    """
    with_ids = finding_ids(findings)
    refused = [f for f, _ in with_ids if f.checker in NEVER_BASELINE]
    ids = {fid for f, fid in with_ids if f.checker not in NEVER_BASELINE}
    if replace_checkers is not None:
        scoped = set(replace_checkers)
        ids |= {fid for fid in load_baseline(path)
                if fid.split(":", 1)[0] not in scoped}
    payload = {"version": 1, "findings": sorted(ids)}
    Path(path).write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n",
                          encoding="utf-8")
    return refused


def partition_by_baseline(
    findings: Iterable[Finding], baseline: set[str],
) -> tuple[list[Finding], list[Finding], set[str]]:
    """(new findings, suppressed findings, stale baseline ids)."""
    with_ids = finding_ids(findings)
    current_ids = {fid for _, fid in with_ids}
    new = [f for f, fid in with_ids if fid not in baseline]
    suppressed = [f for f, fid in with_ids if fid in baseline]
    stale = baseline - current_ids
    return new, suppressed, stale
