"""The toslint checkers — this codebase's invariants, mechanically enforced.

Eight disciplines, each born from a class of bug the elastic control/data
plane makes likely (see ISSUE 2 / ROADMAP):

- ``knob-discipline``: every ``TOS_*`` env read goes through
  ``utils/envtune`` and is registered in ``utils/knobs.py`` (which the
  README table mirrors) — an undocumented knob is untunable in production.
- ``dial-discipline``: no raw ``socket.create_connection`` outside
  ``utils/net.py`` — a single-shot dial turns every restart window into a
  hard failure; ``connect_with_backoff`` is the one sanctioned dial.
- ``shard-io-discipline``: binary reads of record-shard files are confined
  to ``tfrecord.py``/``ingest/`` — an ad-hoc ``open(shard, 'rb')`` skips
  CRC verification and gzip detection.
- ``lock-discipline``: in the threaded modules, attributes mutated both
  under and outside ``self._lock`` (a data race until proven otherwise),
  and blocking calls made while a lock is held (a convoy/deadlock seed).
- ``reactor-discipline``: in the serving frontend's reactor classes, no
  blocking calls (sleeps, joins, blocking socket loops, lock waits) inside
  the reactor callback scope — one blocking call stalls EVERY connection.
- ``silent-except``: ``except ...: pass`` without a log line or an explicit
  ``# toslint: allow-silent(<reason>)`` pragma — silence is how invariants
  rot.
- ``metrics-discipline``: metric stores are created through the telemetry
  registry, never as ad-hoc module-level dicts of counters — an ad-hoc
  store is invisible to ``cluster.metrics()``/the run report and ignores
  the ``TOS_METRICS`` switch.  Spans follow the same rule: recorded
  through ``telemetry.trace`` with dotted-lowercase names, never buffered
  in module-level span lists.
- ``trace-purity``: no wall-clock reads, ``np.random``, ``os.environ`` or
  global/nonlocal mutation inside ``jax.jit``/``pjit``/``shard_map``-traced
  functions — tracing bakes the first value in forever.

All heuristics are lexical and intra-file by design: cheap enough for
tier-1, no imports of the checked code, false positives go to the committed
baseline (except the two never-baselined classes, which are always fixed).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterator

from tensorflowonspark_tpu.analysis.core import (
    Checker,
    Finding,
    ModuleSource,
    register_checker,
)


def _scoped_walk(node: ast.AST, scope: tuple[str, ...] = ()):
    """Yield (node, enclosing-scope tuple); scope nodes include themselves."""
    for child in ast.iter_child_nodes(node):
        child_scope = scope
        if isinstance(child, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)):
            child_scope = scope + (child.name,)
        yield child, child_scope
        yield from _scoped_walk(child, child_scope)


def _qual(scope: tuple[str, ...]) -> str:
    return ".".join(scope) or "<module>"


def _terminal_name(func: ast.AST) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _module_consts(tree: ast.Module) -> dict[str, str]:
    """Module-level ``NAME = "literal"`` string constants (so the common
    ``ENV_VAR = "TOS_X"`` indirection stays visible to the env checkers)."""
    out: dict[str, str] = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)):
            out[stmt.targets[0].id] = stmt.value.value
    return out


def _literal_str(node: ast.AST | None, consts: dict[str, str]) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


# -- 1. knob discipline -------------------------------------------------------

_ENV_READ_QUALS = frozenset({
    "os.environ.get", "os.getenv", "os.environ.setdefault", "os.environ.pop",
})
_ENV_HELPERS = frozenset({"env_float", "env_int", "env_str", "env_bool"})


@register_checker
class KnobDisciplineChecker(Checker):
    """TOS_* env reads must go through utils/envtune + the knob registry."""

    id = "knob-discipline"
    hint = ("read the knob via utils/envtune (env_float/env_int/env_str/"
            "env_bool) and register it in utils/knobs.py")

    def __init__(self) -> None:
        self._used: set[str] = set()

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        exempt = mod.path.endswith("utils/envtune.py")
        consts = _module_consts(mod.tree)
        from tensorflowonspark_tpu.utils import knobs

        for node, scope in _scoped_walk(mod.tree):
            if isinstance(node, ast.Call):
                fq = mod.imports.qualify(node.func)
                # alias-resolved terminal name: `env_float as _env_float`
                # still counts as the helper it is
                name = (fq.rsplit(".", 1)[-1] if fq
                        else _terminal_name(node.func))
                if fq in _ENV_READ_QUALS and not exempt:
                    knob = _literal_str(node.args[0] if node.args else None, consts)
                    if knob and knob.startswith("TOS_"):
                        yield Finding(
                            self.id, mod.path, node.lineno,
                            f"raw env read of {knob} (via {fq}) bypasses utils/envtune",
                            self.hint, f"{_qual(scope)}@{knob}")
                elif name in _ENV_HELPERS:
                    knob = _literal_str(node.args[0] if node.args else None, consts)
                    if knob is None:
                        yield Finding(
                            self.id, mod.path, node.lineno,
                            f"{name}() knob name is not a resolvable string "
                            "literal; static cross-checks cannot see it",
                            "pass the TOS_* name as a literal (or module "
                            "constant)", f"{_qual(scope)}@<dynamic>")
                    elif knob.startswith("TOS_"):
                        self._used.add(knob)
                        if knob not in knobs.KNOBS:
                            yield Finding(
                                self.id, mod.path, node.lineno,
                                f"knob {knob} is read but not registered in "
                                "utils/knobs.py",
                                "add a Knob(name, kind, default, doc) entry "
                                "and regenerate the README table",
                                f"{_qual(scope)}@{knob}")
            elif (isinstance(node, ast.Subscript)
                  and isinstance(node.ctx, ast.Load) and not exempt):
                if mod.imports.qualify(node.value) == "os.environ":
                    knob = _literal_str(node.slice, consts)
                    if knob and knob.startswith("TOS_"):
                        yield Finding(
                            self.id, mod.path, node.lineno,
                            f"raw env read of {knob} (os.environ[...]) "
                            "bypasses utils/envtune",
                            self.hint, f"{_qual(scope)}@{knob}")

    def finalize(self, project_root: Path | None) -> Iterator[Finding]:
        from tensorflowonspark_tpu.utils import knobs

        for name in sorted(set(knobs.KNOBS) - self._used):
            yield Finding(
                self.id, "tensorflowonspark_tpu/utils/knobs.py", 1,
                f"registered knob {name} is never read through utils/envtune",
                "delete the stale registry entry or wire the read through "
                "envtune", f"<registry>@{name}")
        readme = None if project_root is None else project_root / "README.md"
        if readme is None or not readme.exists():
            return
        lines = readme.read_text(encoding="utf-8").splitlines()
        span = knobs.find_table_block(lines)
        if span is None:
            yield Finding(
                self.id, "README.md", 1,
                "README has no generated knob table "
                f"({knobs.TABLE_BEGIN.split(' ')[0]}... markers missing)",
                "run `python -m tensorflowonspark_tpu.analysis "
                "--write-knob-table`", "<readme>@knob-table")
            return
        begin, end = span
        block = "\n".join(lines[begin + 1:end]).strip()
        if block != knobs.knob_table_markdown().strip():
            yield Finding(
                self.id, "README.md", begin + 1,
                "README knob table is out of sync with utils/knobs.py",
                "run `python -m tensorflowonspark_tpu.analysis "
                "--write-knob-table`", "<readme>@knob-table")


# -- 2. dial discipline -------------------------------------------------------


# The zero-copy socket primitives are easy to get subtly wrong (short
# writes, IOV_MAX, partial recv_into) — they live behind utils/net.py
# helpers (sendmsg_all / recv_exact_into), the framing layer in
# dataserver.py, and the collective peer transport built on that layer.
_ZEROCOPY_IO_NAMES = frozenset({"sendmsg", "recv_into"})
_ZEROCOPY_IO_ALLOWED = ("utils/net.py", "dataserver.py",
                        "collective/transport.py")
# Collective peer sockets (dials AND listeners) are confined to
# collective/transport.py: a peer channel outside it would sidestep the
# generation stamping / broken-connection abort cascade that makes a ring
# death recoverable — group.py/ops.py speak in ranks and tags only.
_COLLECTIVE_SOCKET_CALLS = frozenset({
    "connect_with_backoff", "bound_socket", "create_connection", "socket",
})
_COLLECTIVE_TRANSPORT = "collective/transport.py"
# Ingest-worker peer channels (the data-service tier's worker->trainer
# chunk streams) are confined to the existing transport homes: ingest/
# modules must speak through dataserver.DataClient/DataServer (authkey
# handshake, v2/v3 framing, ring upgrade, poison-on-failure) — an ad-hoc
# socket there would bypass authentication AND the at-least-once failure
# contract the forwarder's re-route path implements.
_INGEST_SOCKET_CALLS = _COLLECTIVE_SOCKET_CALLS


@register_checker
class DialDisciplineChecker(Checker):
    """Raw socket dials are forbidden outside utils/net.py; raw zero-copy
    socket I/O (sendmsg/recv_into) is confined to utils/net.py +
    dataserver.py + collective/transport.py; and within ``collective/``,
    peer sockets of ANY kind are confined to transport.py."""

    id = "dial-discipline"
    hint = ("dial via utils.net.connect_with_backoff (bounded retries + "
            "jitter); a one-shot connect fails hard across restart windows")
    collective_hint = ("open/dial collective peer sockets only in "
                       "collective/transport.py — it owns generation "
                       "stamping and the broken-connection abort cascade; "
                       "group.py/ops.py must go through PeerTransport")
    embed_hint = ("the embedding tier has no wire of its own: shard "
                  "exchanges ride the CollectiveGroup sparse ops and "
                  "serving lookups ride the embed data-feed queue pair — "
                  "a raw socket there would bypass generation fencing "
                  "and the authkey handshake")
    ingest_hint = ("ingest-worker peer channels ride dataserver."
                   "DataClient/DataServer (the transport homes): the "
                   "authkey handshake, wire framing, and the forwarder's "
                   "at-least-once re-route all live there — no raw "
                   "sockets in ingest/")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        if mod.path.endswith("utils/net.py"):
            return
        io_exempt = mod.path.endswith(_ZEROCOPY_IO_ALLOWED)
        collective_confined = ("/collective/" in mod.path
                               and not mod.path.endswith(_COLLECTIVE_TRANSPORT))
        ingest_confined = "/ingest/" in mod.path
        embed_confined = "/embedding/" in mod.path
        for node, scope in _scoped_walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fq = mod.imports.qualify(node.func)
            if collective_confined:
                name = (fq.rsplit(".", 1)[-1] if fq
                        else _terminal_name(node.func))
                if name in _COLLECTIVE_SOCKET_CALLS:
                    yield Finding(
                        self.id, mod.path, node.lineno,
                        f"collective peer socket ({name}()) outside "
                        "collective/transport.py bypasses the transport's "
                        "generation fencing and abort cascade",
                        self.collective_hint, f"{_qual(scope)}@{name}")
                    continue
            if embed_confined:
                name = (fq.rsplit(".", 1)[-1] if fq
                        else _terminal_name(node.func))
                if name in _COLLECTIVE_SOCKET_CALLS:
                    yield Finding(
                        self.id, mod.path, node.lineno,
                        f"raw socket ({name}()) in embedding/ — the tier "
                        "rides the collective transport and the embed "
                        "queue pair, never its own connections",
                        self.embed_hint, f"{_qual(scope)}@{name}")
                    continue
            if ingest_confined:
                name = (fq.rsplit(".", 1)[-1] if fq
                        else _terminal_name(node.func))
                if name in _INGEST_SOCKET_CALLS:
                    yield Finding(
                        self.id, mod.path, node.lineno,
                        f"ingest-worker peer socket ({name}()) in ingest/ "
                        "bypasses the data-plane transport homes "
                        "(authkey handshake + at-least-once re-route)",
                        self.ingest_hint, f"{_qual(scope)}@{name}")
                    continue
            if fq == "socket.create_connection":
                yield Finding(
                    self.id, mod.path, node.lineno,
                    "raw socket.create_connection bypasses connect_with_backoff",
                    self.hint, f"{_qual(scope)}@create_connection")
            elif not io_exempt:
                name = _terminal_name(node.func)
                if name in _ZEROCOPY_IO_NAMES:
                    yield Finding(
                        self.id, mod.path, node.lineno,
                        f"raw {name}() outside utils/net.py/dataserver.py/"
                        "collective/transport.py — scatter-gather/"
                        "preallocated-buffer socket I/O must go through the "
                        "shared helpers (short writes, IOV_MAX, partial "
                        "reads are handled there once)",
                        "use utils.net.sendmsg_all / recv_exact_into (or the "
                        "dataserver framing layer)",
                        f"{_qual(scope)}@{name}")


# -- 2b. shard IO discipline --------------------------------------------------

# Record shards carry per-record CRCs and optional whole-stream gzip; the
# ONLY readers that honour both live in tfrecord.py (read_records /
# read_record_spans) and the ingest pipeline built on them.  An ad-hoc
# `open(shard_path, "rb")` elsewhere silently skips CRC verification (and
# misparses gzip shards), so binary opens of shard-looking paths are
# confined.  Heuristic is lexical like the rest of toslint: the filename
# expression's source text mentioning shard/tfrecord/part- is the signal.
_SHARDISH_ARG = re.compile(r"shard|tfrecord|part-", re.IGNORECASE)
_SHARD_OPEN_QUALS = frozenset({"open", "io.open", "gzip.open"})
# View producers over shard buffers: confined tighter than binary opens
# (tfrecord.py + dfutil.py only) because a view carries the zero-copy
# LIFETIME contract — valid until its chunk is released, the whole shard
# buffer pinned while it lives — and an ad-hoc producer hands out views
# that no release/debug machinery tracks.
_SHARD_VIEW_QUALS = frozenset({"memoryview", "mmap.mmap"})


@register_checker
class ShardIODisciplineChecker(Checker):
    """Binary reads of record-shard files are confined to tfrecord.py and
    ingest/ — everything else must go through the verifying codecs.  Raw
    buffer/mmap views of shard data are confined tighter still
    (tfrecord.py/dfutil.py): view producers own the zero-copy lifetime
    contract."""

    id = "shard-io-discipline"
    hint = ("read shards via tfrecord.read_records/read_record_spans (or "
            "the ingest pipeline / dfutil.read_shard) — a raw open() "
            "bypasses CRC verification and gzip detection")
    view_hint = ("produce record views via tfrecord.record_views / "
                 "read_record_spans (or dfutil.decode_span_columns) — "
                 "ad-hoc memoryview/mmap slicing of shard data bypasses "
                 "the zero-copy lifetime contract (views valid only until "
                 "their chunk is released)")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        if mod.path.endswith("tfrecord.py"):
            return
        view_exempt = mod.path.endswith("dfutil.py")
        open_exempt = "/ingest/" in mod.path
        for node, scope in _scoped_walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fq = mod.imports.qualify(node.func)
            if fq in _SHARD_VIEW_QUALS:
                if view_exempt:
                    continue
                call_src = ast.unparse(node)
                if _SHARDISH_ARG.search(call_src):
                    yield Finding(
                        self.id, mod.path, node.lineno,
                        f"raw shard-buffer view ({call_src[:60]}) outside "
                        "tfrecord.py/dfutil.py bypasses the zero-copy "
                        "lifetime contract",
                        self.view_hint, f"{_qual(scope)}@{fq}")
                continue
            if open_exempt:
                continue
            name = fq if fq in _SHARD_OPEN_QUALS else None
            if name is None and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "read_bytes":
                # Path(...).read_bytes() — a binary read by construction
                target_src = ast.unparse(node.func.value)
                if _SHARDISH_ARG.search(target_src):
                    yield Finding(
                        self.id, mod.path, node.lineno,
                        f"raw binary read of a record shard "
                        f"({target_src}.read_bytes()) outside "
                        "tfrecord.py/ingest/ skips CRC verification",
                        self.hint, f"{_qual(scope)}@read_bytes")
                continue
            if name is None:
                continue
            if not self._is_binary_read(node, name):
                continue
            target = node.args[0] if node.args else None
            target_src = ast.unparse(target) if target is not None else ""
            if _SHARDISH_ARG.search(target_src):
                yield Finding(
                    self.id, mod.path, node.lineno,
                    f"raw binary open of a record shard ({name}("
                    f"{target_src}, ...)) outside tfrecord.py/ingest/ "
                    "skips CRC verification",
                    self.hint, f"{_qual(scope)}@{name}")

    @staticmethod
    def _is_binary_read(call: ast.Call, name: str) -> bool:
        """True when the open() mode is a literal binary READ ('rb'...).
        Dynamic (non-literal) modes stay quiet (can't judge without false
        positives) — but an ABSENT mode on ``gzip.open`` counts: its
        default is 'rb', exactly the CRC-bypassing read this checker
        confines.  Writes are the writer's business (RecordWriter owns
        shard writes, but e.g. benchmarks legitimately stage raw files)."""
        mode_node = None
        if len(call.args) >= 2:
            mode_node = call.args[1]
        for kw in call.keywords:
            if kw.arg == "mode":
                mode_node = kw.value
        if mode_node is None:
            return name == "gzip.open"  # plain open() defaults to text 'r'
        if not (isinstance(mode_node, ast.Constant)
                and isinstance(mode_node.value, str)):
            return False
        mode = mode_node.value
        return "b" in mode and not any(c in mode for c in "wax+")


# -- journal-write discipline (ISSUE 13) --------------------------------------

# Journal-ish path expressions: the coordinator's write-ahead journal files
# (coordinator.journal / *.snap) — lexical signal, like the shard heuristic.
_JOURNALISH_ARG = re.compile(r"journal", re.IGNORECASE)
_JOURNAL_OPEN_QUALS = frozenset({"open", "io.open", "os.open"})


@register_checker
class JournalDisciplineChecker(Checker):
    """The write-ahead journal's durability contract lives in ONE module:
    ``journal.py`` owns every ``os.fsync`` call and every journal-file
    open.  An ad-hoc fsync elsewhere is a hidden latency cliff on whatever
    lock its caller holds; an ad-hoc journal-file open bypasses the
    append-ordering / torn-tail / snapshot-atomicity rules recovery
    correctness depends on (replay must be able to trust the file)."""

    id = "journal-discipline"
    hint = ("route durable appends/snapshots through journal.Journal (and "
            "reads through journal.replay) — fsync discipline and journal "
            "file opens are confined to journal.py")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        if mod.path.endswith("/journal.py"):
            return
        for node, scope in _scoped_walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fq = mod.imports.qualify(node.func)
            if fq == "os.fsync":
                yield Finding(
                    self.id, mod.path, node.lineno,
                    "os.fsync outside journal.py: durable-write discipline "
                    "is confined to the journal module",
                    self.hint, f"{_qual(scope)}@os.fsync")
                continue
            if fq not in _JOURNAL_OPEN_QUALS:
                continue
            target = node.args[0] if node.args else None
            target_src = ast.unparse(target) if target is not None else ""
            if _JOURNALISH_ARG.search(target_src):
                yield Finding(
                    self.id, mod.path, node.lineno,
                    f"journal file opened outside journal.py ({fq}("
                    f"{target_src[:60]}, ...)) bypasses the append/replay "
                    "contract",
                    self.hint, f"{_qual(scope)}@{fq}")


# -- 3. lock discipline / race heuristics ------------------------------------

_THREADED_BASENAMES = frozenset({
    "coordinator.py", "cluster.py", "dataserver.py", "supervisor.py",
    "node.py", "feeding.py",
    # the write-ahead journal: appended from handler threads + the stats
    # thread's snapshot fold under its own lock
    "journal.py",
    # the collective layer: dataserver connection threads deliver into the
    # inbox while the comm executor sends and the map_fun thread reforms
    "transport.py", "group.py", "ops.py",
    # the online-serving subsystem is thread-per-replica + flush/watch
    # threads throughout — same race classes, same discipline
    "gateway.py", "batcher.py", "router.py",
    # staged rollouts + tenant fairness: the governor thread shares its
    # sliding windows with router workers (rollout.py), and the tenant
    # queues (tenancy.py) are owned by the batcher under ITS lock — new
    # locked sections added there must keep the same discipline
    "rollout.py", "tenancy.py",
    # the reactor frontend: completion threads hand replies to the reactor
    "frontend.py",
    # the DIRECT-mode ingest pipeline: claimer + reader pool + consumer —
    # and the data-service tier (service.py): reader threads tee into the
    # shared ChunkCache while the forwarder thread serves from it
    "readers.py", "feed.py", "service.py",
    # the autoscaling subsystem: the Autoscaler tick thread (loop.py) races
    # user stop()/report() calls, and the governor (policy.py) is mutated
    # from whatever thread drives decide()
    "loop.py", "policy.py",
    # the sharded-embedding tier: the serving replica's responder thread
    # reads shard rows the reload handler swaps (serve.py), and the table/
    # shard state (table.py, sharding.py) is shared between the train-step
    # thread and checkpoint/restore paths
    "serve.py", "table.py", "sharding.py",
})
_BLOCKING_NAMES = frozenset({
    "recv", "accept", "join", "sleep", "connect_with_backoff",
    # this tree's blocking socket-I/O wrappers (dataserver/coordinator frame
    # helpers + utils.net.recv_exact) — without these the checker would be
    # blind to blocking-under-lock written the idiomatic way here
    "_send", "_recv", "_send_msg", "_recv_msg", "recv_exact",
})
# join() on paths/strings is not the thread join this checker hunts
_SAFE_JOIN_QUALS = frozenset({
    "os.path.join", "posixpath.join", "ntpath.join",
    "os.pathsep.join", "os.sep.join", "os.linesep.join",
})
_LOCKISH_FRAGMENTS = ("lock", "cond", "mutex")


def _is_lockish(expr: ast.AST) -> bool:
    name = _terminal_name(expr)
    return bool(name) and any(s in name.lower() for s in _LOCKISH_FRAGMENTS)


@register_checker
class LockDisciplineChecker(Checker):
    """Race heuristics for the threaded modules: attributes mutated both
    under and outside the instance lock, and blocking calls under a lock."""

    id = "lock-discipline"
    hint = ("hold the lock for every mutation of shared attributes, and "
            "move blocking calls (I/O, sleeps, joins) outside the critical "
            "section")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        if mod.basename not in _THREADED_BASENAMES:
            return
        for node, scope in _scoped_walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(mod, node, scope)

    def _check_class(self, mod: ModuleSource, cls: ast.ClassDef,
                     scope: tuple[str, ...]) -> Iterator[Finding]:
        # attr -> list of (locked, line, method)
        mutations: dict[str, list[tuple[bool, int, str]]] = {}
        blocking: list[tuple[str, int, str]] = []  # (call name, line, method)

        def scan(node: ast.AST, locked: bool, method: str) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = locked or any(_is_lockish(i.context_expr) for i in node.items)
                for item in node.items:
                    scan(item, locked, method)
                for stmt in node.body:
                    scan(stmt, inner, method)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                # a closure runs later, not while this frame holds the lock
                body = node.body if isinstance(node.body, list) else [node.body]
                for stmt in body:
                    scan(stmt, False, method)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)) and not (
                    isinstance(node, ast.AnnAssign) and node.value is None):
                # a bare `self.x: T` annotation writes nothing
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    for attr in self._self_attrs(t):
                        mutations.setdefault(attr, []).append(
                            (locked, node.lineno, method))
            if isinstance(node, ast.Call) and locked:
                name = _terminal_name(node.func)
                if name in _BLOCKING_NAMES and not self._safe_join(mod, node):
                    blocking.append((name, node.lineno, method))
            for child in ast.iter_child_nodes(node):
                scan(child, locked, method)

        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if item.name == "__init__":
                    continue  # construction happens-before publication
                # `*_locked` suffix is this codebase's caller-holds-the-lock
                # contract: the body runs inside the caller's critical
                # section (so its mutations ARE locked, and blocking calls
                # in it ARE blocking-under-lock)
                held = item.name.endswith("_locked")
                for stmt in item.body:
                    scan(stmt, held, item.name)

        qual = _qual(scope)
        for name, line, method in blocking:
            yield Finding(
                self.id, mod.path, line,
                f"blocking call {name}() while holding a lock "
                f"(in {qual}.{method})",
                self.hint, f"{qual}.{method}@block:{name}")
        for attr, sites in sorted(mutations.items()):
            locked_methods = sorted({m for locked, _, m in sites if locked})
            if not locked_methods:
                continue
            for locked, line, method in sites:
                if locked:
                    continue
                yield Finding(
                    self.id, mod.path, line,
                    f"self.{attr} is mutated under the lock elsewhere "
                    f"(e.g. {qual}.{locked_methods[0]}) but without it in "
                    f"{qual}.{method} — racy unless externally serialized",
                    self.hint, f"{qual}.{method}@mixed:{attr}")

    @staticmethod
    def _self_attrs(target: ast.AST) -> list[str]:
        """Attribute names a target mutates on ``self`` (including
        ``self.x[...] = ...`` container writes)."""
        if isinstance(target, (ast.Tuple, ast.List)):
            return [a for t in target.elts for a in LockDisciplineChecker._self_attrs(t)]
        if isinstance(target, ast.Starred):
            return LockDisciplineChecker._self_attrs(target.value)
        node = target
        if isinstance(node, ast.Subscript):
            node = node.value
        if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return [node.attr]
        return []

    @staticmethod
    def _safe_join(mod: ModuleSource, call: ast.Call) -> bool:
        func = call.func
        if not isinstance(func, ast.Attribute) or func.attr != "join":
            return False
        if isinstance(func.value, ast.Constant):  # "".join / b"".join
            return True
        return mod.imports.qualify(func) in _SAFE_JOIN_QUALS


# -- 3b. reactor discipline ---------------------------------------------------

# The serving frontend multiplexes EVERY gateway connection on one reactor
# thread; a single blocking call in its callback scope stalls the whole
# endpoint (every client's p99, not one).  Scope contract, mirrored in
# serving/frontend.py's threading docstring: every method of a ``*Reactor*``
# class runs on (or must be safe on) the reactor thread, EXCEPT ``__init__``
# (pre-publication) and ``stop`` (the caller-thread join point).
_REACTOR_PATH_SUFFIXES = ("serving/frontend.py",)
_REACTOR_EXEMPT_METHODS = frozenset({"__init__", "stop"})
# Calls that block: sleeps/joins, the blocking socket-loop helpers
# (recv_exact*/sendall/sendmsg_all loop until done — the reactor must use
# one-shot recv/sendmsg_some), dials, and lock/event waits.  Non-blocking
# recv/accept/select on the reactor's own non-blocking fds stay legal.
_REACTOR_BLOCKING = frozenset({
    "sleep", "join", "recv_exact", "recv_exact_into", "sendall",
    "sendmsg_all", "connect_with_backoff", "wait", "acquire",
})


@register_checker
class ReactorDisciplineChecker(Checker):
    """No blocking calls inside the serving reactor's callback scope."""

    id = "reactor-discipline"
    hint = ("the reactor thread serves every connection: park partial I/O "
            "on the write queue / decode buffer and let the selector re-arm "
            "it, or hand the work to a completion thread")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        if not mod.path.endswith(_REACTOR_PATH_SUFFIXES):
            return
        for node, scope in _scoped_walk(mod.tree):
            if not (isinstance(node, ast.ClassDef) and "Reactor" in node.name):
                continue
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if item.name in _REACTOR_EXEMPT_METHODS:
                    continue
                # _scoped_walk scopes include the class node itself
                yield from self._scan_method(mod, scope, item)

    def _scan_method(self, mod: ModuleSource, scope: tuple[str, ...],
                     fn: ast.AST) -> Iterator[Finding]:
        qual = f"{_qual(scope)}.{fn.name}"
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal_name(node.func)
            if name not in _REACTOR_BLOCKING:
                continue
            if name == "join" and LockDisciplineChecker._safe_join(mod, node):
                continue
            yield Finding(
                self.id, mod.path, node.lineno,
                f"blocking call {name}() inside reactor callback scope "
                f"({qual}) — it stalls every gateway connection at once",
                self.hint, f"{qual}@block:{name}")


@register_checker
class TimeoutDisciplineChecker(Checker):
    """Every blocking wait in ``collective/`` must be bounded.

    The gray-failure machinery (straggler detection, quorum eviction,
    degraded-world continuation) only works because NO wait in the
    collective layer can exceed one collective timeout: an unbounded
    ``fut.result()``, ``cond.wait()``, or default-budget peer ``recv``
    turns one slow peer into a wedged trainer no eviction can rescue.
    This pins the invariant mechanically: ``result``/``wait`` calls need a
    timeout (keyword or positional), and peer-plane ``recv`` calls must
    pass their budget EXPLICITLY (the ops layer derives per-op deadlines —
    relying on an implicit transport default hides the bound from the
    reader and from this checker alike)."""

    id = "timeout-discipline"
    hint = ("bound the wait: fut.result(timeout=...), cond.wait(secs), "
            "tp.recv(..., timeout=_left(deadline)) — an unbounded block in "
            "collective/ turns one gray peer into an unevictable wedge")

    _WAIT_ATTRS = frozenset({"result", "wait", "recv"})

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        if "/collective/" not in mod.path:
            return
        for node, scope in _scoped_walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr not in self._WAIT_ATTRS:
                continue
            bounded_kw = any(
                kw.arg == "timeout"
                and not (isinstance(kw.value, ast.Constant)
                         and kw.value.value is None)
                for kw in node.keywords)
            if attr == "recv":
                # inbox.recv carries a required positional timeout (5 args);
                # transport-level recv must say its budget out loud
                if bounded_kw or len(node.args) >= 4:
                    continue
            elif bounded_kw or node.args:
                continue
            yield Finding(
                self.id, mod.path, node.lineno,
                f"unbounded blocking {attr}() in the collective layer — a "
                "gray (slow-not-dead) peer wedges this wait past any "
                "eviction",
                self.hint, f"{_qual(scope)}@{attr}")


# -- 4. silent-exception discipline ------------------------------------------


@register_checker
class SilentExceptChecker(Checker):
    """``except ...: pass`` needs a log line or an allow-silent pragma."""

    id = "silent-except"
    hint = ("log the swallow (logger.debug at least, with exc_info where "
            "useful) or annotate `# toslint: allow-silent(<reason>)`")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        for node, scope in _scoped_walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not all(self._is_noop(stmt) for stmt in node.body):
                continue
            if mod.pragmas.allow_silent(node.lineno, node.body[0].lineno):
                continue
            exc = ast.unparse(node.type) if node.type is not None else "<bare>"
            yield Finding(
                self.id, mod.path, node.lineno,
                f"`except {exc}: pass` swallows the error with no trace",
                self.hint, f"{_qual(scope)}@except:{exc}")

    @staticmethod
    def _is_noop(stmt: ast.stmt) -> bool:
        return isinstance(stmt, ast.Pass) or (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)


# -- 5. metrics discipline ----------------------------------------------------

# Names that telegraph "this is a metrics container" — a module-level dict
# of ad-hoc counters is invisible to cluster.metrics()/the run report and
# bypasses the no-op TOS_METRICS switch.
_METRICISH_NAME = re.compile(
    r"(?:^|_)(metrics?|counters?|gauges?|histograms?|stats?|timings?)(?:_|$)",
    re.IGNORECASE)
# container constructors that make a mutable metrics store
_METRIC_CONTAINER_CALLS = frozenset({
    "dict", "defaultdict", "OrderedDict",
})
# Same idea for trace spans: a module-level list/deque of span records
# bypasses telemetry.trace's per-thread rings — it never reaches the
# heartbeat piggyback, the merged trace.json, or the TOS_TRACE switch.
_SPANISH_NAME = re.compile(r"(?:^|_)(spans?|traces?)(?:_|$)", re.IGNORECASE)
_SPAN_CONTAINER_CALLS = _METRIC_CONTAINER_CALLS | frozenset({"list", "deque"})
# Span-name-bearing telemetry.trace entry points: their literal name must be
# dotted lowercase (`layer.what`), matching the metric-name convention, so
# merged traces group by subsystem instead of by whoever spelled it.
_SPAN_RECORD_ATTRS = frozenset({"span", "record_span", "record_child"})
_SPAN_NAME_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")


@register_checker
class MetricsDisciplineChecker(Checker):
    """Metric stores must be created through the telemetry registry
    (``telemetry.counter/gauge/histogram``), not as ad-hoc module-level
    dicts/``collections.Counter``s of counts: an ad-hoc store never reaches
    the heartbeat piggyback, ``cluster.metrics()``, or the run report, and
    ignores the ``TOS_METRICS`` kill switch."""

    id = "metrics-discipline"
    hint = ("create the metric through tensorflowonspark_tpu.telemetry "
            "(counter()/gauge()/histogram()/timed()) so it reaches "
            "cluster.metrics(), the run report, and the TOS_METRICS switch")
    span_hint = ("record spans through tensorflowonspark_tpu.telemetry."
                 "trace (span()/record_span()/record_child()) with a "
                 "dotted-lowercase name (e.g. 'serve.wire') so they reach "
                 "the heartbeat piggyback, trace.json, and the TOS_TRACE "
                 "switch")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        # the registry/tracer implementations are the one sanctioned home
        if "/telemetry/" in mod.path:
            return
        yield from self._check_span_calls(mod)
        for stmt in mod.tree.body:
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if not names:
                continue
            value = stmt.value
            if value is None:
                continue
            if self._is_collections_counter(mod, value):
                yield Finding(
                    self.id, mod.path, stmt.lineno,
                    f"module-level collections.Counter {names[0]!r} is an "
                    "ad-hoc metrics store outside the telemetry registry",
                    self.hint, f"<module>@{names[0]}")
                continue
            if (any(_SPANISH_NAME.search(n) for n in names)
                    and self._is_span_container(mod, value)):
                yield Finding(
                    self.id, mod.path, stmt.lineno,
                    f"module-level span buffer {names[0]!r} bypasses the "
                    "telemetry tracer (invisible to the heartbeat "
                    "piggyback, trace.json, and the TOS_TRACE switch)",
                    self.span_hint, f"<module>@{names[0]}")
                continue
            if not any(_METRICISH_NAME.search(n) for n in names):
                continue
            if self._is_container_literal(mod, value):
                yield Finding(
                    self.id, mod.path, stmt.lineno,
                    f"module-level metrics container {names[0]!r} bypasses "
                    "the telemetry registry (invisible to cluster.metrics() "
                    "and the TOS_METRICS switch)",
                    self.hint, f"<module>@{names[0]}")

    def _check_span_calls(self, mod: ModuleSource) -> Iterator[Finding]:
        """Span names recorded through telemetry.trace must be dotted
        lowercase (``layer.what``) — the metric-name convention, applied to
        spans so merged traces group by subsystem."""
        consts = _module_consts(mod.tree)
        for node, scope in _scoped_walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SPAN_RECORD_ATTRS):
                continue
            if not self._is_tracer_receiver(mod, node.func):
                continue  # e.g. re.Match.span("group") is not our API
            name = _literal_str(node.args[0] if node.args else None, consts)
            if name is None or _SPAN_NAME_RE.match(name):
                continue
            yield Finding(
                self.id, mod.path, node.lineno,
                f"span name {name!r} is not dotted lowercase "
                "(expected e.g. 'serve.wire')",
                self.span_hint, f"{_qual(scope)}@span:{name}")

    @staticmethod
    def _is_tracer_receiver(mod: ModuleSource, func: ast.Attribute) -> bool:
        """True when ``<recv>.span/record_*`` plausibly targets
        telemetry.trace: the imported module (any alias), a Tracer-ish
        local (``tracer``/``ttrace``/``trace``/``tr``), or a
        ``get_tracer()`` call — not every object with a ``.span`` method
        (``re.Match.span`` takes a group, not a span name)."""
        fq = mod.imports.qualify(func)
        if fq and (".telemetry.trace." in fq
                   or fq.startswith("telemetry.trace.")):
            return True
        recv = func.value
        if isinstance(recv, ast.Call):
            return _terminal_name(recv.func) == "get_tracer"
        return _terminal_name(recv) in ("trace", "ttrace", "tracer", "tr")

    @staticmethod
    def _is_span_container(mod: ModuleSource, value: ast.AST) -> bool:
        if isinstance(value, (ast.List, ast.Dict, ast.DictComp, ast.ListComp)):
            return True
        if isinstance(value, ast.Call):
            fq = mod.imports.qualify(value.func)
            name = fq.rsplit(".", 1)[-1] if fq else _terminal_name(value.func)
            return name in _SPAN_CONTAINER_CALLS
        return False

    @staticmethod
    def _is_collections_counter(mod: ModuleSource, value: ast.AST) -> bool:
        return (isinstance(value, ast.Call)
                and mod.imports.qualify(value.func) == "collections.Counter")

    @staticmethod
    def _is_container_literal(mod: ModuleSource, value: ast.AST) -> bool:
        if isinstance(value, (ast.Dict, ast.DictComp)):
            return True
        if isinstance(value, ast.Call):
            fq = mod.imports.qualify(value.func)
            name = fq.rsplit(".", 1)[-1] if fq else _terminal_name(value.func)
            return name in _METRIC_CONTAINER_CALLS
        return False


# -- 6. trace purity ----------------------------------------------------------

_JIT_NAMES = frozenset({"jit", "pjit", "shard_map"})
_IMPURE_CALL_QUALS = frozenset({
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "os.getenv",
})


def _is_jit_expr(mod: ModuleSource, expr: ast.AST) -> bool:
    """True for ``jax.jit`` / ``pjit`` / ``shard_map`` (bare or aliased),
    ``jax.jit(...)`` calls, and ``partial(jax.jit, ...)``."""
    name = _terminal_name(expr)
    if name in _JIT_NAMES:
        return True
    if isinstance(expr, ast.Call):
        if _terminal_name(expr.func) == "partial":
            return any(_is_jit_expr(mod, a) for a in expr.args)
        return _is_jit_expr(mod, expr.func)
    return False


@register_checker
class TracePurityChecker(Checker):
    """No wall-clock, np.random, os.environ, or global/nonlocal mutation
    inside jit/pjit/shard_map-traced functions: tracing runs the Python body
    ONCE, so any such value is frozen into the compiled program."""

    id = "trace-purity"
    hint = ("hoist the impure read out of the traced function and pass the "
            "value (or a jax.random key) as an argument")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        traced: list[ast.AST] = []
        wrapped_names: set[str] = set()
        for node, _ in _scoped_walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_jit_expr(mod, d) for d in node.decorator_list):
                    traced.append(node)
            elif isinstance(node, ast.Call) and _terminal_name(node.func) in _JIT_NAMES:
                if node.args:
                    if isinstance(node.args[0], ast.Name):
                        wrapped_names.add(node.args[0].id)
                    elif isinstance(node.args[0], ast.Lambda):
                        traced.append(node.args[0])
        if wrapped_names:
            for node, _ in _scoped_walk(mod.tree):
                if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and node.name in wrapped_names and node not in traced):
                    traced.append(node)

        seen: set[tuple[int, str]] = set()
        for fn in traced:
            fn_name = getattr(fn, "name", "<lambda>")
            for finding in self._scan_traced(mod, fn, fn_name):
                key = (finding.line, finding.anchor)
                if key not in seen:
                    seen.add(key)
                    yield finding

    def _scan_traced(self, mod: ModuleSource, fn: ast.AST,
                     fn_name: str) -> Iterator[Finding]:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                fq = mod.imports.qualify(node.func)
                if fq in _IMPURE_CALL_QUALS:
                    yield Finding(
                        self.id, mod.path, node.lineno,
                        f"impure call {fq}() inside traced function "
                        f"{fn_name!r} — the traced value is frozen at "
                        "compile time", self.hint, f"{fn_name}@{fq}")
                elif fq and fq.startswith("numpy.random."):
                    yield Finding(
                        self.id, mod.path, node.lineno,
                        f"{fq}() inside traced function {fn_name!r} — host "
                        "RNG state is invisible to XLA; every trace replays "
                        "the same draw",
                        "use jax.random with an explicit PRNGKey argument",
                        f"{fn_name}@{fq}")
            elif isinstance(node, ast.Attribute):
                if mod.imports.qualify(node) == "os.environ":
                    yield Finding(
                        self.id, mod.path, node.lineno,
                        f"os.environ read inside traced function {fn_name!r}"
                        " — the trace bakes in the value at compile time",
                        "read the env before tracing and pass the value in",
                        f"{fn_name}@os.environ")
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                kind = "global" if isinstance(node, ast.Global) else "nonlocal"
                names = ", ".join(node.names)
                yield Finding(
                    self.id, mod.path, node.lineno,
                    f"{kind} mutation of {names} inside traced function "
                    f"{fn_name!r} — side effects run once at trace time, "
                    "not per step",
                    "traced functions must be pure; return the new value "
                    "instead", f"{fn_name}@{kind}:{names}")


@register_checker
class LockOrderChecker(Checker):
    """tossan static half: whole-tree interprocedural lock-order analysis.

    Per-file ``check`` only accumulates parsed modules; the graph build,
    cycle detection, and callback-under-lock flags all happen in
    ``finalize`` because an acquisition-order cycle is by definition a
    property of the whole tree (see ``analysis/lockgraph.py``).  Findings
    are in ``NEVER_BASELINE``: a cycle is a latent deadlock — fixed, or
    explained inline with ``# toslint: allow-lock-order(<why>)``.
    """

    id = "lock-order"
    hint = ("establish one global acquisition order, or annotate the edge "
            "with `# toslint: allow-lock-order(<why>)`")

    def __init__(self) -> None:
        self._mods: list[ModuleSource] = []

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        self._mods.append(mod)
        return iter(())

    def finalize(self, project_root: Path | None) -> Iterator[Finding]:
        from tensorflowonspark_tpu.analysis import lockgraph

        graph = lockgraph.build_lockgraph(self._mods)
        yield from lockgraph.lock_order_findings(graph)
