"""tossan, static half: whole-tree lock-order analysis (the ``lock-order``
checker).

The per-file ``lock-discipline`` checker sees one module at a time, so an
acquisition-order cycle between two modules (coordinator takes its lock and
calls into the journal, which takes its own; elsewhere the journal calls
back into the coordinator) is invisible until a chaos test hangs.  This
pass is interprocedural over the whole package tree:

1. **Type inference from constructors** — ``self._journal = Journal(...)``
   in ``__init__`` gives attribute ``_journal`` the tree-class type
   ``journal.Journal``; ``self._lock = tos_named_lock("coordinator._lock")``
   (or a bare ``threading.Lock()``) makes ``_lock`` a lock attribute whose
   graph node is the literal name (or ``<module>.<Class>.<attr>`` for
   unnamed locks).  ``self._cb = on_flush`` (a constructor parameter)
   makes ``_cb`` a *callback slot*; every construction site in the tree
   that passes ``on_flush=self._handle`` binds the slot to that method.
2. **Per-callable summaries** — a scoped walk of every method/function
   records, with the set of locks held *locally* at that point, each
   direct lock acquisition (``with self._lock:`` / ``.acquire()``) and
   each resolvable call (self-methods, typed-attribute methods including
   locals assigned from tree-class constructors, module functions,
   constructors, callback slots).
3. **Transitive closure** — a fixpoint propagates "may acquire" sets up
   the call graph, keeping one witness chain (call path + line numbers)
   per (callable, lock).
4. **Global edge fold + cycle report** — every acquisition or call made
   while holding ``H`` contributes ``h -> acquired`` edges for ``h ∈ H``;
   strongly connected components with a cycle are reported once each,
   with the full witness chain for every edge on a representative cycle.
   Also flagged: **callback slots invoked while a lock is held** whose
   bound targets acquire locks — the batcher/reactor "callback under my
   lock" hazard, where the callback's author cannot see the lock they
   run under.

Suppression: ``# toslint: allow-lock-order(<reason>)`` on the line of any
acquisition/call edge on the cycle breaks that cycle for reporting (a
reasoned pragma documents WHY the order is safe — e.g. one side is
startup-only).  ``lock-order`` findings are never baselined
(``core.NEVER_BASELINE``): like knob/dial findings, a real cycle is fixed
or explained inline, never grandfathered.
"""

from __future__ import annotations

import ast
import dataclasses
import json
from typing import Iterator

from tensorflowonspark_tpu.analysis.core import (
    Finding,
    ModuleSource,
)

_LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
})
_NAMED_LOCK_CTORS = frozenset({"tos_named_lock", "tos_named_condition"})


def _terminal_name(func: ast.AST) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _mod_stem(path: str) -> str:
    return path.rsplit("/", 1)[-1].rsplit(".", 1)[0]


@dataclasses.dataclass
class _Event:
    """One acquisition or call inside a callable, with the locks held
    *locally within this callable* at that point."""

    kind: str  # "acquire" | "call" | "callback"
    target: object  # lock node id (acquire) | callable key(s) (call/callback)
    line: int
    held: tuple[str, ...]  # lock node ids held locally at this event


@dataclasses.dataclass
class _ClassInfo:
    key: str  # "<path>:<ClassName>"
    path: str
    name: str
    lock_attrs: dict = dataclasses.field(default_factory=dict)  # attr -> node id
    typed_attrs: dict = dataclasses.field(default_factory=dict)  # attr -> class key
    callback_attrs: dict = dataclasses.field(default_factory=dict)  # attr -> param
    methods: dict = dataclasses.field(default_factory=dict)  # name -> callable key
    init_params: list = dataclasses.field(default_factory=list)  # positional order


class LockGraph:
    """The resolved whole-tree graph; built by :func:`build_lockgraph`."""

    def __init__(self) -> None:
        self.classes: dict[str, _ClassInfo] = {}  # class key -> info
        self.class_by_name: dict[str, list[str]] = {}  # bare name -> keys
        self.functions: dict[str, ast.AST] = {}  # callable key -> def node
        self.fn_mod: dict[str, ModuleSource] = {}  # callable key -> module
        self.fn_class: dict[str, str] = {}  # callable key -> class key
        self.module_locks: dict[str, dict[str, str]] = {}  # path -> var -> node
        self.events: dict[str, list[_Event]] = {}  # callable key -> events
        # callback slot bindings: (class key, attr) -> set of callable keys
        self.bindings: dict[tuple[str, str], set[str]] = {}
        self.may_acquire: dict[str, dict[str, list[str]]] = {}
        # lock node -> lock node -> witness chain (list of "site" strings)
        self.edges: dict[str, dict[str, list[str]]] = {}
        # (path, line) pragma sites that bless edges through them
        self.blessed: set[tuple[str, int]] = set()
        # callback-under-lock findings raw material:
        # (path, line, held node, slot, callee key, acquired node)
        self.callback_sites: list[tuple] = []

    # -- resolution helpers ----------------------------------------------------

    def resolve_class(self, mod: ModuleSource, expr: ast.AST) -> str | None:
        """Class key for a Name/Attribute expression, via the import map:
        the qualified dotted name's tail is matched against tree classes
        (module tail + class name when qualifiable, bare class name as the
        over-approximating fallback)."""
        fq = mod.imports.qualify(expr)
        name = _terminal_name(expr)
        if fq and "." in fq:
            mod_dotted, cls = fq.rsplit(".", 1)
            tail = mod_dotted.rsplit(".", 1)[-1]
            for key in self.class_by_name.get(cls, ()):
                info = self.classes[key]
                if _mod_stem(info.path) == tail or info.path == mod.path:
                    return key
        if name:
            keys = self.class_by_name.get(name, ())
            if len(keys) == 1:
                return keys[0]
            for key in keys:  # same-module definition wins
                if self.classes[key].path == mod.path:
                    return key
        return None


# -- pass 1: declarations ------------------------------------------------------


def _lock_node_for(mod: ModuleSource, cls_name: str, attr: str,
                   value: ast.Call) -> str | None:
    """Graph node id for a lock-constructing assignment, else None."""
    fq = mod.imports.qualify(value.func)
    term = _terminal_name(value.func)
    name = fq.rsplit(".", 1)[-1] if fq else term
    if name in _NAMED_LOCK_CTORS:
        if value.args and isinstance(value.args[0], ast.Constant) \
                and isinstance(value.args[0].value, str):
            return value.args[0].value
        return f"{_mod_stem(mod.path)}.{cls_name}.{attr}" if cls_name else \
            f"{_mod_stem(mod.path)}.{attr}"
    if fq in _LOCK_CTORS or (fq is None and term in
                             ("Lock", "RLock", "Condition")):
        stem = _mod_stem(mod.path)
        return f"{stem}.{cls_name}.{attr}" if cls_name else f"{stem}.{attr}"
    return None


def _collect_declarations(graph: LockGraph, mods: list[ModuleSource]) -> None:
    for mod in mods:
        graph.module_locks.setdefault(mod.path, {})
        for stmt in mod.tree.body:
            # module-level locks: _registry_lock = threading.Lock()
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)):
                node = _lock_node_for(mod, "", stmt.targets[0].id, stmt.value)
                if node:
                    graph.module_locks[mod.path][stmt.targets[0].id] = node
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{mod.path}:{stmt.name}"
                graph.functions[key] = stmt
                graph.fn_mod[key] = mod
            elif isinstance(stmt, ast.ClassDef):
                ckey = f"{mod.path}:{stmt.name}"
                info = _ClassInfo(ckey, mod.path, stmt.name)
                graph.classes[ckey] = info
                graph.class_by_name.setdefault(stmt.name, []).append(ckey)
                for item in stmt.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        mkey = f"{ckey}.{item.name}"
                        info.methods[item.name] = mkey
                        graph.functions[mkey] = item
                        graph.fn_mod[mkey] = mod
                        graph.fn_class[mkey] = ckey


def _scan_constructors(graph: LockGraph, mods: list[ModuleSource]) -> None:
    """Attribute typing from EVERY method's ``self.x = ...`` (constructors
    dominate, but lazily-built clients — ``self._client = DataClient(...)``
    in a getter — matter for exactly the cross-module edges this pass
    exists to see)."""
    for info in graph.classes.values():
        mod = graph.fn_mod[next(iter(info.methods.values()))] if \
            info.methods else None
        if mod is None:
            continue
        init = graph.functions.get(info.methods.get("__init__", ""))
        if init is not None:
            info.init_params = [a.arg for a in init.args.args[1:]]
        for mname, mkey in info.methods.items():
            fn = graph.functions[mkey]
            params = {a.arg for a in getattr(fn.args, "args", [])[1:]}
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                for target in node.targets:
                    if not (isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"):
                        continue
                    attr = target.attr
                    value = node.value
                    if isinstance(value, ast.Call):
                        lock = _lock_node_for(mod, info.name, attr, value)
                        if lock:
                            info.lock_attrs.setdefault(attr, lock)
                            continue
                        ckey = graph.resolve_class(mod, value.func)
                        if ckey:
                            info.typed_attrs.setdefault(attr, ckey)
                            continue
                    if (mname == "__init__" and isinstance(value, ast.Name)
                            and value.id in params):
                        info.callback_attrs.setdefault(attr, value.id)


def _scan_bindings(graph: LockGraph, mods: list[ModuleSource]) -> None:
    """Callback-slot bindings: every ``SomeClass(..., cb=self._handle)``
    construction in the tree binds SomeClass's callback slots (union over
    all sites — the over-approximating bias of the rest of toslint)."""
    for mod in mods:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            ckey = graph.resolve_class(mod, node.func)
            if ckey is None:
                continue
            info = graph.classes[ckey]
            param_of_attr = info.callback_attrs  # attr -> param name
            if not param_of_attr:
                continue
            passed: dict[str, ast.AST] = {}
            for i, arg in enumerate(node.args):
                if i < len(info.init_params):
                    passed[info.init_params[i]] = arg
            for kw in node.keywords:
                if kw.arg:
                    passed[kw.arg] = kw.value
            # which class' method does the value refer to?
            encl = _enclosing_class(graph, mod, node)
            for attr, param in param_of_attr.items():
                value = passed.get(param)
                if value is None:
                    continue
                target = _callable_ref(graph, mod, encl, value)
                if target:
                    graph.bindings.setdefault((ckey, attr), set()).add(target)


def _enclosing_class(graph: LockGraph, mod: ModuleSource,
                     node: ast.AST) -> str | None:
    """Class key whose body lexically contains ``node`` (linear rescan;
    fine at toslint scale)."""
    for ckey, info in graph.classes.items():
        if info.path != mod.path:
            continue
        for mkey in info.methods.values():
            fn = graph.functions[mkey]
            if (fn.lineno <= node.lineno <=
                    getattr(fn, "end_lineno", fn.lineno)):
                return ckey
    return None


def _callable_ref(graph: LockGraph, mod: ModuleSource, encl: str | None,
                  value: ast.AST) -> str | None:
    """Callable key a callback argument refers to: ``self._m`` /
    ``self._attr.m`` / a module function name."""
    if isinstance(value, ast.Attribute):
        if isinstance(value.value, ast.Name) and value.value.id == "self" \
                and encl is not None:
            return graph.classes[encl].methods.get(value.attr)
        if (isinstance(value.value, ast.Attribute)
                and isinstance(value.value.value, ast.Name)
                and value.value.value.id == "self" and encl is not None):
            attr_t = graph.classes[encl].typed_attrs.get(value.value.attr)
            if attr_t:
                return graph.classes[attr_t].methods.get(value.attr)
    if isinstance(value, ast.Name):
        key = f"{mod.path}:{value.id}"
        if key in graph.functions:
            return key
    return None


# -- pass 2: per-callable event summaries --------------------------------------


class _BodyScanner:
    """Walk one callable's body tracking locally-held locks and recording
    acquisition/call events."""

    def __init__(self, graph: LockGraph, mod: ModuleSource,
                 ckey: str | None):
        self.graph = graph
        self.mod = mod
        self.ckey = ckey
        self.events: list[_Event] = []
        self.local_types: dict[str, str] = {}  # var -> class key

    def _self_lock(self, expr: ast.AST) -> str | None:
        """Lock node id for ``self._lock`` / module-level lock names."""
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and self.ckey):
            return self.graph.classes[self.ckey].lock_attrs.get(expr.attr)
        if isinstance(expr, ast.Name):
            return self.graph.module_locks.get(self.mod.path, {}).get(expr.id)
        return None

    def _tree_function(self, func: ast.AST) -> str | None:
        """Module-function key for a (possibly from-imported) reference,
        matched by qualified module tail + function name."""
        fq = self.mod.imports.qualify(func)
        if not fq or "." not in fq:
            return None
        mod_dotted, fname = fq.rsplit(".", 1)
        tail = mod_dotted.rsplit(".", 1)[-1]
        for key in self.graph.functions:
            if key in self.graph.fn_class:
                continue
            path, name = key.split(":", 1)
            if name == fname and _mod_stem(path) == tail:
                return key
        return None

    def _callees(self, call: ast.Call) -> tuple[list[str], str | None]:
        """(resolved callable keys, callback slot attr if this is one)."""
        g, mod, ckey = self.graph, self.mod, self.ckey
        func = call.func
        if isinstance(func, ast.Name):
            # module function or tree-class constructor
            key = f"{mod.path}:{func.id}"
            if key in g.functions and key not in g.fn_class:
                return [key], None
            cls = g.resolve_class(mod, func)
            if cls:
                init = g.classes[cls].methods.get("__init__")
                return ([init] if init else []), None
            fn = self._tree_function(func)  # from-imported module function
            return ([fn] if fn else []), None
        if not isinstance(func, ast.Attribute):
            return [], None
        recv = func.value
        # self.m(...)
        if isinstance(recv, ast.Name) and recv.id == "self" and ckey:
            info = g.classes[ckey]
            m = info.methods.get(func.attr)
            if m:
                return [m], None
            if func.attr in info.callback_attrs:
                bound = g.bindings.get((ckey, func.attr), set())
                return sorted(bound), func.attr
            attr_t = info.typed_attrs.get(func.attr)
            # self._cb(...) where _cb is an untyped constructor capture:
            # fall through (opaque)
            if attr_t:
                m = g.classes[attr_t].methods.get("__call__")
                return ([m] if m else []), None
            return [], None
        # self._attr.m(...)
        if (isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self" and ckey):
            attr_t = g.classes[ckey].typed_attrs.get(recv.attr)
            if attr_t:
                m = g.classes[attr_t].methods.get(func.attr)
                return ([m] if m else []), None
            return [], None
        # local_var.m(...) where local_var = TreeClass(...)
        if isinstance(recv, ast.Name):
            local_t = self.local_types.get(recv.id)
            if local_t:
                m = g.classes[local_t].methods.get(func.attr)
                return ([m] if m else []), None
        # mod.func(...) via imports
        cls = g.resolve_class(mod, func)
        if cls:
            init = g.classes[cls].methods.get("__init__")
            return ([init] if init else []), None
        fn = self._tree_function(func)
        return ([fn] if fn else []), None

    def scan(self, body: list[ast.stmt], held: tuple[str, ...]) -> None:
        for stmt in body:
            self._scan_stmt(stmt, held)

    def _scan_stmt(self, node: ast.AST, held: tuple[str, ...]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                self._scan_expr(item.context_expr, held, skip_call=False)
                lock = self._self_lock(item.context_expr)
                if lock:
                    self.events.append(
                        _Event("acquire", lock, node.lineno, inner))
                    if lock not in inner:
                        inner = inner + (lock,)
            for stmt in node.body:
                self._scan_stmt(stmt, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later, not under this frame's locks
            self.scan(node.body, ())
            return
        if isinstance(node, ast.Lambda):
            self._scan_expr(node.body, ())
            return
        if isinstance(node, ast.Assign):
            # local type inference: x = TreeClass(...)
            if (isinstance(node.value, ast.Call)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                cls = self.graph.resolve_class(self.mod, node.value.func)
                if cls:
                    self.local_types[node.targets[0].id] = cls
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._scan_expr(child, held)
            else:
                self._scan_stmt(child, held)

    def _scan_expr(self, node: ast.AST, held: tuple[str, ...],
                   skip_call: bool = False) -> None:
        if isinstance(node, (ast.Lambda,)):
            self._scan_expr(node.body, ())
            return
        if isinstance(node, ast.Call) and not skip_call:
            self._scan_call(node, held)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.scan(child.body, ())
            elif isinstance(child, ast.expr):
                self._scan_expr(child, held)
            else:
                self._scan_stmt(child, held)

    def _scan_call(self, node: ast.Call, held: tuple[str, ...]) -> None:
        func = node.func
        # explicit .acquire() / .release() on a lock attribute
        if isinstance(func, ast.Attribute) and func.attr in ("acquire",
                                                            "release"):
            lock = self._self_lock(func.value)
            if lock and func.attr == "acquire":
                self.events.append(_Event("acquire", lock, node.lineno, held))
            if lock:
                for arg in node.args:
                    self._scan_expr(arg, held)
                return
        callees, cb_attr = self._callees(node)
        if callees or cb_attr is not None:
            kind = "callback" if cb_attr is not None else "call"
            self.events.append(
                _Event(kind, (tuple(callees), cb_attr), node.lineno, held))
        self._scan_expr(func, held, skip_call=True)
        for arg in node.args:
            self._scan_expr(arg, held)
        for kw in node.keywords:
            self._scan_expr(kw.value, held)


def _collect_events(graph: LockGraph, mods: list[ModuleSource]) -> None:
    for key, fn in graph.functions.items():
        mod = graph.fn_mod[key]
        scanner = _BodyScanner(graph, mod, graph.fn_class.get(key))
        scanner.scan(fn.body, ())
        graph.events[key] = scanner.events


# -- pass 3: transitive may-acquire --------------------------------------------


def _site(graph: LockGraph, key: str, line: int) -> str:
    mod = graph.fn_mod[key]
    name = key.split(":", 1)[1]
    return f"{mod.path}:{line} ({name})"


def _close_may_acquire(graph: LockGraph) -> None:
    """Fixpoint: may_acquire[f] = own acquires + union over callees, with
    one witness chain (call sites down to the acquire) kept per lock."""
    may: dict[str, dict[str, list[str]]] = {k: {} for k in graph.functions}
    changed = True
    while changed:
        changed = False
        for key, events in graph.events.items():
            mine = may[key]
            for ev in events:
                if ev.kind == "acquire":
                    if ev.target not in mine:
                        mine[ev.target] = [_site(graph, key, ev.line)]
                        changed = True
                else:
                    callees, _ = ev.target
                    for callee in callees:
                        for lock, chain in may.get(callee, {}).items():
                            if lock not in mine:
                                mine[lock] = ([_site(graph, key, ev.line)]
                                              + chain)
                                changed = True
    graph.may_acquire = may


# -- pass 4: edge fold + cycles ------------------------------------------------


def _collect_pragmas(graph: LockGraph, mods: list[ModuleSource]) -> None:
    for mod in mods:
        for line, reason in getattr(mod.pragmas, "lock_order", {}).items():
            if reason:  # a reason-less pragma blesses nothing
                graph.blessed.add((mod.path, line))


def _fold_edges(graph: LockGraph) -> None:
    edges = graph.edges
    edge_sites: dict[tuple[str, str], tuple[str, int]] = {}

    def add(a: str, b: str, chain: list[str], path: str, line: int) -> None:
        if a == b:
            return
        if b not in edges.setdefault(a, {}):
            edges[a][b] = chain
            edge_sites[(a, b)] = (path, line)

    for key, events in graph.events.items():
        mod = graph.fn_mod[key]
        for ev in events:
            if not ev.held:
                continue
            if ev.kind == "acquire":
                for h in ev.held:
                    add(h, ev.target, [_site(graph, key, ev.line)],
                        mod.path, ev.line)
            else:
                callees, cb_attr = ev.target
                for callee in callees:
                    acq = graph.may_acquire.get(callee, {})
                    for lock, chain in acq.items():
                        for h in ev.held:
                            add(h, lock,
                                [_site(graph, key, ev.line)] + chain,
                                mod.path, ev.line)
                        if cb_attr is not None:
                            graph.callback_sites.append(
                                (mod.path, ev.line, ev.held[-1], cb_attr,
                                 callee, lock))
    graph.edge_sites = edge_sites  # type: ignore[attr-defined]


def _sccs(edges: dict[str, dict[str, list[str]]]) -> list[list[str]]:
    """Tarjan strongly-connected components (iterative)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    nodes = sorted(set(edges) | {b for bs in edges.values() for b in bs})

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(edges.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(edges.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)

    for v in nodes:
        if v not in index:
            strongconnect(v)
    return out


def _cycle_in(edges: dict[str, dict[str, list[str]]],
              comp: list[str]) -> list[str]:
    """A representative simple cycle within one SCC (node list, first ==
    entry, closed implicitly)."""
    comp_set = set(comp)
    start = min(comp)
    path = [start]
    seen = {start}
    node = start
    while True:
        nxt = min(w for w in edges.get(node, ()) if w in comp_set)
        if nxt == start:
            return path
        if nxt in seen:
            i = path.index(nxt)
            return path[i:]
        path.append(nxt)
        seen.add(nxt)
        node = nxt


# -- public API ----------------------------------------------------------------


def build_lockgraph(mods: list[ModuleSource]) -> LockGraph:
    graph = LockGraph()
    _collect_declarations(graph, mods)
    _scan_constructors(graph, mods)
    _scan_bindings(graph, mods)
    _collect_events(graph, mods)
    _close_may_acquire(graph)
    _collect_pragmas(graph, mods)
    _fold_edges(graph)
    return graph


LOCK_ORDER_HINT = (
    "break the cycle: take the locks in one global order, move the "
    "cross-module call outside the critical section, or — if one side is "
    "provably safe (startup-only, externally serialized) — annotate the "
    "acquisition site with `# toslint: allow-lock-order(<why>)`")
CALLBACK_HINT = (
    "fire callbacks outside the lock (collect under the lock, invoke "
    "after release — the batcher's _fire_done pattern), or annotate "
    "`# toslint: allow-lock-order(<why>)` at the call site")


def lock_order_findings(graph: LockGraph) -> Iterator[Finding]:
    """Cycle + callback-under-lock findings from a built graph."""
    edge_sites = getattr(graph, "edge_sites", {})

    for comp in _sccs(graph.edges):
        has_cycle = len(comp) > 1 or (
            comp and comp[0] in graph.edges.get(comp[0], {}))
        if not has_cycle:
            continue
        cycle = _cycle_in(graph.edges, comp)
        closed = cycle + [cycle[0]]
        if any(edge_sites.get((a, b)) in graph.blessed
               for a, b in zip(closed, closed[1:])):
            continue
        chain_lines = []
        for a, b in zip(closed, closed[1:]):
            via = " -> ".join(graph.edges[a][b])
            chain_lines.append(f"{a} -> {b} (via {via})")
        path, line = edge_sites.get((closed[0], closed[1]), ("<tree>", 1))
        yield Finding(
            "lock-order", path, line,
            "potential deadlock: acquisition-order cycle "
            + " -> ".join(closed) + "; witness: "
            + "; ".join(chain_lines),
            LOCK_ORDER_HINT,
            "cycle:" + "->".join(sorted(set(cycle))))

    seen: set[tuple] = set()
    for path, line, held, slot, callee, lock in sorted(graph.callback_sites):
        if (path, line) in graph.blessed:
            continue
        key = (path, line, slot, lock)
        if key in seen:
            continue
        seen.add(key)
        callee_name = callee.split(":", 1)[1]
        yield Finding(
            "lock-order", path, line,
            f"callback slot '{slot}' fired while holding '{held}', and a "
            f"bound target ({callee_name}) acquires '{lock}' — the "
            "callback's author cannot see the lock they run under",
            CALLBACK_HINT,
            f"callback:{slot}@{lock}")


# -- CI artifact dumps ---------------------------------------------------------


def graph_as_json(graph: LockGraph) -> dict:
    return {
        "schema": "tos-lockgraph-v1",
        "nodes": sorted(set(graph.edges)
                        | {b for bs in graph.edges.values() for b in bs}),
        "edges": [
            {"from": a, "to": b, "witness": chain}
            for a in sorted(graph.edges)
            for b, chain in sorted(graph.edges[a].items())
        ],
    }


def graph_as_dot(graph: LockGraph) -> str:
    lines = ["digraph lockgraph {", '  rankdir="LR";',
             '  node [shape=box, fontname="monospace"];']
    cyclic = {n for comp in _sccs(graph.edges)
              if len(comp) > 1 or (comp and comp[0] in
                                   graph.edges.get(comp[0], {}))
              for n in comp}
    nodes = sorted(set(graph.edges)
                   | {b for bs in graph.edges.values() for b in bs})
    for n in nodes:
        color = ', color="red"' if n in cyclic else ""
        lines.append(f'  "{n}" [label="{n}"{color}];')
    for a in sorted(graph.edges):
        for b, chain in sorted(graph.edges[a].items()):
            tip = chain[0].replace('"', "'")
            style = ' color="red"' if a in cyclic and b in cyclic else ""
            lines.append(f'  "{a}" -> "{b}" [tooltip="{tip}"{style}];')
    lines.append("}")
    return "\n".join(lines)


def dump_lockgraph(graph: LockGraph, directory) -> tuple[str, str]:
    """Write ``lockgraph.dot`` + ``lockgraph.json`` into ``directory``;
    returns the two paths."""
    from pathlib import Path

    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    dot = directory / "lockgraph.dot"
    js = directory / "lockgraph.json"
    dot.write_text(graph_as_dot(graph) + "\n", encoding="utf-8")
    js.write_text(json.dumps(graph_as_json(graph), indent=2, sort_keys=True)
                  + "\n", encoding="utf-8")
    return str(dot), str(js)
