"""toslint — framework-aware static analysis for tensorflowonspark_tpu.

Run it::

    python -m tensorflowonspark_tpu.analysis            # gate: exit 0 = clean
    python -m tensorflowonspark_tpu.analysis --baseline-update
    python -m tensorflowonspark_tpu.analysis --write-knob-table

Stdlib-``ast`` only; see ``core.py`` for the framework and ``checkers.py``
for the five codebase-specific disciplines.
"""

from tensorflowonspark_tpu.analysis.core import (
    Finding,
    analyze_source,
    all_checker_ids,
    default_baseline_path,
    finding_ids,
    format_finding,
    load_baseline,
    partition_by_baseline,
    run_analysis,
    write_baseline,
)

__all__ = [
    "Finding",
    "analyze_source",
    "all_checker_ids",
    "default_baseline_path",
    "finding_ids",
    "format_finding",
    "load_baseline",
    "partition_by_baseline",
    "run_analysis",
    "write_baseline",
]
