"""CLI for toslint: ``python -m tensorflowonspark_tpu.analysis``.

Exit codes: 0 = clean (every finding baselined), 1 = new findings (or
never-baselined classes present), 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tensorflowonspark_tpu.analysis import core


def _write_knob_table(readme: Path) -> int:
    from tensorflowonspark_tpu.utils import knobs

    table = f"{knobs.TABLE_BEGIN}\n{knobs.knob_table_markdown()}\n{knobs.TABLE_END}"
    if not readme.exists():
        print(f"error: {readme} not found", file=sys.stderr)
        return 2
    lines = readme.read_text(encoding="utf-8").splitlines()
    span = knobs.find_table_block(lines)
    if span is None:
        print(f"error: {readme} has no knob-table markers; add\n"
              f"{knobs.TABLE_BEGIN}\n{knobs.TABLE_END}\n"
              "where the table should live", file=sys.stderr)
        return 2
    begin, end = span
    lines[begin:end + 1] = table.splitlines()
    readme.write_text("\n".join(lines) + "\n", encoding="utf-8")
    print(f"wrote knob table to {readme}")
    return 0


def _dump_lockgraph(package_root: Path, out_dir: Path) -> int:
    from tensorflowonspark_tpu.analysis import lockgraph

    project_root = package_root.parent
    mods = []
    for path in core.iter_package_files(package_root):
        rel = path.relative_to(project_root).as_posix()
        try:
            mods.append(core.ModuleSource(rel, path.read_text(encoding="utf-8")))
        except SyntaxError:
            continue  # the lock-order gate itself reports parse errors
    graph = lockgraph.build_lockgraph(mods)
    dot, js = lockgraph.dump_lockgraph(graph, out_dir)
    n_edges = sum(len(bs) for bs in graph.edges.values())
    print(f"lockgraph: {n_edges} edge(s) -> {dot}, {js}")
    return 0


def _findings_json(findings, baseline: set[str]) -> str:
    rows = [
        {"checker": f.checker, "path": f.path, "line": f.line,
         "message": f.message, "hint": f.hint, "id": fid,
         "baselined": fid in baseline}
        for f, fid in core.finding_ids(findings)
    ]
    return json.dumps({"schema": "toslint-findings-v1", "findings": rows},
                      indent=2, sort_keys=True)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="toslint",
        description="framework-aware static analysis for tensorflowonspark_tpu")
    parser.add_argument("--baseline", type=Path, default=None,
                        help="baseline file (default: analysis/baseline.json)")
    parser.add_argument("--baseline-update", action="store_true",
                        help="regenerate the baseline from current findings "
                             "(deterministic: sorted, stable ids); "
                             "knob-/dial-discipline findings are refused")
    parser.add_argument("--package-root", type=Path, default=None,
                        help="package directory to lint (default: the "
                             "installed tensorflowonspark_tpu package)")
    parser.add_argument("--checkers", default=None,
                        help="comma-separated checker ids (default: all)")
    parser.add_argument("--list-checkers", action="store_true")
    parser.add_argument("--knob-table", action="store_true",
                        help="print the generated README knob table and exit")
    parser.add_argument("--write-knob-table", action="store_true",
                        help="rewrite the README knob-table block in place")
    parser.add_argument("--dump-lockgraph", type=Path, default=None,
                        metavar="DIR",
                        help="write the resolved whole-tree lock graph as "
                             "lockgraph.dot + lockgraph.json into DIR (CI "
                             "artifacts) and exit")
    parser.add_argument("--format", choices=("text", "json"), default="text",
                        help="finding output format (json = machine-readable "
                             "rows for every finding, new and baselined)")
    args = parser.parse_args(argv)

    if args.list_checkers:
        print("\n".join(core.all_checker_ids()))
        return 0

    from tensorflowonspark_tpu.utils import knobs

    if args.knob_table:
        print(knobs.knob_table_markdown())
        return 0

    package_root = (args.package_root or core.default_package_root()).resolve()
    if args.write_knob_table:
        return _write_knob_table(package_root.parent / "README.md")
    if args.dump_lockgraph is not None:
        return _dump_lockgraph(package_root, args.dump_lockgraph)

    checker_ids = (None if args.checkers is None
                   else [s.strip() for s in args.checkers.split(",") if s.strip()])
    try:
        findings = core.run_analysis(package_root, checker_ids)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or core.default_baseline_path()
    if args.baseline_update:
        # a --checkers subset update is scoped: other checkers' entries are
        # preserved, never silently dropped
        refused = core.write_baseline(baseline_path, findings,
                                      replace_checkers=checker_ids)
        kept = len(core.load_baseline(baseline_path))
        print(f"baseline: wrote {kept} finding id(s) to {baseline_path}")
        if refused:
            print(f"\n{len(refused)} finding(s) are never baselined "
                  f"({', '.join(sorted(core.NEVER_BASELINE))}) — fix these:",
                  file=sys.stderr)
            for f in refused:
                print(core.format_finding(f), file=sys.stderr)
            return 1
        return 0

    baseline = core.load_baseline(baseline_path)
    new, suppressed, stale = core.partition_by_baseline(findings, baseline)
    if args.format == "json":
        print(_findings_json(findings, baseline))
        return 1 if new else 0
    for f in new:
        print(core.format_finding(f))
    if stale:
        print(f"note: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} no longer fire(s); "
              "run --baseline-update to trim:", file=sys.stderr)
        for fid in sorted(stale):
            print(f"    {fid}", file=sys.stderr)
    status = (f"toslint: {len(new)} new finding(s), "
              f"{len(suppressed)} baselined, {len(stale)} stale")
    print(status, file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
