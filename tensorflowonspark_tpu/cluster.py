"""Driver-side cluster lifecycle API — the ``TFCluster`` replacement.

Reference (``tensorflowonspark/TFCluster.py``): ``run()`` ``:~270-420`` builds
the role template, starts the reservation server, launches node closures on
executors, and returns a cluster handle with ``train`` ``:~70-130``,
``inference`` ``:~130-170``, ``shutdown`` ``:~170-240`` and
``tensorboard_url`` ``:~240-260``; ``InputMode`` at ``:~40``.

TPU-native deltas (BASELINE.json:5, SURVEY.md §2.3):
- **No parameter servers.** ``num_ps`` is gone; async PS data parallelism is
  replaced by sync SPMD data parallelism (XLA all-reduce over ICI inside the
  jitted train step).  Roles are chief/worker/evaluator only.
- **Launcher abstraction** instead of Spark: ``LocalLauncher`` (default) or a
  TPU-pod launcher place node processes; partitions stream over the data
  plane (``dataserver.py``) rather than Spark feed tasks.
- ``InputMode.DIRECT`` (framework reads files itself — the reference's
  ``InputMode.TENSORFLOW``) vs ``InputMode.STREAMING`` (driver streams
  partitions — the reference's ``InputMode.SPARK``).  Aliases with the
  reference names are provided.
"""

from __future__ import annotations

import contextlib
import enum
import logging
import os
import secrets
import threading
import time
from typing import Any, Callable, Sequence

from tensorflowonspark_tpu.coordinator import CoordinatorServer
from tensorflowonspark_tpu.data import as_partitioned
from tensorflowonspark_tpu.dataserver import DataClient
from tensorflowonspark_tpu.launcher import LocalLauncher, SubprocessLauncher  # noqa: F401 - LocalLauncher re-exported
from tensorflowonspark_tpu.node import NodeConfig

logger = logging.getLogger(__name__)


class InputMode(enum.Enum):
    """Reference ``TFCluster.InputMode`` (``TFCluster.py:~40``)."""

    DIRECT = 0      # framework reads files itself (reference: TENSORFLOW)
    STREAMING = 1   # driver streams partitions into node feeds (reference: SPARK)

    # Drop-in aliases for TensorFlowOnSpark users.
    TENSORFLOW = 0
    SPARK = 1


def _build_roles(num_executors: int, master_node: str | None, eval_node: bool) -> list[tuple[str, int]]:
    """Role template (reference ``TFCluster.py:~290-330``, minus ``ps``)."""
    roles: list[tuple[str, int]] = []
    chief_name = master_node or "chief"
    roles.append((chief_name, 0))
    num_workers = num_executors - 1 - (1 if eval_node else 0)
    if num_workers < 0:
        raise ValueError("num_executors too small for the requested roles")
    roles.extend(("worker", i) for i in range(num_workers))
    if eval_node:
        roles.append(("evaluator", 0))
    return roles


class TPUCluster:
    """Handle to a running cluster (reference ``class TFCluster``)."""

    def __init__(
        self,
        coordinator: CoordinatorServer,
        launcher: LocalLauncher,
        cluster_info: list[dict],
        authkey: bytes,
        input_mode: InputMode,
        queues: Sequence[str],
        feed_timeout: float,
        heartbeat_interval: float = 2.0,
    ):
        self.coordinator = coordinator
        self.launcher = launcher
        self.cluster_info = cluster_info
        self.authkey = authkey
        self.input_mode = input_mode
        self.queues = queues
        self.input_qnames = [q for q in queues if q not in ("output", "error")]
        self.feed_timeout = feed_timeout
        self.heartbeat_interval = heartbeat_interval
        self._clients: dict[int, DataClient] = {}
        self._shutdown_done = False
        # Feedable nodes: everything except the evaluator (the reference also
        # excluded ps nodes; we have none).
        self._feed_ids = [m["executor_id"] for m in cluster_info if m["job_name"] != "evaluator"]
        # Dead-node monitor (SURVEY.md §5.3 — the role Spark played for the
        # reference: the driver NOTICES executor death instead of waiting for
        # a feed/barrier/collective timeout to expire).  A node whose
        # heartbeat goes silent past the window is recorded as a node error,
        # and the stop signal both aborts in-flight control-plane
        # barriers/reduces and tells surviving nodes to stop — so blocked
        # train()/inference() calls unblock within seconds, not
        # feed_timeout.  Clean exits deregister first and are never flagged.
        self._dead_after = _env_float("TOS_DEAD_NODE_TIMEOUT",
                                      max(12.0, 6.0 * heartbeat_interval))
        self._monitor_stop = threading.Event()
        self._monitor = threading.Thread(target=self._monitor_loop, daemon=True,
                                         name="dead-node-monitor")
        self._monitor.start()

    def _record_deaths(self) -> list[int]:
        """Role-aware death bookkeeping, shared by the monitor thread and
        shutdown's death-aware join.  The evaluator is an optional SIDECAR —
        no feed, no collectives — so its death is logged and forgotten
        (training continues; reference parity: a failed auxiliary executor
        didn't fail the job).  Data-node deaths are recorded as node errors
        (idempotently) and returned for the caller to escalate on."""
        dead = self.coordinator.dead_nodes(self._dead_after)
        dead_eval = [i for i in dead if i not in self._feed_ids]
        if dead_eval:
            logger.warning("evaluator node(s) %s stopped heartbeating; "
                           "training continues without them", dead_eval)
            self.coordinator.forget(dead_eval)
        dead_data = [i for i in dead if i in self._feed_ids]
        if dead_data:
            self.coordinator.mark_dead(dead_data)
        return dead_data

    def _monitor_loop(self) -> None:
        poll = max(1.0, self.heartbeat_interval)
        while not self._monitor_stop.wait(poll):
            dead_data = self._record_deaths()
            if dead_data:
                logger.error("nodes %s stopped heartbeating (>%.0fs); failing "
                             "in-flight work and signalling stop",
                             dead_data, self._dead_after)
                self.coordinator.signal_stop()
                return

    def dead_nodes(self) -> list[int]:
        """Executor ids currently past the heartbeat window (diagnostic)."""
        return self.coordinator.dead_nodes(self._dead_after)

    # -- data-plane connections ---------------------------------------------

    def _client(self, executor_id: int) -> DataClient:
        if executor_id not in self._clients:
            meta = self.cluster_info[executor_id]
            self._clients[executor_id] = DataClient(
                meta["host"], meta["data_port"], self.authkey,
                call_timeout=self.feed_timeout + 60.0,
                stall_timeout=self.feed_timeout)
        return self._clients[executor_id]

    # -- training feed (reference TFCluster.train :~70-130, §3.2) ------------

    def train(self, data: Any, num_epochs: int = 1, qname: str = "input",
              shuffle_seed: int | None = None) -> None:
        """Stream partitions into the worker feeds (InputMode.STREAMING only).

        Partition *i* goes to feedable node ``i % W`` — the same round-robin
        partition placement Spark gave the reference.  Blocks until all
        partitions are consumed (or nodes report 'terminating').

        ``shuffle_seed`` reorders partitions differently each epoch
        (seed+epoch, deterministic) — the between-epochs shuffle the
        reference inherited from Spark/tf.data file shuffling.
        """
        if self.input_mode != InputMode.STREAMING:
            raise RuntimeError("train(data) requires InputMode.STREAMING (reference: InputMode.SPARK)")
        dataset = as_partitioned(data, default_partitions=len(self._feed_ids))
        errors: list[Exception] = []

        def _feed_worker(worker_pos: int, executor_id: int) -> None:
            try:
                client = self._client(executor_id)
                for epoch in range(num_epochs):
                    epoch_data = (dataset if shuffle_seed is None
                                  else dataset.shuffle_partitions(shuffle_seed + epoch))
                    for p in range(worker_pos, dataset.num_partitions, len(self._feed_ids)):
                        state = client.feed_partition(epoch_data.iter_partition(p), qname)
                        if state == "terminating":
                            logger.info("node %d terminating; dropping remaining feed", executor_id)
                            return
            except Exception as e:
                errors.append(e)

        threads = [
            threading.Thread(target=_feed_worker, args=(pos, eid), name=f"feed-{eid}")
            for pos, eid in enumerate(self._feed_ids)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self._raise_node_errors()
        if errors:
            raise RuntimeError(f"feeding failed: {errors[0]}") from errors[0]

    # -- inference (reference TFCluster.inference :~130-170, §3.3) -----------

    def inference(self, data: Any, qname_in: str = "input", qname_out: str = "output",
                  flat: bool = True, eof_when_done: bool = False) -> list:
        """Round-trip partitions through the nodes; ordered, exactly-count.

        Returns the flattened results in partition order — the invariant the
        reference's output RDD preserved (SURVEY.md §3.3).  ``flat=False``
        returns one result list per partition instead (the pipeline layer
        needs partition boundaries to rebuild a PartitionedDataset).

        Materializes everything; for datasets bigger than driver memory use
        ``inference_stream``.
        """
        dataset = as_partitioned(data, default_partitions=len(self._feed_ids))
        results: list[list | None] = [None] * dataset.num_partitions
        for p, part in self.inference_stream(dataset, qname_in, qname_out,
                                             window=dataset.num_partitions + 1,
                                             eof_when_done=eof_when_done):
            results[p] = part
        if not flat:
            return [part or [] for part in results]
        return [item for part in results for item in (part or [])]

    def inference_stream(self, data: Any, qname_in: str = "input",
                         qname_out: str = "output", window: int | None = None,
                         eof_when_done: bool = False):
        """Lazily yield ``(partition_index, results)`` in partition order.

        Restores the reference's lazy-RDD property
        (``TFCluster.py:~130-170``): partitions are read, scored, and yielded
        incrementally, so driver memory holds at most ``window`` completed
        partitions (default ``2 × feedable nodes``) — workers pause instead
        of running ahead of the consumer.

        ``eof_when_done=True`` sends end-of-feed to each node as soon as its
        share of partitions has been dispatched AND collected (instead of at
        shutdown).  REQUIRED for global-mesh scoring map_funs
        (``inference.sharded_bundle_inference_loop``): there, a node whose
        share ran out must learn it is done WHILE the driver is still
        collecting from its peers — its end-of-data consensus votes (and
        filler SPMD rounds) are what let the peers' remaining batches
        execute.  Leave False for task-parallel loops that should keep
        serving across multiple inference calls on one cluster.
        """
        if self.input_mode != InputMode.STREAMING:
            raise RuntimeError(
                "inference requires InputMode.STREAMING (reference: InputMode.SPARK); "
                "DIRECT-mode map_funs read files themselves and never consume the feed"
            )
        dataset = as_partitioned(data, default_partitions=len(self._feed_ids))
        num_workers = len(self._feed_ids)
        if eof_when_done:
            # Global-mesh scoring cannot be window-gated: a node whose next
            # partition is gated on earlier global output would stop feeding
            # its SPMD rounds while its peers wait for it in a collective —
            # a circular wait.  Sharded scoring therefore always dispatches
            # freely (driver may hold up to all partitions, as inference()
            # already does).
            window = dataset.num_partitions + 1
        window = window if window is not None else max(2 * num_workers, 4)
        buf: dict[int, list] = {}
        cond = threading.Condition()
        state = {"next": 0, "stopped": False, "done": 0}
        errors: list[Exception] = []

        def _infer_worker(worker_pos: int, executor_id: int) -> None:
            try:
                client = self._client(executor_id)
                for p in range(worker_pos, dataset.num_partitions, num_workers):
                    with cond:
                        cond.wait_for(lambda: p < state["next"] + window
                                      or state["stopped"])
                        if state["stopped"]:
                            return
                    part = client.infer_partition(dataset.iter_partition(p),
                                                  qname_in, qname_out)
                    with cond:
                        buf[p] = part
                        cond.notify_all()
                if eof_when_done:
                    client.send_eof(qname_in)
            except Exception as e:
                with cond:
                    errors.append(e)
                    cond.notify_all()
            finally:
                with cond:
                    state["done"] += 1
                    cond.notify_all()

        threads = [
            threading.Thread(target=_infer_worker, args=(pos, eid),
                             name=f"infer-{eid}", daemon=True)
            for pos, eid in enumerate(self._feed_ids)
        ]
        for t in threads:
            t.start()
        try:
            for p in range(dataset.num_partitions):
                with cond:
                    cond.wait_for(lambda: p in buf or errors
                                  or state["done"] == num_workers)
                    if errors:
                        raise RuntimeError(f"inference failed: {errors[0]}") from errors[0]
                    if p not in buf:
                        # every worker exited without error yet p is missing
                        self._raise_node_errors()
                        raise RuntimeError(f"inference lost partition {p}")
                    part = buf.pop(p)
                    state["next"] = p + 1
                    cond.notify_all()
                yield p, part
        finally:
            with cond:
                state["stopped"] = True
                cond.notify_all()
            for t in threads:
                t.join()
        self._raise_node_errors()
        if errors:
            # A worker that failed AFTER its last partition was collected
            # (e.g. send_eof) never trips the consumer loop's error check —
            # surface it here or the node silently misses its EOF and stalls
            # in next_batch until shutdown's kill timeout.
            raise RuntimeError(f"inference worker failed after all results were "
                               f"collected: {errors[0]}") from errors[0]

    # -- teardown (reference TFCluster.shutdown :~170-240, §3.5) -------------

    def shutdown(self, grace_secs: float = 0.0, timeout: float = 120.0) -> None:
        """Send end-of-feed, join node processes, propagate node errors."""
        if self._shutdown_done:
            return
        # Stop the dead-node monitor first: shutdown's own escalation
        # (join -> stop -> terminate) owns failure handling from here, and
        # nodes it terminates must not be re-reported as deaths.
        self._monitor_stop.set()
        try:
            # DIRECT-mode map_funs never consume the feed; EOF would just open
            # pointless connections to nodes that may already have exited.
            if self.input_mode == InputMode.STREAMING:
                # executor_id is assigned in REGISTRATION order, not launch
                # order — match processes through the launch_index each node
                # reported at registration (pids can't do this: over ssh
                # transports the local handle's pid is the ssh client).
                procs = self.launcher.processes
                id_to_proc = {
                    m["executor_id"]: procs[m["launch_index"]]
                    for m in self.cluster_info
                    if 0 <= m.get("launch_index", -1) < len(procs)
                }
                for executor_id in self._feed_ids:
                    proc = id_to_proc.get(executor_id)
                    if proc is not None and not proc.is_alive():
                        # node already finished and tore down its data plane;
                        # an EOF would only block on a dead peer
                        logger.debug("node %d already exited; skipping EOF",
                                     executor_id)
                        continue
                    for qname in self.input_qnames:
                        try:
                            self._client(executor_id).send_eof(qname)
                        except Exception:
                            proc = id_to_proc.get(executor_id)
                            if proc is not None and not proc.is_alive():
                                # Normal teardown race: the node finished its
                                # map_fun (e.g. inference loops exit on stop)
                                # and closed its data plane before EOF landed.
                                logger.debug("node %d exited before EOF on %r",
                                             executor_id, qname)
                                continue
                            # The cached client's socket may have died with an
                            # earlier timed-out call; this EOF is what unblocks
                            # the node's next_batch, so retry once on a FRESH
                            # connection before giving up.  One-shot socket
                            # client: no shm-ring negotiation just to deliver
                            # a ~20-byte EOF frame during teardown.
                            stale = self._clients.pop(executor_id, None)
                            if stale is not None:
                                with contextlib.suppress(Exception):
                                    stale.close()
                            try:
                                meta = self.cluster_info[executor_id]
                                retry = DataClient(meta["host"], meta["data_port"],
                                                   self.authkey, prefer_ring=False,
                                                   call_timeout=30.0,
                                                   stall_timeout=30.0)
                                try:
                                    retry.send_eof(qname)
                                finally:
                                    with contextlib.suppress(Exception):
                                        retry.close()
                            except Exception:
                                logger.warning(
                                    "could not send EOF to node %d queue %r",
                                    executor_id, qname, exc_info=True)
            if grace_secs:
                time.sleep(grace_secs)
            # Politely wait for map_funs to finish; only then escalate.  The
            # stop flag breaks in-flight barriers/reduces, so raising it early
            # would abort healthy nodes mid-collective.  The wait is
            # DEATH-AWARE: if a node stops heartbeating mid-join, survivors
            # may be wedged in a collective with the dead peer forever —
            # waiting out the full polite timeout would just delay the
            # inevitable escalation (SURVEY.md §5.3 prompt fail-fast).
            forced = False
            death_detected = False
            deadline = time.monotonic() + timeout
            while True:
                slice_ = min(2.0, max(0.05, deadline - time.monotonic()))
                if self.launcher.join(slice_):
                    break
                dead = self._record_deaths()
                if dead:
                    death_detected = True
                    logger.warning("nodes %s died during shutdown; escalating now", dead)
                if death_detected or time.monotonic() >= deadline:
                    alive = self.launcher.alive()
                    logger.warning("nodes %s still running; signalling stop", alive)
                    self.coordinator.signal_stop()  # heartbeats tell stragglers to stop
                    # with a confirmed death, survivors wedged in collectives
                    # never drain — keep the post-stop grace short
                    if not self.launcher.join(5.0 if death_detected else 15.0):
                        forced = True
                        logger.warning("nodes %s ignored stop; terminating", self.launcher.alive())
                        self.launcher.terminate()
                    break
            for c in self._clients.values():
                c.close()
            self._raise_node_errors()
            exit_codes = [p.exitcode for p in self.launcher.processes]
            if any(code is None for code in exit_codes):
                # survived SIGTERM+SIGKILL: a live zombie may still hold chips
                raise RuntimeError(f"node processes could not be killed (exit codes {exit_codes}); "
                                   f"zombie processes may be holding TPU devices")
            if forced:
                raise RuntimeError(f"node processes had to be force-terminated (exit codes {exit_codes})")
            if any(code != 0 for code in exit_codes):
                raise RuntimeError(f"node processes exited abnormally: {exit_codes}")
        finally:
            self._shutdown_done = True
            self.coordinator.stop()

    def _raise_node_errors(self) -> None:
        errs = self.coordinator.errors()
        if errs:
            tb = errs[0].get("traceback", "")
            raise RuntimeError(
                f"node {errs[0].get('executor_id')} failed "
                f"({len(errs)} node error(s) total):\n{tb}"
            )

    # -- observability (reference TFCluster.tensorboard_url :~240-260) -------

    def chip_plan(self):
        """Authoritative global chip numbering across the registered nodes
        (``tpu_info.plan_topology`` over each node's reported
        ``device_summary``, in executor-id order) — the driver-side
        replacement for the reference's per-executor randomized GPU picking
        (``gpu_info.py``; SURVEY.md §5.2 disposition).  Returns one
        ``HostAssignment`` per node; evaluators report their chips too but
        own no data-plane role."""
        from tensorflowonspark_tpu import tpu_info

        infos = self.coordinator.cluster_info()
        pending = [m["executor_id"] for m in infos
                   if (m.get("device") or {}).get("num_devices") is None]
        if pending:
            # jax_distributed nodes register a placeholder and report real
            # device facts only after jax.distributed.initialize — a plan
            # built from placeholders would be silently all-zero
            raise RuntimeError(
                f"chip plan unavailable: nodes {pending} have not reported "
                "device facts yet (distributed nodes report after their "
                "jax.distributed bootstrap); retry once the job is running")
        counts = [int((m.get("device") or {}).get("num_devices") or 0)
                  for m in infos]
        return tpu_info.plan_topology(counts)

    def tensorboard_url(self) -> str | None:
        for meta in self.coordinator.cluster_info():
            if "tb_url" in meta:
                return meta["tb_url"]
        return None


def _env_float(name: str, default: float) -> float:
    """Env-tunable default (reference: ``TFOS_SERVER_TIMEOUT``-style knobs,
    ``reservation.py:~120-160``): ops can raise cluster-formation / feed
    budgets fleet-wide without touching job code."""
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", name, raw)
        return default
    if value <= 0:
        # 0 is NOT "no timeout" here: it would make every data-plane put
        # fail instantly; fail safe to the default instead
        logger.warning("ignoring non-positive %s=%r", name, raw)
        return default
    return value


def run(
    map_fun: Callable,
    tf_args: Any = None,
    num_executors: int = 1,
    input_mode: InputMode = InputMode.DIRECT,
    master_node: str | None = None,
    eval_node: bool = False,
    tensorboard: bool = False,
    log_dir: str = "",
    default_fs: str = "",
    queues: Sequence[str] = ("input", "output", "error"),
    queue_capacity: int = 1024,
    feed_timeout: float | None = None,
    reservation_timeout: float | None = None,
    heartbeat_interval: float = 2.0,
    launcher: Any | None = None,
    env: dict[str, str] | None = None,
    per_node_env: Sequence[dict[str, str]] | None = None,
    jax_distributed: bool = False,
    coordinator_host: str | None = None,
) -> TPUCluster:
    """Start a cluster (reference ``TFCluster.run`` ``:~270-420``).

    No ``sc`` (no Spark), no ``num_ps`` (sync SPMD replaces parameter
    servers), no ``driver_ps_nodes``/``release_port`` (their race classes are
    designed out — SURVEY.md §5.2).

    ``env`` applies to every node; ``per_node_env`` (one dict per executor)
    layers per-process overrides on top — the carrier for disjoint
    accelerator slices (``tpu_info.chip_visibility_env``) when several node
    processes share a host.

    ``reservation_timeout``/``feed_timeout`` default from the
    ``TOS_RESERVATION_TIMEOUT``/``TOS_FEED_TIMEOUT`` env vars when not given
    (the reference's ``TFOS_SERVER_TIMEOUT``-style ops knobs), else
    120s/600s.

    ``coordinator_host`` pins the control-plane bind/advertise interface
    (default: bind all interfaces, advertise the routable ``local_ip()`` so
    remote executors launched over ssh can actually dial back — reference
    ``reservation.Server`` behavior).  The control plane authenticates every
    connection with the per-cluster ``authkey`` (HMAC challenge-response,
    same handshake as the data plane).
    """
    if reservation_timeout is None:
        reservation_timeout = _env_float("TOS_RESERVATION_TIMEOUT", 120.0)
    if feed_timeout is None:
        feed_timeout = _env_float("TOS_FEED_TIMEOUT", 600.0)
    if per_node_env is not None and len(per_node_env) != num_executors:
        raise ValueError(f"per_node_env needs {num_executors} entries, got {len(per_node_env)}")
    roles = _build_roles(num_executors, master_node, eval_node)
    authkey = secrets.token_bytes(16)
    coordinator = CoordinatorServer(num_executors, roles, authkey=authkey)
    addr = coordinator.start(coordinator_host)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)

    configs = [
        NodeConfig(
            coordinator_addr=addr,
            authkey=authkey,
            map_fun=map_fun,
            tf_args=tf_args,
            queues=tuple(queues),
            input_qnames=tuple(q for q in queues if q not in ("output", "error")),
            queue_capacity=queue_capacity,
            feed_timeout=feed_timeout,
            reservation_timeout=reservation_timeout,
            heartbeat_interval=heartbeat_interval,
            default_fs=default_fs,
            log_dir=log_dir,
            tensorboard=tensorboard,
            jax_distributed=jax_distributed,
            env={**(env or {}), **(per_node_env[i] if per_node_env else {})},
            launch_index=i,
        )
        for i in range(num_executors)
    ]
    # Default to SubprocessLauncher: children run the lean ``node_entry``
    # module directly (~0.5s to a live node), where multiprocessing-spawn
    # re-imports the driver's __main__ machinery in every child (~3s under
    # pytest), and OS-level env lands before any site hook can import jax.
    launcher = launcher or SubprocessLauncher()
    launcher.launch(configs, log_dir or None)
    try:
        cluster_info = coordinator.await_registrations(reservation_timeout)
    except TimeoutError:
        launcher.terminate()
        coordinator.stop()
        raise
    logger.info("cluster up: %s", [(m["executor_id"], m["job_name"]) for m in cluster_info])
    return TPUCluster(coordinator, launcher, cluster_info, authkey, input_mode,
                      queues, feed_timeout, heartbeat_interval)
