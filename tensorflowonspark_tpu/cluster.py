"""Driver-side cluster lifecycle API — the ``TFCluster`` replacement.

Reference (``tensorflowonspark/TFCluster.py``): ``run()`` ``:~270-420`` builds
the role template, starts the reservation server, launches node closures on
executors, and returns a cluster handle with ``train`` ``:~70-130``,
``inference`` ``:~130-170``, ``shutdown`` ``:~170-240`` and
``tensorboard_url`` ``:~240-260``; ``InputMode`` at ``:~40``.

TPU-native deltas (BASELINE.json:5, SURVEY.md §2.3):
- **No parameter servers.** ``num_ps`` is gone; async PS data parallelism is
  replaced by sync SPMD data parallelism (XLA all-reduce over ICI inside the
  jitted train step).  Roles are chief/worker/evaluator only.
- **Launcher abstraction** instead of Spark: ``LocalLauncher`` (default) or a
  TPU-pod launcher place node processes; partitions stream over the data
  plane (``dataserver.py``) rather than Spark feed tasks.
- ``InputMode.DIRECT`` (framework reads files itself — the reference's
  ``InputMode.TENSORFLOW``) vs ``InputMode.STREAMING`` (driver streams
  partitions — the reference's ``InputMode.SPARK``).  Aliases with the
  reference names are provided.
"""

from __future__ import annotations

import collections
import contextlib
import enum
import glob
import json
import logging
import os
import secrets
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_condition, tos_named_lock
import time
from typing import Any, Callable, Sequence

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu.coordinator import CoordinatorServer
from tensorflowonspark_tpu.telemetry import trace as ttrace
from tensorflowonspark_tpu.telemetry import trace_export as ttrace_export
from tensorflowonspark_tpu.data import as_partitioned
from tensorflowonspark_tpu.dataserver import DataClient
from tensorflowonspark_tpu.launcher import (  # noqa: F401 - LocalLauncher re-exported
    LocalLauncher,
    SubprocessLauncher,
    TPUPodLauncher,
)
from tensorflowonspark_tpu.node import NodeConfig
from tensorflowonspark_tpu.supervisor import RestartPolicy, Supervisor
from tensorflowonspark_tpu.utils.envtune import env_bool as _env_bool
from tensorflowonspark_tpu.utils.envtune import env_float as _env_float
from tensorflowonspark_tpu.utils.envtune import env_int as _env_int

logger = logging.getLogger(__name__)


class InputMode(enum.Enum):
    """Reference ``TFCluster.InputMode`` (``TFCluster.py:~40``).

    What each mode supports (this table matches runtime behavior — every
    mode-mismatch error names the mode that IS supported):

    ========================  =======================  ======================
    API                       DIRECT (≈ TENSORFLOW)    STREAMING (≈ SPARK)
    ========================  =======================  ======================
    ``train(data)``           ``data`` = shard path/   ``data`` = rows
                              glob/dir; the ledger     (PartitionedDataset /
                              feeds shard PATHS,       iterable); the driver
                              nodes read the bytes     streams every row
    ``ctx.get_data_feed()``   ``ingest.IngestFeed``    ``feeding.DataFeed``
                              (node-side readers)      (driver-streamed)
    ``inference()``           unsupported — use        supported (ordered,
                              STREAMING, or score      exactly-count)
                              via ``serve()``
    ``serve()``               supported                supported
    ========================  =======================  ======================

    DIRECT map_funs may also ignore the feed entirely and read files
    self-service (``dfutil.shard_files`` strided by ``ctx.executor_id`` —
    the ``examples/mnist/mnist_tfr.py`` idiom); the ledger-driven path feed
    is what adds at-least-once re-feed and elastic recovery on top.
    """

    DIRECT = 0      # nodes read sharded files themselves (reference: TENSORFLOW)
    STREAMING = 1   # driver streams partitions into node feeds (reference: SPARK)

    # Drop-in aliases for TensorFlowOnSpark users.
    TENSORFLOW = 0
    SPARK = 1


def _build_roles(num_executors: int, master_node: str | None, eval_node: bool) -> list[tuple[str, int]]:
    """Role template (reference ``TFCluster.py:~290-330``, minus ``ps``)."""
    roles: list[tuple[str, int]] = []
    chief_name = master_node or "chief"
    roles.append((chief_name, 0))
    num_workers = num_executors - 1 - (1 if eval_node else 0)
    if num_workers < 0:
        raise ValueError("num_executors too small for the requested roles")
    roles.extend(("worker", i) for i in range(num_workers))
    if eval_node:
        roles.append(("evaluator", 0))
    return roles


class _PartitionLedger:
    """Driver-side record of every (epoch, partition) a ``train()`` call must
    deliver: queued on its home slot, in flight on an executor, done, or
    abandoned.

    The reference got this bookkeeping from Spark's task scheduler — a dead
    executor's partition-feed task was simply rerun elsewhere (PAPER.md
    §5.3); with Spark gone the ledger reinstates it driver-side.  Placement
    stays the reference's deterministic round-robin (partition ``i`` belongs
    to feedable slot ``i % W``) while every slot is healthy; when a slot's
    feed fails, its unacknowledged task moves to a shared *orphan* pool that
    any worker — a surviving peer, or the slot's own supervised restart —
    drains once its home queue is empty.  Training is therefore
    at-least-once: a partition whose feed died mid-stream is re-fed from the
    top, and the consumer may see some of its items twice.

    An ack means ``feed_partition`` returned cleanly — the node BUFFERED the
    whole partition + its EndPartition marker, not that the map_fun consumed
    it.  A sudden death takes the queue's buffered tail down with it, so
    acked tasks stay on a per-slot *delivered* list until the node's
    consumption watermark (partitions whose EndPartition the map_fun popped,
    reported with each ack) passes them; when recovery observes an actual
    restart (fresh process, empty queues) the still-unconsumed window is
    re-delivered via ``requeue_unconsumed`` — duplicates allowed, loss not.
    The watermark baseline is conservative (first report after a (re)start
    anchors it), which can only over-requeue, never under.
    """

    def __init__(self, num_partitions: int, num_epochs: int, num_slots: int,
                 max_attempts: int = 3, journal_fn: Callable | None = None,
                 train_gen: int = 0):
        # Control-plane journal rider (ISSUE 13): assign/ack/requeue events
        # append to the coordinator's write-ahead journal so a postmortem
        # (or a future cold-start resume) can reconstruct exact partition
        # accounting across a control-plane failover.  ``journal_fn`` is a
        # callable returning the LIVE Journal (or None mid-crash) — the
        # instance is replaced by every recovery, so it is never cached.
        self._journal_fn = journal_fn
        self._train_gen = train_gen
        self._cond = tos_named_condition("cluster.ledger._cond")
        self._own = [
            collections.deque((e, p)
                              for e in range(num_epochs)
                              for p in range(pos, num_partitions, num_slots))
            for pos in range(num_slots)
        ]
        self._orphans: collections.deque = collections.deque()
        self._inflight: dict[int, tuple[int, int]] = {}
        # whether the slot's in-flight task came from the orphan pool: a
        # terminating consumer may forfeit its OWN share, but a dead peer's
        # requeued work is not its to drop (abandon_slot)
        self._inflight_orphan: dict[int, bool] = {}
        self._attempts: dict[tuple[int, int], int] = {}
        # buffered-on-the-node but not yet known-consumed, in feed order
        self._delivered: list[collections.deque] = [
            collections.deque() for _ in range(num_slots)]
        self._watermark: list[int | None] = [None] * num_slots
        self._outstanding = num_partitions * num_epochs
        self._failure: Exception | None = None
        # slots deliberately drained out mid-run (cluster.resize scale-in):
        # their next_task answers None even with work outstanding — the
        # home queue went to the orphan pool and survivors deliver it
        self._retired_slots: set[int] = set()
        self.max_attempts = max_attempts

    def _note(self, ev: str, pos: int | None, task: tuple | None = None,
              **extra) -> None:
        """Best-effort journal rider for one ledger event (caller may hold
        ``_cond``; the journal has its own lock).  Failures are logged and
        swallowed — the in-memory ledger stays authoritative for the run."""
        if self._journal_fn is None:
            return
        journal = self._journal_fn()
        if journal is None:
            return  # control plane mid-failover; the ledger itself survives
        try:
            # sync=False: ledger riders are flight evidence replay treats as
            # no-ops — an fsync here would serialize every feed worker on
            # disk flushes under the ledger condition for nothing recovery
            # needs (the next mutation append / snapshot flushes them)
            journal.append("ledger", {"ev": ev, "gen": self._train_gen,
                                      "slot": pos,
                                      "task": list(task) if task else None,
                                      **extra}, sync=False)
        except Exception:  # noqa: BLE001 - journaling must not break feeding
            logger.debug("ledger journal append failed", exc_info=True)

    def add_slot(self) -> int:
        """Admit one more feed slot mid-run (cluster.resize scale-out);
        returns its position.  The new slot starts with an empty home queue
        — call :meth:`rebalance_to` to shift pending work onto it, and it
        drains the shared orphan pool either way."""
        with self._cond:
            self._own.append(collections.deque())
            self._delivered.append(collections.deque())
            self._watermark.append(None)
            self._cond.notify_all()
            return len(self._own) - 1

    def rebalance_to(self, pos: int) -> int:
        """Move a fair share of still-queued (never-dispatched) home tasks
        from the most-loaded peers onto slot ``pos`` — how a scale-out
        newcomer gets work NOW instead of waiting for requeues.  Tasks are
        taken from the TAIL of peers' queues (their far-future work), so
        every slot keeps delivering its near-term partitions in order.
        Returns how many tasks moved."""
        with self._cond:
            total = sum(len(q) for q in self._own) + len(self._orphans)
            slots = len(self._own) - len(self._retired_slots)
            target = total // max(1, slots)
            moved = 0
            while len(self._own[pos]) < target:
                donor = max((i for i in range(len(self._own))
                             if i != pos and i not in self._retired_slots),
                            key=lambda i: len(self._own[i]), default=None)
                if donor is None or len(self._own[donor]) <= target:
                    break
                self._own[pos].append(self._own[donor].pop())
                moved += 1
            if moved:
                self._cond.notify_all()
            return moved

    def retire_slot(self, pos: int) -> int:
        """Scale-in: stop assigning slot ``pos`` new work and hand its
        still-queued home tasks to the orphan pool for survivors to deliver.
        Its in-flight task (if any) finishes normally, and its
        acked-but-unconsumed window drains through the usual watermark path
        (the node consumes its buffered queue in FIFO order before the
        retirement EOF reaches it).  Returns how many tasks moved."""
        with self._cond:
            moved = len(self._own[pos])
            self._orphans.extend(self._own[pos])
            self._own[pos].clear()
            self._retired_slots.add(pos)
            self._cond.notify_all()
            self._note("retire_slot", pos, moved=moved)
            return moved

    def slot_idle(self, pos: int) -> bool:
        """True when the slot has no queued home work and no in-flight feed
        — the point at which a retirement EOF cannot truncate a partition
        mid-stream (everything acked is fully buffered ahead of it)."""
        with self._cond:
            return not self._own[pos] and pos not in self._inflight

    def slot_retired(self, pos: int) -> bool:
        with self._cond:
            return pos in self._retired_slots

    def next_task(self, pos: int) -> tuple[int, int] | None:
        """Block until slot ``pos`` has work (home queue first, then orphans)
        or the feed is over; None means stop (all resolved, retired slot, or
        failed)."""
        with self._cond:
            while True:
                if self._failure is not None:
                    return None
                if pos in self._retired_slots:
                    return None
                if self._own[pos]:
                    task = self._own[pos].popleft()
                    self._inflight_orphan[pos] = False
                elif self._orphans:
                    task = self._orphans.popleft()
                    self._inflight_orphan[pos] = True
                elif self._outstanding == 0:
                    return None
                else:
                    # work may still be requeued by a failing peer
                    self._cond.wait(0.5)
                    continue
                self._inflight[pos] = task
                self._attempts[task] = self._attempts.get(task, 0) + 1
                self._note("assign", pos, task,
                           attempt=self._attempts[task])
                return task

    def attempts(self, task: tuple[int, int]) -> int:
        with self._cond:
            return self._attempts.get(task, 0)

    def ack(self, pos: int, consumed: int | None = None) -> None:
        """The slot's in-flight partition was fully BUFFERED on the node;
        ``consumed`` is the node's cumulative consumption watermark as of
        this ack (None when the node predates the watermark protocol)."""
        with self._cond:
            task = self._inflight.pop(pos, None)
            if task is not None:
                self._delivered[pos].append(task)
                self._outstanding -= 1
                self._cond.notify_all()
                self._note("ack", pos, task, consumed=consumed)
            self._advance_watermark_locked(pos, consumed)

    def update_watermark(self, pos: int, consumed: int | None) -> None:
        """Standalone watermark report (tail drain: the slot's feeds are all
        acked, the driver polls the node for consumption progress)."""
        with self._cond:
            self._advance_watermark_locked(pos, consumed)

    def _advance_watermark_locked(self, pos: int, consumed: int | None) -> None:
        if consumed is None:
            return
        if self._watermark[pos] is None or consumed < self._watermark[pos]:
            # first report since this (re)started process: anchor only —
            # the count may include consumption the ledger never saw
            # (an earlier train() on a reused cluster), so advancing on
            # it could drop un-consumed work
            self._watermark[pos] = consumed
            return
        delta = consumed - self._watermark[pos]
        self._watermark[pos] = consumed
        for _ in range(min(delta, len(self._delivered[pos]))):
            self._delivered[pos].popleft()

    def needs_drain(self, pos: int) -> bool:
        """True while the slot has acked-but-not-known-consumed partitions —
        work a sudden death would still take down with the node's queue."""
        with self._cond:
            return self._failure is None and bool(self._delivered[pos])

    def failed(self) -> bool:
        with self._cond:
            return self._failure is not None

    def requeue(self, pos: int) -> tuple[int, int] | None:
        """Return the slot's unacknowledged task to the orphan pool (any
        surviving or restarted worker may take it); returns that task."""
        with self._cond:
            task = self._inflight.pop(pos, None)
            if task is not None:
                self._orphans.append(task)
                self._cond.notify_all()
                self._note("requeue", pos, task)
            return task

    def requeue_unconsumed(self, pos: int) -> int:
        """The slot's process RESTARTED (fresh empty queues): every
        buffered-but-not-known-consumed task died with the predecessor's
        queue — put them back in play.  Only correct after an actual
        restart; on a mere socket loss the healthy node will still drain
        its buffer and re-delivery would be pure duplication."""
        with self._cond:
            n = len(self._delivered[pos])
            self._orphans.extend(self._delivered[pos])
            self._delivered[pos].clear()
            self._watermark[pos] = None  # replacement counts from zero
            self._outstanding += n
            if n:
                self._cond.notify_all()
                self._note("requeue_unconsumed", pos, count=n)
            return n

    def abandon_slot(self, pos: int) -> None:
        """The slot's consumer said 'terminating': resolve its remaining home
        tasks (and its in-flight one, if it was its own) as deliberately
        dropped — reference semantics, an early-terminating node forfeits the
        rest of its share.  An in-flight task acquired from the ORPHAN pool
        is a dead peer's work, not this slot's to forfeit: it goes back for a
        surviving or restarted worker to deliver.  Acked-but-unconsumed
        partitions are forfeited either way: the consumer chose to stop with
        them buffered."""
        with self._cond:
            dropped = len(self._own[pos])
            self._own[pos].clear()
            task = self._inflight.pop(pos, None)
            if task is not None:
                if self._inflight_orphan.get(pos):
                    self._orphans.append(task)
                else:
                    dropped += 1
            self._delivered[pos].clear()  # forfeited, not lost
            self._outstanding -= dropped
            self._cond.notify_all()
            self._note("abandon", pos, dropped=dropped)

    def fail(self, exc: Exception) -> None:
        """Unrecoverable: wake every worker with a stop answer."""
        with self._cond:
            if self._failure is None:
                self._failure = exc
                self._note("fail", None, reason=str(exc)[:200])
            self._cond.notify_all()


class TPUCluster:
    """Handle to a running cluster (reference ``class TFCluster``)."""

    def __init__(
        self,
        coordinator: CoordinatorServer,
        launcher: LocalLauncher,
        cluster_info: list[dict],
        authkey: bytes,
        input_mode: InputMode,
        queues: Sequence[str],
        feed_timeout: float,
        heartbeat_interval: float = 2.0,
        elastic: bool | RestartPolicy = False,
        log_dir: str = "",
    ):
        self.coordinator = coordinator
        self.launcher = launcher
        self.cluster_info = cluster_info
        self.authkey = authkey
        self.input_mode = input_mode
        self.queues = queues
        self.log_dir = log_dir
        self._started_at = time.monotonic()
        self.input_qnames = [q for q in queues if q not in ("output", "error")]
        self.feed_timeout = feed_timeout
        self.heartbeat_interval = heartbeat_interval
        self._clients: dict[int, DataClient] = {}
        # incarnation each cached client was built against — the recovery
        # baseline "which process was I talking to when the call failed"
        # (reading the slot's CURRENT incarnation at failure time would miss
        # a restart that completed while the failed call was still blocked)
        self._client_incs: dict[int, int] = {}
        # executor_id -> (ledger, slot) while a train() feed is live, so the
        # dead-node monitor can re-deliver a dead slot's unconsumed window
        self._active_ledger: dict[int, tuple] = {}
        # Monotonic per-train() generation, prefixed onto every EndPartition
        # dedupe key: node-side FeedQueues outlive a train() call on a reused
        # cluster, and without the prefix a second train()'s (epoch,
        # partition) keys would all hit the first train()'s seen-set, freeze
        # the consumption watermark, and stall every slot's tail drain.
        self._train_gen = 0
        self._shutdown_done = False
        # Feedable nodes: everything except the evaluator (the reference also
        # excluded ps nodes; we have none) and the data-service tier —
        # ingest workers are fed the DIRECT ledger's shard items, trainers
        # are fed rows/paths, and the two lists must never mix.
        self._feed_ids = [m["executor_id"] for m in cluster_info
                          if m["job_name"] not in ("evaluator", "ingest")]
        # Disaggregated ingest tier (ingest/service.py): standalone
        # data-service nodes (role "ingest") that claim shard items from
        # the partition ledger and stream decoded chunks to the trainers.
        # When present, a DIRECT-mode train() feeds THESE slots.
        self._ingest_ids = [m["executor_id"] for m in cluster_info
                            if m["job_name"] == "ingest"]
        # Dead-node monitor (SURVEY.md §5.3 — the role Spark played for the
        # reference: the driver NOTICES executor death instead of waiting for
        # a feed/barrier/collective timeout to expire).  A node whose
        # heartbeat goes silent past the window is recorded as a node error,
        # and the stop signal both aborts in-flight control-plane
        # barriers/reduces and tells surviving nodes to stop — so blocked
        # train()/inference() calls unblock within seconds, not
        # feed_timeout.  Clean exits deregister first and are never flagged.
        self._dead_after = _env_float("TOS_DEAD_NODE_TIMEOUT",
                                      max(12.0, 6.0 * heartbeat_interval))
        # Window for an in-flight death to be DECLARED (monitor poll +
        # heartbeat silence) — _recover_client and _drain_slot_tail both key
        # their "is this slot healthy / cleanly exited" judgements on the
        # same window, and they must not drift apart.
        self._declare_grace = self._dead_after + 3.0 * max(1.0, heartbeat_interval)
        # Elastic recovery (supervisor.py): data-node deaths become supervised
        # restarts instead of job failures; feed workers ride out the restart
        # window (TOS_RECOVERY_TIMEOUT) and re-feed unacknowledged partitions.
        self.supervisor: Supervisor | None = None
        if elastic:
            policy = elastic if isinstance(elastic, RestartPolicy) else None
            self.supervisor = Supervisor(coordinator, launcher, policy)
        # Control-plane crash recovery (ISSUE 13): a journaled coordinator
        # gets a supervisor of its own — crash() wakes it, it waits out the
        # budgeted backoff, and restore() replays the journal under a bumped
        # epoch.  Independent of `elastic` (node restarts need respawnable
        # processes; the coordinator restarts in-process from its journal).
        self.coordinator_supervisor = None
        if getattr(coordinator, "journal_enabled", False):
            from tensorflowonspark_tpu.supervisor import CoordinatorSupervisor

            self.coordinator_supervisor = CoordinatorSupervisor(coordinator)
        self._recovery_timeout = _env_float("TOS_RECOVERY_TIMEOUT", 90.0)
        self._max_feed_attempts = _env_int("TOS_MAX_PARTITION_ATTEMPTS", 3)
        # Online serving gateways opened via serve(); closed at shutdown so
        # their routers stop before the feed gets its EOFs.
        self._gateways: list = []
        # Elastic autoscaling (resize / autoscale):
        # - _resize_lock serializes resize() calls (policy loop + user);
        # - _train_lock guards the live train() session handle so a
        #   scale-out can attach a feed worker to an in-flight train();
        # - _retiring marks slots mid-drain (the monitor treats their death
        #   as retirement, never as a recovery candidate);
        # - _audit_waived launch indexes are excluded from shutdown's
        #   exit-code audit (a retired node we terminated, or one killed
        #   mid-drain, must not fail the job post-hoc);
        # - _resize_log / _autoscalers feed the run report's autoscale block;
        # - _closing gates resize() off (and short-circuits an in-flight
        #   drain) the moment shutdown begins, so teardown never races a
        #   resize mutating _feed_ids.
        self._closing = threading.Event()
        self._resize_lock = tos_named_lock("cluster._resize_lock")
        self._train_lock = tos_named_lock("cluster._train_lock")
        self._train_session: dict | None = None
        # live inference() calls (guarded by _train_lock): scale-in refuses
        # while one is in flight — its partitions are statically assigned
        self._inference_live = 0
        self._retiring: set[int] = set()
        self._audit_waived: set[int] = set()
        self._resize_log: list[dict] = []
        self._autoscalers: list = []
        # Feed pump: one sender per node connection (the train/inference
        # worker threads), chunk sends pipelined per connection
        # (TOS_SEND_WINDOW in DataClient) and optionally capped fleet-wide
        # (TOS_SENDER_POOL); the gate is installed on every cached client.
        self._sender_gate = self._make_sender_gate()
        self._monitor_stop = threading.Event()
        self._monitor = threading.Thread(target=self._monitor_loop, daemon=True,
                                         name="dead-node-monitor")
        self._monitor.start()
        # Periodic TensorBoard export of the aggregated cluster metrics
        # (TOS_METRICS_EXPORT_SECS cadence; scalars land under
        # <log_dir>/metrics via summary.SummaryWriter) — TFoS parity: the
        # reference's only live dashboard was TensorBoard, so the metrics
        # subsystem surfaces there too, not just in cluster.metrics().
        self._export_stop = threading.Event()
        self._export_thread: threading.Thread | None = None
        if log_dir and telemetry.enabled():
            self._export_thread = threading.Thread(
                target=self._metrics_export_loop, daemon=True,
                name="metrics-export")
            self._export_thread.start()

    def _record_deaths(self, record_error: bool = True) -> list[int]:
        """Role-aware death bookkeeping, shared by the monitor thread and
        shutdown's death-aware join.  The evaluator is an optional SIDECAR —
        no feed, no collectives — so its death is logged and forgotten
        (training continues; reference parity: a failed auxiliary executor
        didn't fail the job).  Data-node deaths are declared (incarnation
        fenced, in-flight rendezvous aborted) and the newly-declared ids are
        returned for the caller to escalate on; ``record_error=False`` is the
        elastic path — a death the supervisor will recover from must not
        leave a fatal node error behind."""
        dead = self.coordinator.dead_nodes(self._dead_after)
        dead_eval = [i for i in dead if i not in self._feed_ids
                     and i not in self._ingest_ids]
        if dead_eval:
            logger.warning("evaluator node(s) %s stopped heartbeating; "
                           "training continues without them", dead_eval)
            self.coordinator.forget(dead_eval)
        # ingest workers are DATA slots for death handling: their ledger
        # windows requeue and the supervisor recovers them exactly like a
        # trainer's — the elastic contract of the disaggregated tier
        dead_data = [i for i in dead
                     if i in self._feed_ids or i in self._ingest_ids]
        newly: list[int] = []
        # A slot mid-retirement (resize scale-in) dies ON PURPOSE or at
        # worst mid-drain: declare it (fence + rendezvous abort) but never
        # record a fatal node error — the ledger re-feed owns its partitions
        # and resize owns its teardown, elastic or not.
        retiring = [i for i in dead_data if i in self._retiring]
        if retiring:
            newly.extend(self.coordinator.mark_dead(retiring,
                                                    record_error=False))
        rest = [i for i in dead_data if i not in self._retiring]
        if rest:
            newly.extend(self.coordinator.mark_dead(rest,
                                                    record_error=record_error))
        return newly

    def _requeue_dead_slot(self, executor_id: int) -> None:
        """A slot's process is gone (death, or kill mid-drain): put its
        in-flight partition AND its buffered-but-unconsumed window back in
        play, and tear down its cached data client so no feed worker stays
        wedged dialing the dead peer."""
        entry = self._active_ledger.get(executor_id)
        if entry is not None:
            entry[0].requeue(entry[1])
            n = entry[0].requeue_unconsumed(entry[1])
            if n:
                logger.warning("re-delivering %d buffered partition(s) "
                               "node %d died holding", n, executor_id)
        self._drop_client(executor_id, abort=True)

    def _handle_collective_events(self) -> None:
        """React to gray-failure evictions/readmissions the coordinator
        adjudicated (quorum of survivor suspicion votes): an EVICTED slot's
        process is alive-but-benched, so the supervisor PARKS it (no
        respawn — a replacement would split-brain the slot) and its ledger
        slot retires (queued partitions rebalance to survivors, exactly the
        scale-in machinery); a READMITTED slot unparks and — when a train()
        is live — grows back in through the scale-out attach path.  A
        benched process that stops heartbeating altogether is REAPED into
        an ordinary death (eviction must not hide a real corpse forever):
        unparked and handed to the supervisor like any other death."""
        self.coordinator.reap_silent_probation(self._dead_after)
        for ev in self.coordinator.drain_collective_events():
            eid = int(ev["eid"])
            if ev["kind"] == "evicted":
                logger.warning("node %d evicted from collective group %r "
                               "(gray failure); benching its feed slot",
                               eid, ev.get("group"))
                if self.supervisor is not None:
                    self.supervisor.park(eid)
                self._evict_slot_work(eid)
            elif ev["kind"] == "readmitted":
                if self.supervisor is not None:
                    self.supervisor.unpark(eid)
                if self._attach_train_slot(eid):
                    logger.info("readmitted node %d re-attached to the "
                                "live feed", eid)
            elif ev["kind"] == "probation_death":
                self._requeue_dead_slot(eid)
                if self.supervisor is not None:
                    self.supervisor.unpark(eid)
                    self.supervisor.handle_death(eid)

    def _evict_slot_work(self, executor_id: int) -> None:
        """Rebalance an evicted slot's feed work onto survivors: retire its
        ledger slot (no new assignments; queued partitions move — the
        autoscale retire machinery), re-deliver its in-flight and
        buffered-but-unconsumed window, and drop its cached data client so
        no feed worker stays wedged against the benched peer.  The PROCESS
        stays alive in probation; readmission re-attaches a fresh slot."""
        with self._train_lock:
            entry = self._active_ledger.pop(executor_id, None)
        if entry is None:
            return
        ledger, pos = entry
        ledger.requeue(pos)
        moved = ledger.retire_slot(pos)
        n = ledger.requeue_unconsumed(pos)
        if moved or n:
            logger.warning("evicted node %d: %d queued partition(s) "
                           "rebalanced to survivors, %d buffered "
                           "re-delivered", executor_id, moved, n)
        self._drop_client(executor_id, abort=True)

    def _monitor_loop(self) -> None:
        poll = max(1.0, self.heartbeat_interval)
        while not self._monitor_stop.wait(poll):
            try:
                self._handle_collective_events()
            except Exception:  # noqa: BLE001 - eviction bookkeeping must not kill the monitor
                logger.warning("collective eviction bookkeeping failed",
                               exc_info=True)
            newly = self._record_deaths(
                record_error=(self.supervisor is None))
            # Retiring slots first: their death mid-drain is part of the
            # plan — requeue their ledger window (survivors deliver it) and
            # never escalate; resize's reaper finalizes the retirement.
            fatal: list[int] = []
            for eid in newly:
                if eid in self._retiring:
                    logger.warning("retiring node %d died mid-drain; its "
                                   "partitions re-feed to survivors", eid)
                    self._requeue_dead_slot(eid)
                    continue
                fatal.append(eid)
            if self.supervisor is not None:
                # Elastic path: the death is declared WITHOUT a fatal node
                # error and handed to the supervisor; monitoring continues —
                # further deaths (including the replacement's) re-enter here.
                for eid in fatal:
                    logger.warning("node %d stopped heartbeating (>%.0fs); "
                                   "scheduling supervised restart",
                                   eid, self._dead_after)
                    # dead process = dead queue: its in-flight partition AND
                    # its buffered-but-unconsumed window go back in play
                    # BEFORE the restart begins.  The in-flight requeue
                    # matters on a blackholed host: the slot's feed worker is
                    # still wedged inside feed_partition riding out
                    # call_timeout, and without it the task would stay pinned
                    # (and every surviving worker spin-waiting on it) for the
                    # full ~11-minute socket budget; the worker's own later
                    # requeue is then a safe no-op.  The client teardown
                    # matters for the same reason: a worker blocked inside a
                    # dead ring peer (no RST) is woken instead of waited on.
                    self._requeue_dead_slot(eid)
                    self.supervisor.handle_death(eid)
                continue
            if fatal:
                logger.error("nodes %s stopped heartbeating (>%.0fs); failing "
                             "in-flight work and signalling stop",
                             fatal, self._dead_after)
                self.coordinator.signal_stop()
                return

    def dead_nodes(self) -> list[int]:
        """Executor ids currently past the heartbeat window (diagnostic)."""
        return self.coordinator.dead_nodes(self._dead_after)

    # -- data-plane connections ---------------------------------------------

    def _make_sender_gate(self) -> Callable[[], Any]:
        """Send-permit factory for the feed pump (``TOS_SENDER_POOL``):
        0/unset means every node connection sends concurrently (one sender
        thread each); N > 0 bounds how many are mid-send at once.  The
        permit is acquired by ``DataClient`` around individual CHUNK sends
        — never across a whole partition round-trip, where one stalled
        node's backpressure (or a node's inference compute) would pin a
        permit and starve every other connection."""
        pool = _env_int("TOS_SENDER_POOL", 0, minimum=0)
        if pool <= 0:
            return contextlib.nullcontext
        sem = threading.BoundedSemaphore(pool)

        @contextlib.contextmanager
        def _permit():
            with sem:
                yield

        return _permit

    def _fresh_meta(self, executor_id: int) -> dict:
        """Current node meta from the coordinator, not the formation-time
        snapshot: a supervised restart re-registered this slot with a NEW
        host/data_port, and the snapshot would dial the dead one."""
        return (self.coordinator.node_meta(executor_id)
                or self.cluster_info[executor_id])

    def _client(self, executor_id: int, *, connect_timeout: float = 60.0,
                connect_attempts: int | None = None) -> DataClient:
        # Return the looked-up/constructed instance, never a second dict
        # read: the monitor's _drop_client(abort=True) may pop the entry
        # concurrently with a death declaration, and a re-lookup here would
        # KeyError — the caller still holds a usable (if doomed) client whose
        # next call surfaces the real data-plane failure instead.
        client = self._clients.get(executor_id)
        if client is None:
            meta = self._fresh_meta(executor_id)
            inc, _ = self.coordinator.registered_incarnation(executor_id)
            # Record the targeted incarnation BEFORE dialing: even a failed
            # dial establishes the recovery baseline "which process was I
            # trying to reach", which _recover_client compares restarts
            # against.
            self._client_incs[executor_id] = inc
            client = DataClient(
                meta["host"], meta["data_port"], self.authkey,
                call_timeout=self.feed_timeout + 60.0,
                stall_timeout=self.feed_timeout,
                connect_timeout=connect_timeout,
                connect_attempts=connect_attempts)
            client.sender_gate = self._sender_gate
            self._clients[executor_id] = client
        return client

    def _drop_client(self, executor_id: int, *, abort: bool = False) -> None:
        """Discard (and best-effort close) the slot's cached data client —
        its socket/ring died with the failure that led here.  ``abort=True``
        (the monitor's death declaration) tears the socket down WITHOUT the
        per-client lock, so a feed worker wedged mid-call on the dead peer is
        woken instead of waited on."""
        stale = self._clients.pop(executor_id, None)
        if stale is not None:
            with contextlib.suppress(Exception):
                stale.abort() if abort else stale.close()

    def _recover_client(self, executor_id: int, *,
                        require_restart: bool = False,
                        cancel: Callable[[], bool] | None = None) -> DataClient | None:
        """After a data-plane failure on ``executor_id``: wait out the slot's
        restart window and hand back a fresh client, or None when the slot
        cannot (or must not) be re-fed.  ``cancel`` lets the caller's job
        abort this wait early (a peer already failed the whole feed — pinning
        its join on this slot's 90s window would only delay that error).

        ``require_restart=True`` is the inference rule: only a *restarted*
        node (fresh process, empty queues — observable as a bumped
        incarnation) may be re-fed, because a healthy node whose socket
        merely severed can still hold partial results of the failed attempt
        in its output queue, and a re-feed would corrupt the exactly-count
        invariant.  Training re-feeds either way (at-least-once).
        """
        # Baseline = the incarnation the FAILED client was talking to (kept
        # by _client/_drop_client), not the slot's current one: a restart
        # that completed while the failed call was still blocked (e.g. a
        # zombie riding out stall_timeout) already bumped the current value.
        inc0 = self._client_incs.get(
            executor_id, self.coordinator.registered_incarnation(executor_id)[0])
        deadline = time.monotonic() + self._recovery_timeout
        grace_end = time.monotonic() + self._declare_grace
        while time.monotonic() < deadline and not self._shutdown_done:
            if cancel is not None and cancel():
                return None
            if (self.supervisor is not None
                    and self.supervisor.permanently_failed(executor_id) is not None):
                return None
            inc, tracked = self.coordinator.registered_incarnation(executor_id)
            restarted = inc > inc0
            if tracked and (restarted or not require_restart):
                try:
                    # Short bounded dial: the outer loop is the retry.  The
                    # default 60s x 3-attempt dial would let one blackholed
                    # host pin this thread minutes past _recovery_timeout.
                    return self._client(executor_id, connect_timeout=5.0,
                                        connect_attempts=1)
                except Exception:  # noqa: BLE001 - port dark mid-restart
                    time.sleep(0.5)
                    continue
            if not tracked:
                if self.supervisor is None:
                    return None  # declared dead with nobody to revive it
                if (not self.supervisor.restarting(executor_id)
                        and any(e.get("executor_id") == executor_id
                                for e in self.coordinator.errors())):
                    # The node EXITED with a recorded error (map_fun failure:
                    # report_error + deregister, never declared dead) — no
                    # restart was or will be scheduled, so waiting out the
                    # recovery window would just delay the inevitable by 90s.
                    return None
            if require_restart and tracked and not restarted \
                    and time.monotonic() > grace_end:
                return None  # healthy-node sever: re-feeding is not safe
            time.sleep(0.5)
        return None

    def _drain_slot_tail(self, ledger, worker_pos: int, executor_id: int,
                         qname: str, client: DataClient | None) -> DataClient | None:
        """Elastic train tail: poll the slot's consumption watermark until its
        acked-but-unconsumed window empties, the node dies (the monitor then
        requeues the window, clearing it here), or consumption stalls.

        The stall bound (``TOS_DRAIN_STALL_TIMEOUT``) keeps a map_fun that
        deliberately stopped consuming (a ``max_steps`` cutoff) from pinning
        ``train()`` forever — on stall the pre-drain semantics return: the
        buffered tail is the consumer's to lose.  Returns the (possibly
        refreshed or dropped) data client for the caller to keep using."""
        stall_limit = _env_float("TOS_DRAIN_STALL_TIMEOUT", 300.0)
        # Grace for the monitor to turn an observed "untracked" into either a
        # supervised restart or a window requeue before we call it a CLEAN
        # exit (deregister) — same window _recover_client uses.
        untracked_grace = self._declare_grace
        last_wm: int | None = None
        last_progress = time.monotonic()
        untracked_since: float | None = None
        while ledger.needs_drain(worker_pos):
            if self._shutdown_done or (
                    self.supervisor is not None
                    and self.supervisor.permanently_failed(executor_id)
                    is not None):
                return client
            # Checked EVERY iteration (the poll below may fail forever
            # against an exited process): a slot that stays untracked with
            # no restart in flight past the grace deregistered CLEANLY —
            # its consumer chose to exit with the tail buffered, which
            # forfeits it exactly like a 'terminating' answer would.
            _, tracked = self.coordinator.registered_incarnation(executor_id)
            if tracked or (self.supervisor is not None
                           and self.supervisor.restarting(executor_id)):
                untracked_since = None
            elif untracked_since is None:
                untracked_since = time.monotonic()
            elif time.monotonic() - untracked_since > untracked_grace:
                logger.warning(
                    "executor %d exited cleanly with buffered partitions "
                    "unconsumed; its tail is forfeited", executor_id)
                return client
            if time.monotonic() - last_progress > stall_limit:
                logger.warning(
                    "executor %d stopped consuming with buffered partitions "
                    "outstanding (no progress in %.0fs); leaving its tail "
                    "un-drained", executor_id, stall_limit)
                return client
            try:
                if client is None:
                    client = self._client(executor_id, connect_timeout=5.0,
                                          connect_attempts=1)
                wm = client.poll_consumed(qname)
            except Exception:  # noqa: BLE001 - slot mid-death/restart
                self._drop_client(executor_id)
                client = None
                time.sleep(0.5)
                continue
            ledger.update_watermark(worker_pos, wm)
            if wm != last_wm:
                last_wm = wm
                last_progress = time.monotonic()
            time.sleep(0.2)
        return client

    # -- training feed (reference TFCluster.train :~70-130, §3.2) ------------

    def train(self, data: Any, num_epochs: int = 1, qname: str = "input",
              shuffle_seed: int | None = None,
              num_partitions: int | None = None,
              span_bytes: int | None = None,
              mode: str = "async",
              embedding: Any = None) -> None:
        """Feed the workers for ``num_epochs`` epochs; blocks until all
        partitions are consumed (or nodes report 'terminating').

        **STREAMING** (reference ``InputMode.SPARK``): ``data`` is the rows
        themselves (a ``PartitionedDataset`` or any iterable of
        partitions); the driver streams every row over the data plane.

        **DIRECT** (reference ``InputMode.TENSORFLOW``): ``data`` is a
        shard *directory, glob, file, or list of paths*
        (``ingest.enumerate_shards``); the ledger feeds shard PATHS — tens
        of bytes per shard — and each node's ingest pipeline reads, CRC-
        verifies, and decodes the bytes itself (``ctx.get_data_feed`` →
        ``ingest.IngestFeed``), so aggregate feed bandwidth scales with the
        node count and the driver stays out of the training hot path.  One
        shard per ledger partition by default (``num_partitions`` groups
        them round-robin for many-tiny-file datasets).

        Both modes share the SAME partition ledger: partition *i* homes on
        feedable node ``i % W`` (the reference's round-robin placement),
        delivery is at-least-once with the consumption watermark bounding
        what a death can lose, and elastic restart recovery / incarnation
        fencing apply unchanged — in DIRECT mode a dead node's unread
        shards are simply re-assigned to a survivor or its replacement.

        Plain shards larger than ``span_bytes`` (default
        ``TOS_INGEST_SPAN_BYTES``; 0 disables) split into record-aligned
        *sub-shard* ledger items (``ingest.ShardSpan``), so N nodes
        parallelize inside one multi-GB shard instead of pinning it to a
        single reader — with the same at-least-once re-feed and recovery
        semantics at span granularity.  Gzip shards always stay whole
        (no byte-addressable record boundaries to split on).

        ``shuffle_seed`` reorders partitions differently each epoch
        (seed+epoch, deterministic) — the between-epochs shuffle the
        reference inherited from Spark/tf.data file shuffling; in DIRECT
        mode this is a between-epochs *shard* (work-item) shuffle.

        ``mode="sync"`` declares CROSS-HOST SYNCHRONOUS training (the
        MultiWorkerMirrored/ParameterServer replacement at cluster scope):
        the published job manifest carries a ``sync`` block (collective
        group name + world size) so every node's map_fun forms the
        :meth:`NodeContext.collective_group` and exchanges gradients each
        step — a compile-once jit step with a bucketed ring all-reduce via
        ``parallel.dp.make_train_step(cross_host_grad_fn=group.grad_fn())``,
        with the lockstep batch iterator keeping per-host step counts
        aligned (``make_batch_iterator(lockstep=True)``).  The feed
        machinery itself is identical to the default ``"async"``
        (driver-fed, at-least-once) mode; with ``elastic=True`` a node
        death mid-collective aborts the poisoned round at the group's
        generation barrier, the supervised restart rejoins, and training
        resumes from the synced step.
        """
        if mode not in ("async", "sync"):
            raise ValueError(
                f"train mode must be 'async' or 'sync', got {mode!r}")
        # Published for map_funs either way the data travels: the sync block
        # is the map_fun-facing DECLARATION of this train call's mode (one
        # map_fun body can branch on it) with the intended group name and
        # the driver's feedable count at publish time.  Group formation
        # itself defaults to the registration-time num_data_nodes
        # (ctx.collective_group) — after a resize the two can differ; see
        # the collectives caveat on resize().
        sync_block = ({"group": "train", "world": len(self._feedable_ids())}
                      if mode == "sync" else None)
        if embedding is not None:
            # sharded-embedding declaration (ShardPlan or its manifest
            # dict): published under the sync block so every node builds
            # the SAME range-shard layout — the plan is the one authority
            # on row ownership for the sparse collectives
            if sync_block is None:
                raise ValueError(
                    "embedding plans require mode='sync' (the sharded "
                    "table rides the sync collective group)")
            sync_block["embedding"] = (embedding.to_manifest()
                                       if hasattr(embedding, "to_manifest")
                                       else dict(embedding))
        if self.input_mode == InputMode.DIRECT:
            from tensorflowonspark_tpu.ingest import shards_as_partitioned

            if not isinstance(data, (str, os.PathLike, list, tuple)) and not \
                    hasattr(data, "iter_partition"):
                raise RuntimeError(
                    "InputMode.DIRECT (reference: InputMode.TENSORFLOW) "
                    "train() takes a shard path/glob/directory (or list of "
                    "paths), not row data — nodes read the files themselves. "
                    "To stream rows from the driver, run the cluster with "
                    "input_mode=InputMode.STREAMING (reference: InputMode.SPARK)")
            if hasattr(data, "iter_partition"):
                dataset = data  # pre-built partitions of paths: passthrough
                num_shards = num_items = None
            else:
                from tensorflowonspark_tpu.ingest import (
                    enumerate_shards,
                    split_shards,
                )

                files = enumerate_shards(data)
                num_shards = len(files)
                items = split_shards(files, span_bytes)
                num_items = len(items)
                dataset = shards_as_partitioned(items, num_partitions,
                                                span_bytes=0)
            manifest = {
                "kind": "tfrecord_shards", "qname": qname,
                "num_shards": num_shards,
                # work items the ledger feeds: == num_shards unless large
                # plain shards were split into sub-shard span ranges
                "num_items": num_items,
                "num_partitions": dataset.num_partitions,
                "num_epochs": num_epochs,
                "mode": mode,
                "spec": str(data) if isinstance(data, (str, os.PathLike)) else None,
            }
            if sync_block is not None:
                manifest["sync"] = sync_block
            if self._ingest_ids:
                # disaggregated tier declaration: map_funs (and operators
                # reading ctx.job_manifest()) see which tier the ledger
                # feeds and how the pool is configured — ingest_opts
                # overrides win over the env knobs, mirroring what the
                # workers themselves resolve
                from tensorflowonspark_tpu.ingest.service import (
                    cache_bytes_default,
                    shuffle_default,
                )

                opts = self._ingest_opts()
                shuffle = opts.get("shuffle")
                cache_bytes = opts.get("cache_bytes")
                manifest["ingest"] = {
                    "workers": len(self._ingest_feedable_ids()),
                    # None = "not overridden": the env knob applies,
                    # through the SAME helpers IngestService resolves with
                    "shuffle": bool(shuffle_default() if shuffle is None
                                    else shuffle),
                    "cache_bytes": int(cache_bytes_default()
                                       if cache_bytes is None
                                       else cache_bytes),
                }
            self.coordinator.set_manifest(manifest)
        else:
            if isinstance(data, (str, os.PathLike)):
                raise RuntimeError(
                    "train() got a path but this cluster runs "
                    "InputMode.STREAMING (reference: InputMode.SPARK), which "
                    "streams ROWS from the driver — pass the rows (e.g. "
                    "dfutil.load_tfrecords(dir)[0]), or run the cluster with "
                    "input_mode=InputMode.DIRECT (reference: "
                    "InputMode.TENSORFLOW) for node-side shard ingestion")
            dataset = as_partitioned(data, default_partitions=len(self._feed_ids))
            if sync_block is not None:
                # STREAMING publishes a manifest only when sync mode needs
                # one (async streaming kept its no-manifest behavior)
                self.coordinator.set_manifest({
                    "kind": "stream_rows", "qname": qname,
                    "num_partitions": dataset.num_partitions,
                    "num_epochs": num_epochs, "mode": mode,
                    "sync": sync_block,
                })
        # One view per epoch (identity, or the seeded between-epochs shuffle);
        # precomputed so a re-fed partition sees the same epoch ordering.
        views = [dataset if shuffle_seed is None
                 else dataset.shuffle_partitions(shuffle_seed + epoch)
                 for epoch in range(num_epochs)]
        # NOTE: the feedable-slot snapshot, the ledger, and the live-session
        # install all commit TOGETHER under _train_lock just before the
        # workers spawn (same lock _scale_in commits retirement intent
        # under) — the closures below bind the ``ledger``/``feed_ids``
        # names late, so defining them first is safe.  A snapshot taken
        # out here instead would race a concurrent scale-in: the victim
        # would get a fresh ledger slot feeding straight into its teardown.
        self._train_gen += 1
        train_gen = self._train_gen
        errors: list[Exception] = []

        def _feed_worker(worker_pos: int, executor_id: int) -> None:
            client: DataClient | None = None
            while True:
                task = ledger.next_task(worker_pos)
                if task is None:
                    # All partitions resolved — but "acked" only means
                    # buffered on the node.  In elastic mode nobody may walk
                    # away while this slot still holds unconsumed work: a
                    # death seconds after train() returns would be recovered
                    # (no error recorded) with the buffered tail silently
                    # gone.  Poll the node's watermark until the window
                    # drains; if the node dies instead, the monitor requeues
                    # the window and next_task hands it back out here.
                    # A RETIRED slot must drain its watermark even without a
                    # supervisor: scale-in's wait loop polls needs_drain, and
                    # nobody else reads the node's consumed count once this
                    # worker walks away — without this, a resize() on a
                    # non-elastic cluster burns its whole drain_timeout and
                    # then terminates a perfectly healthy victim.
                    if not ledger.needs_drain(worker_pos) or (
                            self.supervisor is None
                            and not ledger.slot_retired(worker_pos)):
                        return
                    client = self._drain_slot_tail(ledger, worker_pos,
                                                   executor_id, qname, client)
                    if not ledger.needs_drain(worker_pos):
                        continue  # drained, or death requeued the window
                    return  # shutdown / permanent failure / consumption stall
                # THIS holder's attempt number, captured at acquisition: after
                # a requeue the task is shared state again, and a peer popping
                # it would bump the live counter — judging the budget off a
                # re-read could fail the job while that peer's viable attempt
                # is still in flight.
                attempt = ledger.attempts(task)
                epoch, p = task
                # sampled partitions get a trace: root span = ledger
                # assignment -> buffered ack, the feed itself a child, and
                # the ctx rides the EndPartition so the node's consume span
                # (feed -> map_fun) joins the same trace
                part_trace = ttrace.sample()
                t_assign = time.monotonic()
                try:
                    if client is None:
                        client = self._client(executor_id)
                    # (train_gen, epoch, partition) is the EndPartition
                    # dedupe key: a re-feed of this same task must not
                    # double-count in the node's consumption watermark, while
                    # a LATER train() on a reused cluster (new generation)
                    # must count afresh
                    # span: wall time to stream + ack one partition (send
                    # rate AND node-side backpressure both land in here —
                    # the first place to look when train() slows down)
                    with telemetry.timed("driver.feed_partition_secs"), \
                            ttrace.span("driver.feed_partition",
                                        parent=part_trace):
                        state = client.feed_partition(
                            views[epoch].iter_partition(p), qname,
                            task_key=(train_gen,) + task,
                            trace=part_trace)
                except Exception as e:  # noqa: BLE001 - wrapped + ledgered below
                    wrapped = RuntimeError(
                        f"feeding executor {executor_id} failed on partition "
                        f"{p} (epoch {epoch}, attempt {attempt}"
                        f"/{ledger.max_attempts}): {e}")
                    wrapped.__cause__ = e
                    # Unacked partition back to the pool (at-least-once), then
                    # ride out the slot's restart window; a surviving peer may
                    # pick the orphan up meanwhile.
                    ledger.requeue(worker_pos)
                    if (ledger.slot_retired(worker_pos)
                            or executor_id in self._retiring):
                        # resize owns this slot's teardown: a feed failing
                        # against a victim reaped mid-drain is part of the
                        # plan, not a train() failure — the partition is
                        # already requeued for survivors, so just walk away
                        # (no restart is ever coming for a retired slot).
                        logger.info(
                            "feed worker for retiring node %d exiting; "
                            "partition %d requeued for survivors",
                            executor_id, p)
                        self._drop_client(executor_id)
                        return
                    inc_failed = self._client_incs.get(executor_id)
                    self._drop_client(executor_id)
                    client = None
                    if attempt >= ledger.max_attempts:
                        errors.append(wrapped)
                        ledger.fail(wrapped)
                        return
                    logger.warning("%s; awaiting recovery", wrapped)
                    client = self._recover_client(executor_id,
                                                  cancel=ledger.failed)
                    if client is None:
                        errors.append(wrapped)
                        ledger.fail(wrapped)
                        return
                    if self._client_incs.get(executor_id) != inc_failed:
                        # actual restart: the predecessor's queue (and every
                        # buffered-but-unconsumed partition in it) is gone
                        n = ledger.requeue_unconsumed(worker_pos)
                        if n:
                            logger.warning(
                                "executor %d restarted with %d buffered "
                                "partition(s) unconsumed; re-delivering them",
                                executor_id, n)
                    continue
                if state == "terminating":
                    logger.info("node %d terminating; dropping remaining feed", executor_id)
                    ledger.abandon_slot(worker_pos)
                    return
                ledger.ack(worker_pos, client.partitions_consumed(qname))
                ttrace.record_span(
                    "train.partition", part_trace, None, t_assign,
                    time.monotonic() - t_assign,
                    {"epoch": epoch, "partition": p, "executor": executor_id,
                     "attempt": attempt} if part_trace else None)

        def _runner(worker_pos: int, executor_id: int) -> None:
            try:
                _feed_worker(worker_pos, executor_id)
            except Exception as e:  # noqa: BLE001 - never strand the ledger
                wrapped = RuntimeError(
                    f"feed worker for executor {executor_id} crashed: {e}")
                wrapped.__cause__ = e
                errors.append(wrapped)
                ledger.fail(wrapped)

        # Live train session: resize() scale-out attaches new feed workers
        # through ``spawn`` while this call is in flight, so the thread list
        # can GROW — the join loop below re-checks until it stabilizes.
        session: dict = {"ledger": None, "threads": []}

        def _spawn_worker(worker_pos: int, executor_id: int) -> None:
            t = threading.Thread(target=_runner, args=(worker_pos, executor_id),
                                 name=f"feed-{executor_id}")
            session["threads"].append(t)
            t.start()

        session["spawn"] = _spawn_worker
        # The monitor re-delivers a dead slot's buffered-but-unconsumed
        # window the moment it declares the death — the slot's own feed
        # worker may be idle in next_task() at that point and would never
        # pass through the recovery path that also checks.
        #
        # Snapshot -> ledger -> install, all in ONE _train_lock hold:
        # _scale_in commits retirement intent under this lock, so a
        # concurrent scale-in either lands before the snapshot (victim
        # excluded, retires with no slot here) or after the install
        # (victim's slot found in _active_ledger and drained properly) —
        # never in between, where it would EOF a slot this train is about
        # to feed.  A slot mid-drain is excluded from the snapshot for the
        # same reason.
        with self._train_lock:
            # Disaggregated tier: a DIRECT train over a cluster with ingest
            # workers feeds THEIR slots — the workers decode and forward,
            # the trainers consume chunks.  The ledger machinery (and every
            # elastic property hanging off it) is identical either way;
            # only the slot membership changes.
            ingest_tier = (self.input_mode == InputMode.DIRECT
                           and bool(self._ingest_ids))
            feed_ids = (self._ingest_feedable_ids() if ingest_tier
                        else self._feedable_ids())
            if not feed_ids:
                raise RuntimeError("no feedable slots for train() (all "
                                   "retired or draining)")
            session["tier"] = "ingest" if ingest_tier else "nodes"
            ledger = _PartitionLedger(dataset.num_partitions, num_epochs,
                                      len(feed_ids),
                                      max_attempts=self._max_feed_attempts,
                                      journal_fn=self.coordinator.live_journal,
                                      train_gen=train_gen)
            session["ledger"] = ledger
            self._train_session = session
            self._active_ledger = {eid: (ledger, pos)
                                   for pos, eid in enumerate(feed_ids)}
            for pos, eid in enumerate(feed_ids):
                _spawn_worker(pos, eid)
        try:
            while True:
                with self._train_lock:
                    threads = list(session["threads"])
                for t in threads:
                    t.join()
                with self._train_lock:
                    if len(session["threads"]) == len(threads):
                        break
        finally:
            with self._train_lock:
                self._train_session = None
                self._active_ledger = {}
        self._raise_node_errors()
        if errors:
            raise RuntimeError(f"feeding failed: {errors[0]}") from errors[0]

    # -- inference (reference TFCluster.inference :~130-170, §3.3) -----------

    def inference(self, data: Any, qname_in: str = "input", qname_out: str = "output",
                  flat: bool = True, eof_when_done: bool = False) -> list:
        """Round-trip partitions through the nodes; ordered, exactly-count.

        Returns the flattened results in partition order — the invariant the
        reference's output RDD preserved (SURVEY.md §3.3).  ``flat=False``
        returns one result list per partition instead (the pipeline layer
        needs partition boundaries to rebuild a PartitionedDataset).

        Materializes everything; for datasets bigger than driver memory use
        ``inference_stream``.
        """
        dataset = as_partitioned(data, default_partitions=len(self._feed_ids))
        results: list[list | None] = [None] * dataset.num_partitions
        for p, part in self.inference_stream(dataset, qname_in, qname_out,
                                             window=dataset.num_partitions + 1,
                                             eof_when_done=eof_when_done):
            results[p] = part
        if not flat:
            return [part or [] for part in results]
        return [item for part in results for item in (part or [])]

    def inference_stream(self, data: Any, qname_in: str = "input",
                         qname_out: str = "output", window: int | None = None,
                         eof_when_done: bool = False):
        """Lazily yield ``(partition_index, results)`` in partition order.

        Restores the reference's lazy-RDD property
        (``TFCluster.py:~130-170``): partitions are read, scored, and yielded
        incrementally, so driver memory holds at most ``window`` completed
        partitions (default ``2 × feedable nodes``) — workers pause instead
        of running ahead of the consumer.

        ``eof_when_done=True`` sends end-of-feed to each node as soon as its
        share of partitions has been dispatched AND collected (instead of at
        shutdown).  REQUIRED for global-mesh scoring map_funs
        (``inference.sharded_bundle_inference_loop``): there, a node whose
        share ran out must learn it is done WHILE the driver is still
        collecting from its peers — its end-of-data consensus votes (and
        filler SPMD rounds) are what let the peers' remaining batches
        execute.  Leave False for task-parallel loops that should keep
        serving across multiple inference calls on one cluster.
        """
        if self.input_mode != InputMode.STREAMING:
            raise RuntimeError(
                "inference()/inference_stream() require InputMode.STREAMING "
                "(reference: InputMode.SPARK) — the exactly-count result "
                "contract needs driver-streamed row partitions.  This "
                "cluster runs InputMode.DIRECT (reference: "
                "InputMode.TENSORFLOW), whose feed carries shard paths for "
                "node-side ingestion; for request/response scoring on a "
                "DIRECT cluster use cluster.serve(export_dir) instead")
        # Snapshot: a concurrent resize() must not skew the worker/partition
        # mapping mid-call (newcomers join the NEXT inference call, and a
        # slot mid-drain must not be handed partitions it will never score).
        # Atomic with the live-call marker: _scale_in checks the marker
        # under the same lock before committing retirement intent, so a
        # scale-in can never EOF a worker that owns statically-assigned
        # partitions of THIS call — it refuses until the call completes
        # (train() has a live re-feed session; inference() deliberately
        # does not, its exactly-once contract is positional).
        with self._train_lock:
            feed_ids = self._feedable_ids()
            self._inference_live += 1
        try:
            dataset = as_partitioned(data, default_partitions=len(feed_ids))
        except Exception:
            with self._train_lock:
                self._inference_live -= 1
            raise
        num_workers = len(feed_ids)
        if eof_when_done:
            # Global-mesh scoring cannot be window-gated: a node whose next
            # partition is gated on earlier global output would stop feeding
            # its SPMD rounds while its peers wait for it in a collective —
            # a circular wait.  Sharded scoring therefore always dispatches
            # freely (driver may hold up to all partitions, as inference()
            # already does).
            window = dataset.num_partitions + 1
        window = window if window is not None else max(2 * num_workers, 4)
        buf: dict[int, list] = {}
        cond = tos_named_condition("cluster.drain._cond")
        state = {"next": 0, "stopped": False, "done": 0}
        errors: list[Exception] = []

        def _infer_worker(worker_pos: int, executor_id: int) -> None:
            # The worker's share of partitions, retried in place on failure.
            # Exactly-once is preserved by construction: the consumer reads a
            # partition's results from ``buf[p]`` exactly once, and a failed
            # attempt is only ever retried against a *restarted* node (fresh
            # queues) — never a healthy one that may hold partial results
            # (``_recover_client(require_restart=True)``).
            pending = collections.deque(
                range(worker_pos, dataset.num_partitions, num_workers))
            client: DataClient | None = None
            attempts = 0
            try:
                while pending:
                    p = pending[0]
                    with cond:
                        cond.wait_for(lambda: p < state["next"] + window
                                      or state["stopped"])
                        if state["stopped"]:
                            return
                    try:
                        if client is None:
                            client = self._client(executor_id)
                        with telemetry.timed("driver.infer_partition_secs"):
                            part = client.infer_partition(
                                dataset.iter_partition(p), qname_in, qname_out)
                    except Exception as e:  # noqa: BLE001 - wrapped below
                        # A failed DIAL (client is still None) sent nothing:
                        # no partial results can exist anywhere, so any live
                        # process is safe to feed — demanding a restart would
                        # wedge recovery when the slot died pre-dial (the
                        # incarnation baseline already includes the death
                        # bump, so "restarted" could never be observed).
                        had_conn = client is not None
                        attempts += 1
                        wrapped = RuntimeError(
                            f"inference executor {executor_id} failed on "
                            f"partition {p} (attempt {attempts}"
                            f"/{self._max_feed_attempts}): {e}")
                        wrapped.__cause__ = e
                        self._drop_client(executor_id)
                        client = None
                        if attempts < self._max_feed_attempts:
                            logger.warning("%s; awaiting recovery", wrapped)
                            client = self._recover_client(
                                executor_id, require_restart=had_conn,
                                cancel=lambda: state["stopped"] or bool(errors))
                        if client is None:
                            with cond:
                                errors.append(wrapped)
                                cond.notify_all()
                            return
                        continue
                    attempts = 0
                    pending.popleft()
                    with cond:
                        buf[p] = part
                        cond.notify_all()
                if eof_when_done:
                    if client is None:
                        client = self._client(executor_id)
                    client.send_eof(qname_in)
            except Exception as e:
                with cond:
                    errors.append(e)
                    cond.notify_all()
            finally:
                with cond:
                    state["done"] += 1
                    cond.notify_all()

        threads = [
            threading.Thread(target=_infer_worker, args=(pos, eid),
                             name=f"infer-{eid}", daemon=True)
            for pos, eid in enumerate(feed_ids)
        ]
        started = 0
        try:
            for t in threads:
                t.start()
                started += 1
        except Exception:
            # partial start (thread exhaustion): stop the live workers and
            # release the scale-in guard — a leaked _inference_live would
            # refuse every scale-in for the cluster's remaining life
            with cond:
                state["stopped"] = True
                cond.notify_all()
            for t in threads[:started]:
                t.join(timeout=10.0)
            with self._train_lock:
                self._inference_live -= 1
            raise
        try:
            for p in range(dataset.num_partitions):
                with cond:
                    cond.wait_for(lambda: p in buf or errors
                                  or state["done"] == num_workers)
                    if errors:
                        raise RuntimeError(f"inference failed: {errors[0]}") from errors[0]
                    if p not in buf:
                        # every worker exited without error yet p is missing
                        self._raise_node_errors()
                        raise RuntimeError(f"inference lost partition {p}")
                    part = buf.pop(p)
                    state["next"] = p + 1
                    cond.notify_all()
                yield p, part
        finally:
            with cond:
                state["stopped"] = True
                cond.notify_all()
            for t in threads:
                t.join()
            with self._train_lock:
                self._inference_live -= 1
        self._raise_node_errors()
        if errors:
            # A worker that failed AFTER its last partition was collected
            # (e.g. send_eof) never trips the consumer loop's error check —
            # surface it here or the node silently misses its EOF and stalls
            # in next_batch until shutdown's kill timeout.
            raise RuntimeError(f"inference worker failed after all results were "
                               f"collected: {errors[0]}") from errors[0]

    # -- online serving (beyond-reference: request/response path) ------------

    def serve(self, export_dir: str, **kwargs) -> Any:
        """Open an online-serving gateway over this cluster's nodes.

        The nodes must be running the resident ``serving.serving_loop``
        map_fun (pass it to ``cluster.run`` with ``{"export_dir": ...}``
        args); the returned :class:`~tensorflowonspark_tpu.serving.
        ServingGateway` answers individual requests with dynamic
        micro-batching, least-outstanding replica routing, and a TCP wire
        endpoint — see ``serving/gateway.py``.  Run the cluster with
        ``elastic=True`` so a replica death becomes a supervised restart
        the gateway rides out (in-flight batches retry on a survivor)
        instead of a job failure.

        Keyword args pass through to ``ServingGateway`` (``max_batch``,
        ``max_delay_ms``, ``queue_limit``, ``default_timeout``, ``listen``,
        ``reload_poll_secs``, ...); the ``TOS_SERVE_*`` knobs supply
        defaults.  The gateway closes automatically at ``shutdown()``.
        """
        from tensorflowonspark_tpu.serving import ServingGateway

        gateway = ServingGateway(self, export_dir, **kwargs)
        self._gateways.append(gateway)
        return gateway

    # -- elastic autoscaling (beyond-reference: cluster.resize) ---------------

    def _feedable_ids(self) -> list[int]:
        """The ONE definition of 'feedable right now': data slots minus
        those mid-drain (train()/inference() snapshots and the autoscaler's
        ``current`` must never disagree on membership)."""
        return [eid for eid in self._feed_ids if eid not in self._retiring]

    def num_feedable(self) -> int:
        """Feedable (non-evaluator, non-retiring) nodes right now — the
        ``current`` the autoscaler policies compare their desired count to."""
        return len(self._feedable_ids())

    def _ingest_feedable_ids(self) -> list[int]:
        """Live data-service worker slots (ingest role, not mid-drain) —
        the ledger targets of a DIRECT train on a disaggregated cluster."""
        return [eid for eid in self._ingest_ids if eid not in self._retiring]

    def num_ingest(self) -> int:
        """Live ingest-worker count — the ``current`` an ingest-tier
        autoscaler policy compares its desired pool size to."""
        return len(self._ingest_feedable_ids())

    def _ingest_opts(self) -> dict:
        """The tier's decode configuration as launched
        (``run(ingest_opts=...)``, carried on every NodeConfig) — the
        manifest must describe what the workers ACTUALLY run, not the env
        defaults the opts may override."""
        for cfg in getattr(self.launcher, "configs", []):
            opts = getattr(cfg, "ingest_opts", None)
            if opts:
                return dict(opts)
        return {}

    def resize(self, num_nodes: int, *, drain_timeout: float | None = None) -> dict:
        """Grow or shrink the LIVE cluster to ``num_nodes`` feedable nodes.

        **Scale-out** spawns fresh node processes through the launcher
        (cloned from an existing worker's config), admits them through the
        coordinator's rendezvous mid-run, and puts them to work immediately:
        an in-flight ``train()`` gets a new feed worker whose ledger slot is
        rebalanced a fair share of the still-queued partitions (plus the
        shared orphan pool), and every open serving gateway admits the node
        as a routing replica.

        **Scale-in** picks the least-loaded victims (router outstanding,
        then ``feed.queue_depth``; the chief — executor 0 — never retires),
        marks them DRAINING (no new ledger assignments, serving routers stop
        routing to them and drain their in-flight batches), waits for
        buffered partitions to be consumed (``drain_timeout``, default
        ``TOS_DRAIN_TIMEOUT``), sends end-of-feed so the map_fun exits
        cleanly, and retires the slot *intentionally*: no respawn, no
        restart-budget charge, no node error.  A victim killed mid-drain
        cannot wedge the resize — the at-least-once ledger re-feeds its
        partitions to survivors and the reaper escalates to terminate.

        The reference cluster was frozen at ``num_executors`` for life
        (Spark could replace a dead executor, never follow traffic); this is
        the mechanism half of elastic autoscaling — drive it by hand, or let
        :meth:`autoscale` run a telemetry-driven policy loop over it.
        Refused for ``jax.distributed`` jobs (a live XLA world has a fixed
        process count).  Returns a record of what changed (also appended to
        the run report's ``autoscale`` block).

        Collectives caveat: default-group ``ctx.barrier()``/reduces track
        the live membership (retired slots leave the participant count),
        but ``group="data"`` collectives, ``ctx.all_done`` consensus, and
        tensor-plane :meth:`NodeContext.collective_group` worlds use each
        node's registration-time ``num_data_nodes`` and do NOT follow
        resizes.  Collective groups survive same-world elastic RESTARTS
        (the generation-barrier rejoin, ``collective/group.py``); a
        *changed* world size still means a new ``train()`` call.
        """
        if num_nodes < 1:
            raise ValueError("resize needs num_nodes >= 1")
        if any(getattr(cfg, "jax_distributed", False)
               for cfg in getattr(self.launcher, "configs", [])):
            raise RuntimeError(
                "cannot resize a jax.distributed job: a live XLA world has "
                "a fixed process count (same constraint as elastic=True)")
        with self._resize_lock:
            if self._closing.is_set() or self._shutdown_done:
                raise RuntimeError("cluster is shutting down")
            current = self.num_feedable()
            t0 = time.monotonic()
            if num_nodes == current:
                return {"action": "noop", "from": current, "to": current}
            if num_nodes > current:
                added = self._scale_out(num_nodes - current)
                record: dict = {"action": "scale_out", "from": current,
                                "to": current + len(added), "added": added}
            else:
                retired = self._scale_in(current - num_nodes, drain_timeout)
                record = {"action": "scale_in", "from": current,
                          "to": current - len(retired), "retired": retired}
            record["secs"] = round(time.monotonic() - t0, 3)
            self._resize_log.append(record)
            telemetry.counter(f"cluster.{record['action']}_total").inc()
            telemetry.gauge("cluster.feedable_nodes").set(self.num_feedable())
            logger.info("cluster resized: %s", record)
            return dict(record)

    def _worker_template(self):
        """The NodeConfig to clone for scale-out newcomers: the highest-
        launch-index feedable node's — a worker wherever one exists (the
        chief's config is only used on a 1-node cluster, where it is the
        worker config too)."""
        best = None
        for meta in self.cluster_info:
            if meta["executor_id"] not in self._feed_ids:
                continue
            li = meta.get("launch_index", -1)
            if 0 <= li < len(self.launcher.configs) and (
                    best is None or li > best):
                best = li
        if best is None:
            raise RuntimeError("no feedable node config to clone for scale-out")
        return self.launcher.configs[best]

    def _spawn_slots(self, count: int, job_name: str, template,
                     spawn_event: str) -> list[int]:
        """Shared scale-out spawner (trainer and ingest tiers): open
        ``count`` slots under ``job_name``, spawn processes cloned from
        ``template``, and await their registration — rolling membership
        back on any failure."""
        import dataclasses as _dc

        new_ids = self.coordinator.open_slots(count, job_name=job_name)
        base = len(self.launcher.processes)
        configs = [_dc.replace(template, launch_index=base + j,
                               replace_executor_id=-1)
                   for j in range(count)]
        timeout = _env_float("TOS_RESERVATION_TIMEOUT", 120.0)
        try:
            self.launcher.spawn_more(configs)
            ttrace.event(spawn_event, executors=new_ids)
            self.coordinator.await_slots(new_ids, timeout)
        except Exception:
            # reap what never registered: an unjoined newcomer must not
            # linger half-booted, and its exit code is not the job's
            # verdict.  A spawn_more failure lands here too (possibly with
            # fewer than count processes appended), so guard the indexing.
            procs = self.launcher.processes
            for j in range(count):
                if base + j >= len(procs):
                    break
                proc = procs[base + j]
                with contextlib.suppress(Exception):
                    if proc.is_alive():
                        proc.terminate()
                self._audit_waived.add(base + j)
            # roll back membership so a LATER resize starts aligned:
            # cancel_slots atomically retires any slot that managed to
            # register before the timeout (it was just reaped — no error,
            # id never reused) and cancels the never-registered rest, so
            # open_slots' promised ids match registration order again and
            # no ghost inflates the default barrier/reduce count
            self.coordinator.cancel_slots(new_ids)
            raise
        self.cluster_info = self.coordinator.cluster_info()
        return new_ids

    def _scale_out(self, count: int) -> list[int]:
        new_ids = self._spawn_slots(count, "worker", self._worker_template(),
                                    "scale_out_spawn")
        for eid in new_ids:
            self._feed_ids.append(eid)
            self._attach_train_slot(eid)
            for gw in self._gateways:
                gw.add_replica(eid)
            ttrace.event("scale_out", executor=eid)
        return new_ids

    def _attach_train_slot(self, executor_id: int, tier: str = "nodes") -> bool:
        """Put a scale-out newcomer to work on an in-flight ``train()``:
        add a ledger slot, rebalance queued partitions onto it, and start
        its feed worker.  No-op (False) when no train is live — or when the
        live train feeds the OTHER tier (a trainer must never be handed the
        ingest ledger's shard items, nor an ingest worker a row feed)."""
        with self._train_lock:
            session = self._train_session
            if session is None or executor_id in self._active_ledger \
                    or session.get("tier", "nodes") != tier:
                return False
            ledger = session["ledger"]
            pos = ledger.add_slot()
            moved = ledger.rebalance_to(pos)
            self._active_ledger[executor_id] = (ledger, pos)
            session["spawn"](pos, executor_id)
        logger.info("executor %d joined the live feed (slot %d, %d queued "
                    "partition(s) rebalanced to it)", executor_id, pos, moved)
        return True

    def _pick_victims(self, count: int) -> list[int]:
        """Least-loaded victim selection: serving-router outstanding first
        (``replica_loads`` — the same numbers routing picks by), then
        ``feed.queue_depth`` from the rolling stats, ties broken newest-
        first.  The chief (executor 0) never retires — its process carries
        cluster-level duties (TensorBoard, the reference's master role)."""
        candidates = [eid for eid in self._feed_ids
                      if eid != 0 and eid not in self._retiring]
        if len(candidates) < count:
            raise ValueError(
                f"cannot retire {count} node(s): only {len(candidates)} "
                "retireable (the chief never retires)")
        loads: dict[int, float] = {eid: 0.0 for eid in candidates}
        for gw in self._gateways:
            for eid, n in gw.replica_loads().items():
                if eid in loads:
                    loads[eid] += n
        try:
            stats = self.coordinator.cluster_stats(5.0)
            fq = (stats.get("serving") or {}).get("feed_queue_depth") or {}
        except Exception:  # noqa: BLE001 - stats are advisory here
            fq = {}
        return sorted(candidates,
                      key=lambda eid: (loads[eid], fq.get(str(eid)) or 0,
                                       -eid))[:count]

    def _proc_for(self, executor_id: int):
        """(launch_index, process handle) for a slot, via the registered
        launch_index (pids cannot map over ssh transports)."""
        meta = next((m for m in self.cluster_info
                     if m["executor_id"] == executor_id), None)
        li = (meta or {}).get("launch_index", -1)
        procs = self.launcher.processes
        if 0 <= li < len(procs):
            return li, procs[li]
        return li, None

    def _send_eof_best_effort(self, executor_id: int, qname: str,
                              proc=None) -> None:
        """Best-effort end-of-feed to one node queue — the teardown
        protocol shared by ``shutdown()`` and scale-in retirement: one
        short dial on the pooled client, then one retry on a FRESH
        one-shot socket client, warning only on final failure.

        One-attempt dials throughout: the default 3x60s backoff would
        stack ~185s per queue against a blackholed host, all outside the
        caller's timeout budget.  The retry client skips shm-ring
        negotiation — no ring handshake just to deliver a ~20-byte EOF
        frame.  A node whose process already exited is a normal teardown
        race (its map_fun finished and closed its data plane first), not
        a failure."""
        try:
            self._client(executor_id, connect_timeout=5.0,
                         connect_attempts=1).send_eof(qname)
            return
        except Exception:  # noqa: BLE001 - retried on a fresh socket below
            if proc is not None and not proc.is_alive():
                logger.debug("node %d exited before EOF on %r",
                             executor_id, qname)
                return
            # The cached client's socket may have died with an earlier
            # timed-out call; this EOF is what unblocks the node's
            # next_batch, so retry once on a FRESH connection before
            # giving up.
            self._drop_client(executor_id)
            try:
                meta = self._fresh_meta(executor_id)
                retry = DataClient(meta["host"], meta["data_port"],
                                   self.authkey, prefer_ring=False,
                                   call_timeout=30.0, stall_timeout=30.0,
                                   connect_timeout=5.0, connect_attempts=1)
                try:
                    retry.send_eof(qname)
                finally:
                    with contextlib.suppress(Exception):
                        retry.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                logger.warning("could not send EOF to node %d queue %r",
                               executor_id, qname, exc_info=True)

    def _send_retirement_eof(self, executor_id: int) -> None:
        """End-of-feed to one retiring node so its map_fun exits cleanly
        (FIFO: everything already buffered is consumed first).  Best-effort
        — a node that died mid-drain gets reaped by the caller instead."""
        _, proc = self._proc_for(executor_id)
        for qname in self.input_qnames:
            self._send_eof_best_effort(executor_id, qname, proc=proc)

    def _scale_in(self, count: int, drain_timeout: float | None) -> list[int]:
        if drain_timeout is None:
            drain_timeout = _env_float("TOS_DRAIN_TIMEOUT", 60.0)
        victims = self._pick_victims(count)
        # Intent FIRST: from this moment a victim's death is retirement —
        # the supervisor declines recovery, the monitor requeues without
        # escalation, and no restart budget is charged.  Committed under
        # _train_lock against the live-inference marker: an inference()
        # call's partitions are statically assigned to the workers that
        # started it, so a retirement EOF mid-call would fail the whole
        # call on a healthy cluster — refuse instead (the autoscaler's
        # next tick simply retries).
        with self._train_lock:
            if self._inference_live:
                raise RuntimeError(
                    "cannot scale in during a live inference() call: its "
                    "partitions are statically assigned to the workers "
                    "that started it; retry after the call completes")
            for eid in victims:
                self._retiring.add(eid)
        for eid in victims:
            if self.supervisor is not None:
                self.supervisor.retire(eid)
        self.coordinator.mark_draining(victims)
        ttrace.event("drain_begin", executors=victims)
        # TOS_DRAIN_TIMEOUT is a PER-VICTIM budget (the knob's contract),
        # not a shared pot: every victim has been draining concurrently
        # since intent was marked above, so a loaded early victim consuming
        # its full budget must not starve the later ones into forced
        # terminates — each blocking step below gets the full allowance.
        # 1) Serving: drain each victim out of every gateway's routing
        #    (in-flight batches finish; queued ones re-route on timeout).
        for gw in self._gateways:
            for eid in victims:
                with contextlib.suppress(Exception):
                    gw.retire_replica(eid, timeout=max(1.0, drain_timeout))
        # 2) Training ledger: queued home partitions to the orphan pool,
        #    then wait for the in-flight feed and the buffered-but-
        #    unconsumed window to drain (watermark path).  A victim that
        #    dies here breaks the wait via is_tracked — the monitor already
        #    requeued its window.
        with self._train_lock:
            entries = [(eid, self._active_ledger.get(eid)) for eid in victims]
        for eid, entry in entries:
            if entry is not None:
                moved = entry[0].retire_slot(entry[1])
                if moved:
                    logger.info("%d queued partition(s) of retiring node %d "
                                "redistributed", moved, eid)
        for eid, entry in entries:
            if entry is None:
                continue
            ledger, pos = entry
            victim_deadline = time.monotonic() + drain_timeout
            while time.monotonic() < victim_deadline:
                if ledger.slot_idle(pos) and not ledger.needs_drain(pos):
                    break
                if not self.coordinator.is_tracked(eid):
                    break  # died/exited; the ledger re-feed owns its work
                if self._closing.is_set():
                    break  # shutdown owns teardown from here; stop waiting
                time.sleep(0.1)
        # 3) Retirement EOF -> map_fun exits -> clean process exit.
        for eid in victims:
            if self.coordinator.is_tracked(eid):
                self._send_retirement_eof(eid)
        # 4) Reap: join the process (a fresh per-victim budget — the
        #    knob's contract is per victim, and victims drained
        #    concurrently since intent, so a loaded early victim must not
        #    starve a later one into a forced terminate), escalating past
        #    it; then finalize the slot's retirement everywhere.
        for eid in victims:
            self._reap_retired(eid, drain_timeout, "node")
            if self.supervisor is None:
                telemetry.counter("elastic.retirements_total").inc()
            if eid in self._feed_ids:
                self._feed_ids.remove(eid)
            self._retiring.discard(eid)
            ttrace.event("scale_in", executor=eid)
        return victims

    def _reap_retired(self, executor_id: int, drain_timeout: float,
                      kind: str) -> None:
        """Shared scale-in reaper tail (trainer and ingest tiers): join the
        victim past its retirement EOF, escalate to terminate/kill, then
        finalize — requeue its ledger window, waive its exit code, drop
        its client, retire the slot.

        The requeue runs whatever ended the victim — clean EOF exit, our
        terminate, or a kill that landed too close to the reap for the
        monitor to declare (retire_node forecloses that declaration for
        good): idempotent (a fully-drained window requeues nothing), and
        at-least-once semantics demand re-feeding anything that cannot be
        PROVEN consumed."""
        li, proc = self._proc_for(executor_id)
        if proc is not None:
            proc.join(max(2.0, drain_timeout))
            if proc.is_alive():
                logger.warning("retiring %s %d did not exit after EOF; "
                               "terminating it", kind, executor_id)
                # stop liveness tracking FIRST so the monitor never flags
                # the terminate as a death
                self.coordinator.forget([executor_id])
                proc.terminate()
                proc.join(5.0)
                if proc.is_alive():
                    proc.kill()
                    proc.join(5.0)
        self._requeue_dead_slot(executor_id)
        if li >= 0:
            # a retired node's exit code is not the job's verdict (we may
            # have terminated it, or chaos killed it mid-drain)
            self._audit_waived.add(li)
        self._drop_client(executor_id, abort=True)
        self.coordinator.retire_node(executor_id)

    # -- data-service tier scaling (the ingest fleet knob) --------------------

    def resize_ingest(self, num_workers: int, *,
                      drain_timeout: float | None = None) -> dict:
        """Grow or shrink the data-service tier to ``num_workers`` live
        ingest workers — the fleet knob BENCH_r12's per-box decode ceiling
        becomes (decode parallelism was a per-trainer constant before this
        tier existed).

        Scale-out opens ``ingest``-role slots mid-run, spawns fresh node
        processes (the coordinator's role assignment routes them into
        ``ingest.service.ingest_worker_main``), and attaches each to an
        in-flight ingest-fed ``train()`` with a rebalanced ledger share.
        Scale-in drains the highest-numbered workers (ledger retire ->
        orphaned shard items re-feed to surviving workers -> retirement
        EOF -> reap), with the same at-least-once guarantees a worker
        death gets.  Trainers are untouched in both directions.

        Limitation: each worker snapshots the TRAINER endpoints at its own
        boot (``ingest_worker_main`` reads ``ctx.cluster_info``), so a
        trainer added by ``resize()`` mid-run joins the forwarding
        rotation only as workers (re)start — resize the trainer fleet
        between train() calls, or cycle the ingest tier afterwards."""
        if num_workers < 0:
            raise ValueError("resize_ingest needs num_workers >= 0")
        # same preconditions run() enforces for ingest_workers: the tier
        # only has work on a DIRECT cluster, and a jax_distributed world
        # has a fixed process count
        if self.input_mode != InputMode.DIRECT:
            raise RuntimeError(
                "resize_ingest needs InputMode.DIRECT: the data-service "
                "tier claims shard items from the ledger, which a "
                "STREAMING cluster never produces")
        if any(getattr(cfg, "jax_distributed", False)
               for cfg in getattr(self.launcher, "configs", [])):
            raise RuntimeError(
                "cannot resize the ingest tier of a jax.distributed job: "
                "a live XLA world has a fixed process count")
        with self._resize_lock:
            if self._closing.is_set() or self._shutdown_done:
                raise RuntimeError("cluster is shutting down")
            current = self.num_ingest()
            t0 = time.monotonic()
            if num_workers == current:
                return {"action": "noop", "tier": "ingest",
                        "from": current, "to": current}
            if num_workers > current:
                added = self._scale_out_ingest(num_workers - current)
                record: dict = {"action": "scale_out", "tier": "ingest",
                                "from": current, "to": current + len(added),
                                "added": added}
            else:
                retired = self._scale_in_ingest(current - num_workers,
                                                drain_timeout)
                record = {"action": "scale_in", "tier": "ingest",
                          "from": current, "to": current - len(retired),
                          "retired": retired}
            record["secs"] = round(time.monotonic() - t0, 3)
            self._resize_log.append(record)
            telemetry.counter(f"cluster.ingest_{record['action']}_total").inc()
            telemetry.gauge("cluster.ingest_workers").set(self.num_ingest())
            logger.info("ingest tier resized: %s", record)
            return dict(record)

    def _ingest_template(self):
        """NodeConfig to clone for ingest scale-out: any live config works
        (role assignment — not the config — routes a process into the
        service loop), preferring an existing ingest worker's so its
        ``ingest_opts`` tuning rides along."""
        best = None
        for meta in self.cluster_info:
            li = meta.get("launch_index", -1)
            if not 0 <= li < len(self.launcher.configs):
                continue
            if meta["executor_id"] in self._ingest_ids:
                return self.launcher.configs[li]
            if best is None:
                best = self.launcher.configs[li]
        if best is None:
            raise RuntimeError("no node config to clone for ingest scale-out")
        return best

    def _scale_out_ingest(self, count: int) -> list[int]:
        new_ids = self._spawn_slots(count, "ingest", self._ingest_template(),
                                    "ingest_scale_out_spawn")
        for eid in new_ids:
            self._ingest_ids.append(eid)
            self._attach_train_slot(eid, tier="ingest")
            ttrace.event("ingest_scale_out", executor=eid)
        return new_ids

    def _scale_in_ingest(self, count: int,
                         drain_timeout: float | None) -> list[int]:
        if drain_timeout is None:
            drain_timeout = _env_float("TOS_DRAIN_TIMEOUT", 60.0)
        candidates = [eid for eid in self._ingest_ids
                      if eid not in self._retiring]
        if len(candidates) < count:
            raise ValueError(f"cannot retire {count} ingest worker(s): only "
                             f"{len(candidates)} live")
        victims = sorted(candidates)[-count:]  # newest workers first out
        with self._train_lock:
            # A live ingest-fed train() must keep at least one worker: the
            # trainer tier's analogue is the chief-never-retires floor —
            # with ZERO survivors every ledger slot would retire, queued
            # partitions would orphan with nobody to deliver them, and
            # train() would return "success" with records never decoded.
            if (self._train_session is not None
                    and self._train_session.get("tier") == "ingest"
                    and count >= len(candidates)):
                raise RuntimeError(
                    "cannot retire every ingest worker while an ingest-fed "
                    "train() is in flight: its ledger partitions would "
                    "orphan with no worker to deliver them; keep >= 1, or "
                    "retry after the train completes")
            for eid in victims:
                self._retiring.add(eid)
        for eid in victims:
            if self.supervisor is not None:
                self.supervisor.retire(eid)
        self.coordinator.mark_draining(victims)
        ttrace.event("ingest_drain_begin", executors=victims)
        # queued shard items to the orphan pool; surviving workers (or the
        # victims themselves, for their in-flight item) deliver them
        with self._train_lock:
            entries = [(eid, self._active_ledger.get(eid)) for eid in victims]
        for eid, entry in entries:
            if entry is not None:
                moved = entry[0].retire_slot(entry[1])
                if moved:
                    logger.info("%d queued shard item(s) of retiring ingest "
                                "worker %d redistributed", moved, eid)
        for eid, entry in entries:
            if entry is None:
                continue
            ledger, pos = entry
            deadline = time.monotonic() + drain_timeout
            while time.monotonic() < deadline:
                if ledger.slot_idle(pos) and not ledger.needs_drain(pos):
                    break
                if not self.coordinator.is_tracked(eid):
                    break
                if self._closing.is_set():
                    break
                time.sleep(0.1)
        for eid in victims:
            if self.coordinator.is_tracked(eid):
                self._send_retirement_eof(eid)
        for eid in victims:
            self._reap_retired(eid, drain_timeout, "ingest worker")
            if eid in self._ingest_ids:
                self._ingest_ids.remove(eid)
            self._retiring.discard(eid)
            ttrace.event("ingest_scale_in", executor=eid)
        return victims

    def autoscale(self, policy=None, **kwargs):
        """Start a telemetry-driven autoscaling loop over :meth:`resize`:
        each tick samples ``cluster.stats(window)``, asks the policy for a
        desired node count, applies hysteresis (cooldown after any action;
        scale-in only after K consecutive under-target windows) and min/max
        bounds, and resizes.  Returns the started
        :class:`~tensorflowonspark_tpu.autoscale.Autoscaler` (stopped
        automatically at shutdown), or None when disabled via
        ``TOS_AUTOSCALE=0`` — the ops kill switch.

        Keyword args (``min_nodes``, ``max_nodes``, ``tick_secs``,
        ``cooldown_secs``, ``scale_in_ticks``, ``window``, ...) pass through
        to ``Autoscaler``; the ``TOS_AUTOSCALE_*`` knobs supply defaults.
        """
        if not _env_bool("TOS_AUTOSCALE", True):
            logger.warning("autoscaling disabled by TOS_AUTOSCALE=0; "
                           "cluster.autoscale() is a no-op")
            return None
        from tensorflowonspark_tpu.autoscale import Autoscaler

        scaler = Autoscaler(self, policy, **kwargs)
        scaler.start()
        self._autoscalers.append(scaler)
        return scaler

    # -- teardown (reference TFCluster.shutdown :~170-240, §3.5) -------------

    def shutdown(self, grace_secs: float = 0.0, timeout: float | None = None) -> None:
        """Send end-of-feed, join node processes, propagate node errors.

        ``timeout`` defaults to 120s, env-overridable via
        ``TOS_SHUTDOWN_TIMEOUT`` (and EOF delivery honours
        ``TOS_EOF_TIMEOUT``) — the ``TFOS_SERVER_TIMEOUT``-style ops knobs.
        """
        if timeout is None:
            timeout = _env_float("TOS_SHUTDOWN_TIMEOUT", 120.0)
        if self._shutdown_done:
            return
        # Autoscalers first: a policy loop firing resize() mid-teardown
        # would race the EOF/join sequence below.  _closing makes any
        # FUTURE resize() refuse and tells an in-flight drain to stop
        # waiting; the bare lock acquisition then barriers on that
        # in-flight resize actually releasing _feed_ids before teardown
        # iterates it (scaler.stop's 30s join alone could give up while a
        # long drain still holds the lock).
        self._closing.set()
        for scaler in self._autoscalers:
            with contextlib.suppress(Exception):
                scaler.stop()
        with self._resize_lock:
            pass
        # Stop the dead-node monitor first: shutdown's own escalation
        # (join -> stop -> terminate) owns failure handling from here, and
        # nodes it terminates must not be re-reported as deaths.  The
        # supervisor stops with it — a node dying during teardown is a
        # failure to report, not a slot to refill.
        self._monitor_stop.set()
        if self.supervisor is not None:
            self.supervisor.stop()
        if self.coordinator_supervisor is not None:
            # a coordinator crash during teardown stays down: the journal is
            # about saving runs, not resurrecting a server we are stopping
            self.coordinator_supervisor.stop()
        # Serving gateways first: their routers hold data-plane connections
        # and must stop dispatching before EOF ends the serving_loops.
        for gw in self._gateways:
            with contextlib.suppress(Exception):
                gw.close()
        self._gateways = []
        try:
            # EOF goes to BOTH input modes: a DIRECT-mode IngestFeed
            # consumes the path feed and its claimer winds down on
            # EndOfFeed exactly like a streaming DataFeed (self-service
            # DIRECT map_funs that never touch the feed leave it unread).
            # executor_id is assigned in REGISTRATION order, not launch
            # order — match processes through the launch_index each node
            # reported at registration (pids can't do this: over ssh
            # transports the local handle's pid is the ssh client).
            procs = self.launcher.processes
            id_to_proc = {
                m["executor_id"]: procs[m["launch_index"]]
                for m in self.cluster_info
                if 0 <= m.get("launch_index", -1) < len(procs)
            }
            # Ingest workers FIRST: their EOF ends the shard feed, each
            # service forwards its pipeline tail and exits — and the brief
            # join below lets that tail land BEFORE any trainer's
            # EndOfFeed is queued (FIFO: a chunk delivered before the
            # trainer's EOF is consumed, one after it is teardown-dropped).
            def _eof_node(executor_id: int) -> None:
                proc = id_to_proc.get(executor_id)
                if proc is not None and not proc.is_alive():
                    # node already finished and tore down its data plane;
                    # an EOF would only block on a dead peer
                    logger.debug("node %d already exited; skipping EOF",
                                 executor_id)
                    return
                for qname in self.input_qnames:
                    self._send_eof_best_effort(executor_id, qname, proc=proc)

            for executor_id in self._ingest_ids:
                _eof_node(executor_id)
            if self._ingest_ids:
                tail_deadline = time.monotonic() + min(15.0, timeout / 4.0)
                while time.monotonic() < tail_deadline and any(
                        p is not None and p.is_alive()
                        for p in (id_to_proc.get(e)
                                  for e in self._ingest_ids)):
                    time.sleep(0.1)
            for executor_id in self._feed_ids:
                _eof_node(executor_id)
            if grace_secs:
                time.sleep(grace_secs)
            # Politely wait for map_funs to finish; only then escalate.  The
            # stop flag breaks in-flight barriers/reduces, so raising it early
            # would abort healthy nodes mid-collective.  The wait is
            # DEATH-AWARE: if a node stops heartbeating mid-join, survivors
            # may be wedged in a collective with the dead peer forever —
            # waiting out the full polite timeout would just delay the
            # inevitable escalation (SURVEY.md §5.3 prompt fail-fast).
            forced = False
            death_detected = False
            deadline = time.monotonic() + timeout
            while True:
                slice_ = min(2.0, max(0.05, deadline - time.monotonic()))
                if self.launcher.join(slice_):
                    break
                dead = self._record_deaths()
                if dead:
                    death_detected = True
                    logger.warning("nodes %s died during shutdown; escalating now", dead)
                if death_detected or time.monotonic() >= deadline:
                    alive = self.launcher.alive()
                    logger.warning("nodes %s still running; signalling stop", alive)
                    self.coordinator.signal_stop()  # heartbeats tell stragglers to stop
                    # with a confirmed death, survivors wedged in collectives
                    # never drain — keep the post-stop grace short
                    if not self.launcher.join(5.0 if death_detected else 15.0):
                        forced = True
                        logger.warning("nodes %s ignored stop; terminating", self.launcher.alive())
                        self.launcher.terminate()
                    break
            for c in self._clients.values():
                c.close()
            # Run report BEFORE error propagation: a failed run is exactly
            # when the recorded restarts/faults/spans matter most.  Every
            # node has deregistered (or died) by now, so the coordinator's
            # per-node store holds the final snapshots.
            self._stop_metrics_export()
            # stream assembly copies every bounded span store and parses
            # every flight dump: gather once, feed both writers
            trace_streams: dict[str, dict] | None = None
            try:
                trace_streams = self._trace_streams_with_dumps()
            except Exception:  # noqa: BLE001 - tracing must not mask errors
                logger.warning("could not gather trace streams",
                               exc_info=True)
            try:
                trace_path = self.write_trace_artifacts(trace_streams)
                if trace_path:
                    logger.info("merged trace written to %s (load it at "
                                "https://ui.perfetto.dev)", trace_path)
            except Exception:  # noqa: BLE001 - tracing must not mask errors
                logger.warning("could not write trace artifacts",
                               exc_info=True)
            try:
                if telemetry.enabled() and _env_bool("TOS_RUN_REPORT", True):
                    report_path = self.write_run_report(
                        streams=trace_streams)
                    if report_path:
                        logger.info("run report written to %s", report_path)
            except Exception:  # noqa: BLE001 - reporting must not mask errors
                logger.warning("could not write run report", exc_info=True)
            self._raise_node_errors()
            all_codes = [p.exitcode for p in self.launcher.processes]
            if any(code is None for code in all_codes):
                # survived SIGTERM+SIGKILL: a live zombie may still hold chips
                raise RuntimeError(f"node processes could not be killed (exit codes {all_codes}); "
                                   f"zombie processes may be holding TPU devices")
            # intentionally-retired slots (resize scale-in) are excluded
            # from the audit: their terminate/kill-mid-drain exit codes are
            # the resize's business, not the job's verdict
            exit_codes = [c for i, c in enumerate(all_codes)
                          if i not in self._audit_waived]
            if forced:
                raise RuntimeError(f"node processes had to be force-terminated (exit codes {exit_codes})")
            if any(code != 0 for code in exit_codes):
                raise RuntimeError(f"node processes exited abnormally: {exit_codes}")
        finally:
            self._shutdown_done = True
            # idempotent: normally already stopped before the run report; an
            # early-raising shutdown path must still reap the export thread
            self._stop_metrics_export()
            self.coordinator.stop()

    def _stop_metrics_export(self) -> None:
        self._export_stop.set()
        if self._export_thread is not None:
            self._export_thread.join(timeout=10.0)
            self._export_thread = None

    def _raise_node_errors(self) -> None:
        errs = self.coordinator.errors()
        if errs:
            tb = errs[0].get("traceback", "")
            raise RuntimeError(
                f"node {errs[0].get('executor_id')} failed "
                f"({len(errs)} node error(s) total):\n{tb}"
            )

    # -- observability (reference TFCluster.tensorboard_url :~240-260) -------

    def metrics(self) -> dict:
        """Aggregated cluster-wide metrics snapshot.

        Per-node registry snapshots (as last reported over heartbeats /
        final deregister) plus the driver's own registry under ``"driver"``,
        merged by ``telemetry.aggregate_snapshots``: ``"counters"`` holds
        cluster totals, ``"histograms"`` merged span digests with pooled
        percentiles, ``"nodes"`` the per-node detail.
        """
        return self.coordinator.cluster_metrics()

    def stats(self, window: float = 10.0) -> dict:
        """Rolling-window LIVE stats — the autoscaling signals, not
        run-lifetime aggregates: qps, request p50/p99, serve-queue depth
        and in-flight batches (driver stream), plus per-node counter rates
        and feed-queue occupancy, all computed over the last ``window``
        seconds only.  The same payload is remotely queryable through the
        coordinator's ``statz`` op (``CoordinatorClient.stats``).  Headline
        fields live under ``"serving"``; per-stream detail under
        ``"streams"``."""
        return self.coordinator.cluster_stats(window)

    def _trace_streams_with_dumps(self) -> dict[str, dict]:
        """Every process's trace stream (heartbeat-shipped spans/events +
        clock offsets) keyed for export, plus any on-disk flight dumps a
        chaos kill left in ``log_dir`` (SIGKILL forecloses the heartbeat
        path — the dump file is the dead node's only record)."""
        streams: dict[str, dict] = {}
        for key, stream in self.coordinator.trace_streams().items():
            streams[key if key == "driver" else f"node{key}"] = stream
        if self.log_dir:
            for path in sorted(glob.glob(
                    os.path.join(self.log_dir, "flight_*.json"))):
                key = os.path.basename(path)[len("flight_"):-len(".json")]
                try:
                    with open(path, encoding="utf-8") as f:
                        streams[f"flight:{key}"] = json.load(f)
                except Exception:  # noqa: BLE001 - a torn dump must not mask the run
                    logger.debug("unreadable flight dump %s", path,
                                 exc_info=True)
        return streams

    def write_trace_artifacts(
            self, streams: dict[str, dict] | None = None) -> str | None:
        """Write the run's trace artifacts into ``log_dir``: one
        ``trace_<key>.json`` stream per process plus the merged,
        Perfetto-loadable ``trace.json``.  Returns the merged path, or
        None when tracing is off (``TOS_TRACE=0`` leaves zero artifacts)
        or there is no ``log_dir``.  Called automatically at shutdown;
        the standalone merge CLI is
        ``python -m tensorflowonspark_tpu.telemetry.trace_export``."""
        if not self.log_dir:
            return None
        if streams is None:
            streams = self._trace_streams_with_dumps()
        # Tracing may be armed in the node processes only
        # (cluster.run(env={"TOS_TRACE": "1"})): node-shipped spans count
        # even when the driver's own tracer is off.  Flight events alone
        # don't (they're recorded regardless of TOS_TRACE): an untraced
        # chaos run keeps its timeline in run_report.json, and TOS_TRACE=0
        # everywhere still leaves zero trace artifacts.
        if not (ttrace.enabled()
                or any(s.get("spans") for s in streams.values())):
            return None
        if not any(s.get("spans") or s.get("events")
                   for s in streams.values()):
            return None
        for key, stream in streams.items():
            if key.startswith("flight:"):
                continue  # the chaos dump is already its own file
            ttrace_export.write_stream(
                os.path.join(self.log_dir, f"trace_{key}.json"), stream)
        return ttrace_export.write_merged(
            os.path.join(self.log_dir, "trace.json"), streams)

    def debug_dump(self) -> str:
        """Human-readable text report of ``metrics()`` (paste into a bug
        report; the run report is the JSON twin)."""
        return telemetry.debug_dump(self.metrics())

    def write_run_report(self, path: str | None = None,
                         streams: dict[str, dict] | None = None) -> str | None:
        """Write the end-of-run JSON run report; returns the path (None when
        there is nowhere to write: no ``path`` and no ``log_dir``).

        Called automatically at ``shutdown()`` when ``TOS_RUN_REPORT`` is on
        and the cluster has a ``log_dir`` — the report lands next to the
        job's event files / checkpoints as ``run_report.json``.
        """
        if path is None:
            if not self.log_dir:
                return None
            path = os.path.join(self.log_dir, "run_report.json")
        extras: dict = {
            "num_executors": len(self.cluster_info),
            "node_errors": len(self.coordinator.errors()),
            "restarts_by_executor": (
                {str(eid): self.supervisor.restart_count(eid)
                 for eid in self._feed_ids
                 if self.supervisor.restart_count(eid)}
                if self.supervisor is not None else {}),
        }
        if self.coordinator_supervisor is not None and self.coordinator.epoch:
            # a control-plane failover happened: the headline evidence
            extras["coordinator"] = {
                "epoch": self.coordinator.epoch,
                "recoveries": self.coordinator_supervisor.restart_count(),
            }
        if self._resize_log or self._autoscalers:
            # the elasticity postmortem: every resize the run performed and
            # (when a policy loop drove them) every decision it took
            autoscale_block: dict = {
                "final_nodes": self.num_feedable(),
                "resizes": [dict(r) for r in self._resize_log],
            }
            for scaler in self._autoscalers:
                try:
                    autoscale_block.setdefault("policies", []).append(
                        scaler.report())
                except Exception:  # noqa: BLE001 - reporting must not mask the run
                    logger.debug("autoscaler report failed", exc_info=True)
            extras["autoscale"] = autoscale_block
        try:
            # flight-recorder timeline: every process's structured events
            # (kills, deaths, retries, resyncs, reloads) merged onto the
            # driver clock — the postmortem a chaos exit is read by
            flight = ttrace.merge_events(
                self._trace_streams_with_dumps()
                if streams is None else streams)
            if flight:
                extras["flight"] = {"events": flight}
        except Exception:  # noqa: BLE001 - reporting must not mask the run error
            logger.debug("could not merge flight events", exc_info=True)
        report = telemetry.build_run_report(
            self.metrics(),
            wall_secs=round(time.monotonic() - self._started_at, 3),
            extras=extras)
        return telemetry.write_run_report(path, report)

    def _metrics_export_loop(self) -> None:
        """Every ``TOS_METRICS_EXPORT_SECS``: aggregate + write TB scalars."""
        from tensorflowonspark_tpu.summary import SummaryWriter

        period = _env_float("TOS_METRICS_EXPORT_SECS", 30.0)
        writer: SummaryWriter | None = None
        step = 0
        while not self._export_stop.wait(period):
            step += 1
            try:
                if writer is None:
                    writer = SummaryWriter(os.path.join(self.log_dir, "metrics"))
                self._export_metrics_once(writer, step)
            except Exception:  # noqa: BLE001 - observability must not kill jobs
                logger.warning("metrics export failed", exc_info=True)
        # final flush on stop so short runs still leave a scalar trail
        try:
            if writer is None:
                writer = SummaryWriter(os.path.join(self.log_dir, "metrics"))
            self._export_metrics_once(writer, step + 1)
            writer.close()
        except Exception:  # noqa: BLE001
            logger.debug("final metrics export failed", exc_info=True)

    def _export_metrics_once(self, writer, step: int) -> None:
        snap = self.metrics()
        scalars: dict[str, float] = {}
        for name, value in (snap.get("counters") or {}).items():
            scalars[f"metrics/{name}"] = float(value)
        for name, d in (snap.get("histograms") or {}).items():
            for key in ("mean", "p50", "p90", "p99"):
                v = d.get(key)
                if v is not None:
                    scalars[f"metrics/{name}/{key}"] = float(v)
        if scalars:
            writer.add_scalars(scalars, step=step)
            writer.flush()

    def chip_plan(self):
        """Authoritative global chip numbering across the registered nodes
        (``tpu_info.plan_topology`` over each node's reported
        ``device_summary``, in executor-id order) — the driver-side
        replacement for the reference's per-executor randomized GPU picking
        (``gpu_info.py``; SURVEY.md §5.2 disposition).  Returns one
        ``HostAssignment`` per node; evaluators report their chips too but
        own no data-plane role."""
        from tensorflowonspark_tpu import tpu_info

        infos = self.coordinator.cluster_info()
        pending = [m["executor_id"] for m in infos
                   if (m.get("device") or {}).get("num_devices") is None]
        if pending:
            # jax_distributed nodes register a placeholder and report real
            # device facts only after jax.distributed.initialize — a plan
            # built from placeholders would be silently all-zero
            raise RuntimeError(
                f"chip plan unavailable: nodes {pending} have not reported "
                "device facts yet (distributed nodes report after their "
                "jax.distributed bootstrap); retry once the job is running")
        counts = [int((m.get("device") or {}).get("num_devices") or 0)
                  for m in infos]
        return tpu_info.plan_topology(counts)

    def tensorboard_url(self) -> str | None:
        for meta in self.coordinator.cluster_info():
            if "tb_url" in meta:
                return meta["tb_url"]
        return None


def run(
    map_fun: Callable,
    tf_args: Any = None,
    num_executors: int = 1,
    input_mode: InputMode = InputMode.DIRECT,
    master_node: str | None = None,
    eval_node: bool = False,
    tensorboard: bool = False,
    log_dir: str = "",
    default_fs: str = "",
    queues: Sequence[str] = ("input", "output", "error"),
    queue_capacity: int = 1024,
    feed_timeout: float | None = None,
    reservation_timeout: float | None = None,
    heartbeat_interval: float = 2.0,
    launcher: Any | None = None,
    env: dict[str, str] | None = None,
    per_node_env: Sequence[dict[str, str]] | None = None,
    jax_distributed: bool = False,
    coordinator_host: str | None = None,
    elastic: bool | RestartPolicy = False,
    ingest_workers: int | None = None,
    ingest_opts: dict | None = None,
) -> TPUCluster:
    """Start a cluster (reference ``TFCluster.run`` ``:~270-420``).

    No ``sc`` (no Spark), no ``num_ps`` (sync SPMD replaces parameter
    servers), no ``driver_ps_nodes``/``release_port`` (their race classes are
    designed out — SURVEY.md §5.2).

    ``env`` applies to every node; ``per_node_env`` (one dict per executor)
    layers per-process overrides on top — the carrier for disjoint
    accelerator slices (``tpu_info.chip_visibility_env``) when several node
    processes share a host.

    ``reservation_timeout``/``feed_timeout`` default from the
    ``TOS_RESERVATION_TIMEOUT``/``TOS_FEED_TIMEOUT`` env vars when not given
    (the reference's ``TFOS_SERVER_TIMEOUT``-style ops knobs), else
    120s/600s.

    ``elastic`` turns data-node deaths into supervised restarts (True for the
    env-tuned ``RestartPolicy``, or pass a policy): the slot's incarnation is
    fenced, the process is respawned with backoff, the replacement resumes
    from the latest checkpoint (``ctx.is_restart`` /
    ``checkpoint.restore_for_restart``), and unacknowledged partitions are
    re-fed (at-least-once for training; exactly-once per partition for
    inference).  Feed-driven map_funs only: a ``jax.distributed`` job cannot
    readmit a process into a live XLA world, so the combination is refused,
    and map_funs built on control-plane consensus (``ctx.all_done``) need
    application-level resync a restart does not provide.

    ``ingest_workers`` (default ``TOS_INGEST_WORKERS``) adds that many
    standalone DATA-SERVICE nodes (role ``ingest``, the tf.data-service
    design): a DIRECT-mode ``train()`` then feeds its shard items to the
    worker pool, which decodes on its own cores (with the cross-epoch
    chunk cache, ``TOS_INGEST_CACHE_BYTES``) and streams packed chunks to
    every trainer over the zero-copy wire — decode parallelism becomes the
    ``cluster.resize_ingest`` fleet knob instead of a per-trainer
    constant.  ``ingest_opts`` carries the tier's decode configuration
    (``schema=``, ``chunk_records=``, ``readers=``, ``cache_bytes=``,
    ``shuffle=``, ... — :class:`~tensorflowonspark_tpu.ingest.service.
    IngestService` keywords).  DIRECT mode only, and not combinable with
    ``jax_distributed`` (the workers are not XLA-world members).

    ``coordinator_host`` pins the control-plane bind/advertise interface
    (default: bind all interfaces, advertise the routable ``local_ip()`` so
    remote executors launched over ssh can actually dial back — reference
    ``reservation.Server`` behavior).  The control plane authenticates every
    connection with the per-cluster ``authkey`` (HMAC challenge-response,
    same handshake as the data plane).
    """
    # TPUPodLauncher forces jax_distributed=True on every NodeConfig it
    # launches, so checking the parameter alone would let a pod job slip
    # past the guard.
    if elastic and (jax_distributed or isinstance(launcher, TPUPodLauncher)):
        raise ValueError(
            "elastic=... cannot be combined with a jax.distributed job "
            "(jax_distributed=True or a TPUPodLauncher): a restarted "
            "process cannot rejoin a live jax.distributed XLA world "
            "(TF-Replicator generation semantics); run elastic jobs as "
            "per-host meshes")
    if reservation_timeout is None:
        reservation_timeout = _env_float("TOS_RESERVATION_TIMEOUT", 120.0)
    if feed_timeout is None:
        feed_timeout = _env_float("TOS_FEED_TIMEOUT", 600.0)
    if ingest_workers is None:
        ingest_workers = _env_int("TOS_INGEST_WORKERS", 0, minimum=0)
    ingest_workers = max(0, int(ingest_workers))
    if ingest_workers and input_mode != InputMode.DIRECT:
        raise ValueError(
            "ingest_workers need InputMode.DIRECT: the data-service tier "
            "claims shard items from the ledger (STREAMING clusters stream "
            "rows from the driver and have nothing for the tier to decode)")
    if ingest_workers and jax_distributed:
        raise ValueError(
            "ingest_workers cannot be combined with jax_distributed: "
            "data-service workers are not members of the XLA world and "
            "jax.distributed.initialize counts contiguous process ids")
    total_procs = num_executors + ingest_workers
    if per_node_env is not None and len(per_node_env) not in (
            num_executors, total_procs):
        raise ValueError(f"per_node_env needs {num_executors} (trainer) or "
                         f"{total_procs} (trainer+ingest) entries, got "
                         f"{len(per_node_env)}")
    roles = _build_roles(num_executors, master_node, eval_node)
    # data-service slots come LAST so trainer/evaluator ids keep their
    # contiguous reference layout; role assignment is registration-order,
    # so node_main's role-aware dispatch (not the config) decides which
    # process actually runs the service loop
    roles.extend(("ingest", i) for i in range(ingest_workers))
    authkey = secrets.token_bytes(16)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    # Control-plane write-ahead journal (ISSUE 13): with a log_dir every
    # coordinator mutation is journaled to <log_dir>/coordinator.journal and
    # a coordinator crash becomes a supervised, epoch-bumping restart
    # (TPUCluster wires the CoordinatorSupervisor); journal-less
    # coordinators keep the old behaviour — a crash is fatal.
    coordinator = CoordinatorServer(
        total_procs, roles, authkey=authkey,
        journal_path=(os.path.join(log_dir, "coordinator.journal")
                      if log_dir else None))
    addr = coordinator.start(coordinator_host)

    configs = [
        NodeConfig(
            coordinator_addr=addr,
            authkey=authkey,
            map_fun=map_fun,
            tf_args=tf_args,
            queues=tuple(queues),
            input_qnames=tuple(q for q in queues if q not in ("output", "error")),
            input_mode=("direct" if input_mode == InputMode.DIRECT
                        else "streaming"),
            queue_capacity=queue_capacity,
            feed_timeout=feed_timeout,
            reservation_timeout=reservation_timeout,
            heartbeat_interval=heartbeat_interval,
            default_fs=default_fs,
            log_dir=log_dir,
            tensorboard=tensorboard,
            jax_distributed=jax_distributed,
            env={**(env or {}),
                 **(per_node_env[i] if per_node_env is not None
                    and i < len(per_node_env) else {})},
            launch_index=i,
            ingest_opts=dict(ingest_opts) if ingest_opts else None,
        )
        for i in range(total_procs)
    ]
    # Default to SubprocessLauncher: children run the lean ``node_entry``
    # module directly (~0.5s to a live node), where multiprocessing-spawn
    # re-imports the driver's __main__ machinery in every child (~3s under
    # pytest), and OS-level env lands before any site hook can import jax.
    launcher = launcher or SubprocessLauncher()
    launcher.launch(configs, log_dir or None)
    try:
        cluster_info = coordinator.await_registrations(reservation_timeout)
    except TimeoutError:
        launcher.terminate()
        coordinator.stop()
        raise
    logger.info("cluster up: %s", [(m["executor_id"], m["job_name"]) for m in cluster_info])
    return TPUCluster(coordinator, launcher, cluster_info, authkey, input_mode,
                      queues, feed_timeout, heartbeat_interval, elastic=elastic,
                      log_dir=log_dir)
