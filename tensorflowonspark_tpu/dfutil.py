"""Row-data ⇄ TFRecord bridge with schema inference.

The TPU-native replacement for ``tensorflowonspark/dfutil.py`` (~230 LoC):
``saveAsTFRecords``/``loadTFRecords``/``toTFExample``/``fromTFExample``/
``infer_schema`` operated on Spark DataFrames via the tensorflow-hadoop jar;
here the same capabilities operate on ``PartitionedDataset`` rows (dicts)
through the in-repo TFRecord + Example codecs — no Spark, no JVM, no TF.

A "row" is a ``dict[str, value-or-list]``.  Scalars round-trip as length-1
lists unless the schema marks them scalar.
"""

from __future__ import annotations

import contextlib
import dataclasses
import glob as _glob
import json
import os
from typing import Iterator

from tensorflowonspark_tpu import example as ex
from tensorflowonspark_tpu import tfrecord
from tensorflowonspark_tpu.data import PartitionedDataset
from tensorflowonspark_tpu.utils.paths import resolve_uri

_TYPES = ("bytes", "float", "int64")


@dataclasses.dataclass
class ColumnSpec:
    name: str
    dtype: str  # bytes | float | int64
    scalar: bool = True
    # Fixed per-record value count for non-scalar columns, when known
    # (``infer_schema`` records the representative row's width); None
    # declares the column RAGGED.  Columnar consumers key their batch
    # representation on THIS — never on any one chunk's data — so the
    # shape a map_fun sees is stable across chunks and shards.
    width: int | None = None


@dataclasses.dataclass
class Schema:
    """Column layout of a record dataset (reference ``infer_schema``)."""

    columns: list[ColumnSpec]

    def to_json(self) -> str:
        # ``width: null`` is omitted (None is the default anyway): schema
        # files written without any declared width stay readable by older
        # releases whose ColumnSpec predates the field
        return json.dumps([
            {k: v for k, v in dataclasses.asdict(c).items()
             if not (k == "width" and v is None)}
            for c in self.columns])

    @classmethod
    def from_json(cls, s: str) -> "Schema":
        # tolerate unknown keys both ways: old JSON lacking ``width``
        # defaults it, and JSON from a NEWER release (extra fields) must
        # not break this one — schema files outlive installs
        known = {f.name for f in dataclasses.fields(ColumnSpec)}
        return cls([ColumnSpec(**{k: v for k, v in c.items() if k in known})
                    for c in json.loads(s)])

    def __getitem__(self, name: str) -> ColumnSpec:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(name)


def _dtype_of(value) -> str:
    v = value[0] if isinstance(value, (list, tuple)) and value else value
    if isinstance(v, (bytes, bytearray, str)):
        return "bytes"
    if isinstance(v, bool):
        return "int64"
    if isinstance(v, float):
        return "float"
    if isinstance(v, int):
        return "int64"
    # numpy scalars / arrays
    import numpy as np

    if isinstance(v, np.floating):
        return "float"
    if isinstance(v, (np.integer, np.bool_)):
        return "int64"
    raise TypeError(f"unsupported value type {type(v).__name__}")


def infer_schema(row: dict) -> Schema:
    """Infer a Schema from one representative row (reference ``infer_schema``,
    ``dfutil.py:~200-230``)."""
    cols = []
    for name in sorted(row):
        value = row[name]
        scalar = not isinstance(value, (list, tuple))
        import numpy as np

        if isinstance(value, np.ndarray):
            scalar = value.ndim == 0
            value = value.tolist()
        width = None if scalar else len(value)
        cols.append(ColumnSpec(name, _dtype_of(value), scalar, width))
    return Schema(cols)


def to_example(row: dict, schema: Schema | None = None) -> bytes:
    """Serialize one row to a ``tf.train.Example`` (reference ``toTFExample``)."""
    import numpy as np

    feats = {}
    for name, value in row.items():
        if isinstance(value, np.ndarray):
            value = value.tolist()
        if not isinstance(value, (list, tuple)):
            value = [value]
        if schema is not None:
            dtype = schema[name].dtype
            cast = {"bytes": lambda v: v if isinstance(v, (bytes, bytearray)) else str(v).encode(),
                    "float": float, "int64": int}[dtype]
            value = [cast(v) for v in value]
        else:
            # untyped path: floats stay floats, ints stay ints, str → bytes
            value = [v.encode() if isinstance(v, str) else v for v in value]
        feats[name] = list(value)
    return ex.encode_example(feats)


def from_example(buf: bytes, schema: Schema | None = None, binary_features: set | None = None) -> dict:
    """Deserialize an Example into a row (reference ``fromTFExample``).

    ``binary_features`` mirrors the reference's option: bytes columns listed
    there stay ``bytes``; other bytes columns decode to ``str``.
    """
    raw = ex.decode_example(buf)
    row = {}
    for name, values in raw.items():
        if values and isinstance(values[0], bytes) and (binary_features is None or name not in binary_features):
            values = [v.decode("utf-8", errors="replace") for v in values]
        if schema is not None and schema[name].scalar and len(values) == 1:
            row[name] = values[0]
        else:
            row[name] = values
    return row


def save_as_tfrecords(data: PartitionedDataset, output_dir: str, schema: Schema | None = None,
                      compression: str | None = None) -> Schema:
    """Write one TFRecord shard per partition (reference ``saveAsTFRecords``,
    ``dfutil.py:~30-60``); stores the schema alongside as ``_schema.json``.
    ``compression='gzip'`` writes TF-compatible gzipped shards (``.gz``
    suffix; readers auto-detect)."""
    output_dir = resolve_uri(output_dir)
    os.makedirs(output_dir, exist_ok=True)
    # Crash-safe clobber semantics: a re-save replaces the directory's shard
    # set (with compression the shard NAMES change — .gz suffix — so stale
    # shards must go or shard_files() would load both generations), but the
    # previous generation must survive any mid-save failure (schema
    # inference error, disk full, interrupt).  So: write the new generation
    # under temp names invisible to shard_files()'s ``part-*`` glob, and
    # only after every partition is fully written delete the old shards and
    # rename the new ones into place.
    for orphan in _glob.glob(os.path.join(output_dir, ".tmp-part-*")):
        os.remove(orphan)  # uncommitted leftovers of an earlier crashed save
    suffix = ".gz" if compression and compression.lower() == "gzip" else ""
    tmp_final: list[tuple[str, str]] = []
    # Widths auto-inferred from ONE representative row are only a guess;
    # ragged data must RELAX them to None while writing, or the stored
    # schema would promise a fixed-width columnar layout the shards break
    # mid-train.  A caller-provided schema's declarations are its own.
    inferred = schema is None
    try:
        for p in range(data.num_partitions):
            name = f"part-r-{p:05d}{suffix}"
            tmp = os.path.join(output_dir, f".tmp-{name}")
            with tfrecord.RecordWriter(tmp, compression=compression) as w:
                for row in data.iter_partition(p):
                    if schema is None:
                        schema = infer_schema(row)
                    elif inferred:
                        _relax_widths(schema, row)
                    w.write(to_example(row, schema))
            tmp_final.append((tmp, os.path.join(output_dir, name)))
        if schema is None:
            raise ValueError("dataset is empty; cannot infer a schema")
    except BaseException:
        # includes the half-written shard whose writer raised (it is not in
        # tmp_final yet); all .tmp-part-* here are ours and uncommitted
        for tmp in _glob.glob(os.path.join(output_dir, ".tmp-part-*")):
            with contextlib.suppress(OSError):
                os.remove(tmp)
        raise
    for stale in _glob.glob(os.path.join(output_dir, "part-*")):
        os.remove(stale)
    for tmp, final in tmp_final:
        os.replace(tmp, final)
    with open(os.path.join(output_dir, "_schema.json"), "w") as f:
        f.write(schema.to_json())
    return schema


def _relax_widths(schema: Schema, row: dict) -> None:
    """Demote an auto-inferred fixed column width to ragged (None) the
    moment any row disagrees with it — the stored schema must describe
    the data that was actually written."""
    for c in schema.columns:
        if c.width is None:
            continue
        value = row.get(c.name)
        if isinstance(value, (list, tuple)):
            n = len(value)
        elif hasattr(value, "ndim"):  # ndarray
            n = 1 if value.ndim == 0 else len(value)
        else:
            n = 0 if value is None else 1
        if n != c.width:
            c.width = None


def shard_files(input_dir: str) -> list[str]:
    """List the TFRecord shard files of a dataset directory, sorted."""
    local = resolve_uri(input_dir)
    files = sorted(f for f in _glob.glob(os.path.join(local, "part-*")) if not f.endswith(".json"))
    if not files:
        raise FileNotFoundError(f"no TFRecord shards under {local}")
    return files


def read_schema(input_dir: str) -> Schema | None:
    """Load the ``_schema.json`` stored next to the shards, if present."""
    path = os.path.join(resolve_uri(input_dir), "_schema.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return Schema.from_json(f.read())


def read_shard(path: str, schema: Schema | None = None,
               binary_features: set | None = None) -> Iterator[dict]:
    """Iterate one shard file as rows."""
    for rec in tfrecord.read_records(path):
        yield from_example(rec, schema, binary_features)


def read_shard_columns(path: str, schema: Schema,
                       binary_features: set | None = None
                       ) -> tuple[dict, dict]:
    """Columnar shard decode — the data-loader fast path.

    Returns ``(columns, counts)``: ``columns[name]`` is every value of that
    feature across the shard, concatenated (``np.float32``/``np.int64``
    ndarray, or a list of ``bytes``/``str``); ``counts[name]`` is the
    per-record value count (``uint64``; 0 where a record lacks the feature),
    so fixed-width columns reshape to ``[n_records, k]`` and ragged ones
    split by ``np.cumsum``.

    With the native parser (``native/example_parser.cc``) the whole shard is
    decoded in C++ — two ctypes calls per column instead of a Python proto
    walk per record (~25x on tabular/float-heavy shards; image-bytes shards
    are IO-bound either way — see PERF_NOTES).  The pure-Python fallback
    produces identical output, including dtype-mismatch errors.

    The buffer-level half is :func:`decode_span_columns` — the ingest
    reader pipeline calls it per decoded chunk so a shard (or sub-shard
    span range) materializes as K contiguous column buffers without this
    wrapper's whole-shard materialization.
    """
    buf, spans = tfrecord.read_record_spans(path)
    return decode_span_columns(buf, spans, schema, binary_features)


def decode_span_columns(buf, spans, schema: Schema,
                        binary_features: set | None = None
                        ) -> tuple[dict, dict]:
    """Columnar Example decode of record payload ``spans`` within ``buf``
    (a ``tfrecord.read_record_spans``/``read_span_range`` result, or any
    record-aligned subset of its spans).  Same ``(columns, counts)``
    contract as :func:`read_shard_columns`; the native parser decodes the
    whole span set in C++ when built."""

    try:
        from tensorflowonspark_tpu import example_native
    except Exception:  # noqa: BLE001 - no compiler: pure-Python fallback
        example_native = None

    decode_bytes = _bytes_decoder(binary_features)
    if example_native is not None:
        spans = example_native.span_arrays(spans)  # one O(n) walk, not per column
        columns, counts = {}, {}
        for c in schema.columns:
            values, cnt = example_native.extract_column(buf, spans, c.name, c.dtype)
            if c.dtype == "bytes":
                values = decode_bytes(c.name, values)
            columns[c.name] = values
            counts[c.name] = cnt
        return columns, counts

    payloads = (buf[off:off + length] for off, length in spans)
    return _accumulate_columns(payloads, schema, decode_bytes)


def records_to_columns(payloads, schema: Schema,
                       binary_features: set | None = None
                       ) -> tuple[dict, dict]:
    """Columnar accumulation over an iterable of raw Example payloads —
    the streaming twin of :func:`decode_span_columns` for shards with no
    byte-addressable spans (gzip: records stream in, columns come out)."""
    return _accumulate_columns(payloads, schema,
                               _bytes_decoder(binary_features))


def _bytes_decoder(binary_features: set | None):
    def _decode_bytes(name, values):
        if binary_features is None or name not in binary_features:
            return [v.decode("utf-8", errors="replace") for v in values]
        return values

    return _decode_bytes


def _accumulate_columns(payloads, schema: Schema, decode_bytes
                        ) -> tuple[dict, dict]:
    import numpy as np

    expect = {"bytes": bytes, "float": float, "int64": int}
    acc: dict[str, list] = {c.name: [] for c in schema.columns}
    cnt: dict[str, list] = {c.name: [] for c in schema.columns}
    for rec in payloads:
        raw = ex.decode_example(bytes(rec) if isinstance(rec, memoryview)
                                else rec)
        for c in schema.columns:
            values = raw.get(c.name, [])
            # mirror the native path's kind check: a float column read under
            # an int64 schema must raise, not silently truncate
            if values and not isinstance(values[0], expect[c.dtype]):
                raise TypeError(f"feature {c.name!r} is not of dtype {c.dtype!r}")
            acc[c.name].extend(values)
            cnt[c.name].append(len(values))
    columns, counts = {}, {}
    for c in schema.columns:
        if c.dtype == "float":
            columns[c.name] = np.asarray(acc[c.name], np.float32)
        elif c.dtype == "int64":
            columns[c.name] = np.asarray(acc[c.name], np.int64)
        else:
            columns[c.name] = decode_bytes(c.name, acc[c.name])
        counts[c.name] = np.asarray(cnt[c.name], np.uint64)
    return columns, counts


class ColumnChunk:
    """A decoded chunk of Example records as K contiguous column buffers.

    What the ingest reader pipeline pushes in columnar (``schema=``) mode
    instead of a per-record row list: ``columns[name]`` holds the chunk's
    concatenated values (ndarray for float/int64, list for bytes/str) and
    ``counts[name]`` the per-record value counts — the
    :func:`decode_span_columns` layout, chunk-sized.  ``slice(a, b)``
    serves batch windows as zero-copy views whose REPRESENTATION is fixed
    by the SCHEMA, never by any one chunk's data (a chunk that happens to
    be uniform must not change the shape a map_fun sees mid-feed):
    scalar columns come back ``[n]``, declared-width columns ``[n, k]``,
    and ``width=None`` (ragged) columns as a ``(values, counts)`` pair.
    A record violating its column's declared scalar/width raises a loud
    ``ValueError`` naming the column (declare ``width=None`` in the
    schema for genuinely ragged data).  ``rows()`` expands to row-dicts
    (the wire-side inverse — ``data.pack_chunk`` ships a ColumnChunk as
    one out-of-band buffer per numeric column).
    """

    __slots__ = ("columns", "counts", "n", "scalars", "widths", "_offsets",
                 "_validated")

    def __init__(self, columns: dict, counts: dict, n: int,
                 scalars: frozenset = frozenset(),
                 widths: dict | None = None):
        self.columns = columns
        self.counts = counts
        self.n = n
        self.scalars = scalars
        # name -> declared fixed width (1 for scalar), or None = ragged;
        # missing names (legacy schemas) default to ragged — stable, if
        # less convenient, for data whose width nobody declared
        self.widths = widths if widths is not None else {}
        self._offsets: dict = {}
        self._validated: set = set()

    @classmethod
    def from_schema(cls, columns: dict, counts: dict, schema: Schema
                    ) -> "ColumnChunk":
        n = len(next(iter(counts.values()))) if counts else 0
        widths = {c.name: 1 if c.scalar else getattr(c, "width", None)
                  for c in schema.columns}
        return cls(columns, counts, n,
                   frozenset(c.name for c in schema.columns if c.scalar),
                   widths)

    def __reduce__(self):
        # plain tuple state: ndarray columns ride pickle protocol 5's
        # native out-of-band buffer support (one buffer per column)
        return (_rebuild_column_chunk,
                (self.columns, self.counts, self.n, tuple(self.scalars),
                 self.widths))

    def __len__(self) -> int:
        return self.n

    def _col_width(self, name: str):
        """The column's schema-declared width (None = ragged), VALIDATED
        against this chunk's counts once (own marker set — the offsets
        cache must not stand in for it, or a rows() call would bypass the
        check): fixed-width representation with non-conforming data
        mis-frames silently, so it fails loudly."""
        import numpy as np

        w = self.widths.get(name)
        if w is not None and name not in self._validated:
            counts = np.asarray(self.counts[name], np.int64)
            if counts.size and (counts.min() != w or counts.max() != w):
                bad = int(counts[(counts != w).argmax()]) \
                    if hasattr(counts, "argmax") else "?"
                raise ValueError(
                    f"column {name!r} declares width {w} but a record has "
                    f"{bad} values; declare width=None in the schema for "
                    "ragged columns")
            self._validated.add(name)
        return w

    def _col_offsets(self, name: str):
        import numpy as np

        off = self._offsets.get(name)
        if off is None:
            counts = np.asarray(self.counts[name], np.int64)
            off = np.concatenate(([0], np.cumsum(counts)))
            self._offsets[name] = off
        return off

    def slice(self, a: int, b: int) -> dict:
        """Columns of records ``[a, b)`` as zero-copy views: scalar
        columns ``[n]`` (flat lists for bytes), declared-width columns
        ``[n, k]`` ndarray views (list-of-lists for bytes), ragged
        (``width=None``) columns ``(values, counts)`` pairs."""
        out = {}
        for name, values in self.columns.items():
            k = self._col_width(name)
            if k is not None:
                vals = values[a * k:b * k]
                if k == 1:
                    out[name] = vals
                elif hasattr(vals, "reshape"):
                    out[name] = vals.reshape(b - a, k)
                else:  # bytes column, k values per record
                    out[name] = [vals[i * k:(i + 1) * k] for i in range(b - a)]
            else:
                off = self._col_offsets(name)
                lo, hi = int(off[a]), int(off[b])
                out[name] = (values[lo:hi], self.counts[name][a:b])
        return out

    def rows(self) -> list[dict]:
        """Expand back to per-record row dicts (``from_example`` shape:
        scalar-schema columns unwrap single values, others stay lists)."""
        out: list[dict] = [{} for _ in range(self.n)]
        for name, values in self.columns.items():
            off = self._col_offsets(name)
            scalar = name in self.scalars
            for i in range(self.n):
                lo, hi = int(off[i]), int(off[i + 1])
                vals = values[lo:hi]
                if not isinstance(vals, list):
                    vals = vals.tolist()
                out[i][name] = vals[0] if scalar and len(vals) == 1 else vals
        return out


def _rebuild_column_chunk(columns, counts, n, scalars,
                          widths=None) -> ColumnChunk:
    return ColumnChunk(columns, counts, n, frozenset(scalars), widths)


def rows_to_columns(rows: list) -> tuple[tuple, list] | None:
    """Reshape a chunk of row-dicts into ``(keys, per-key value lists)``.

    The columnar half of the zero-copy wire format (``data.pack_chunk``):
    a chunk of homogeneous row-dicts — the shape every ``dfutil`` reader
    and the pipeline layer produce — serializes as one header + per-column
    contiguous buffers instead of K dict pickles.  Returns None when the
    rows do not share one key set (heterogeneous chunks stay row-major).
    """
    if not rows or not isinstance(rows[0], dict):
        return None
    keys = tuple(rows[0])
    keyset = set(keys)
    for r in rows:
        if type(r) is not dict or len(r) != len(keys) or set(r) != keyset:
            return None
    return keys, [[r[k] for r in rows] for k in keys]


def columns_to_rows(keys: tuple, value_lists: list) -> list[dict]:
    """Inverse of ``rows_to_columns`` (kept here so the two can never
    drift; ``data.PackedChunk.rows`` is the wire-side consumer)."""
    return [dict(zip(keys, vals)) for vals in zip(*value_lists)]


def load_tfrecords(input_dir: str, binary_features: set | None = None) -> tuple[PartitionedDataset, Schema | None]:
    """Load a TFRecord directory as a PartitionedDataset of rows (reference
    ``loadTFRecords``, ``dfutil.py:~60-100``); one partition per shard file."""
    schema = read_schema(input_dir)
    files = shard_files(input_dir)
    return (
        PartitionedDataset([(lambda f=f: read_shard(f, schema, binary_features)) for f in files]),
        schema,
    )
