"""TPU chip discovery and topology assignment — the ``gpu_info`` replacement.

Reference (``tensorflowonspark/gpu_info.py``): parse ``nvidia-smi``, pick
free GPUs with randomized retries to dodge allocation races between
executors sharing a host, export ``CUDA_VISIBLE_DEVICES``.

TPU-native redesign (SURVEY.md §2.2 row "Hops-YARN GPU scheduling", §5.2):
TPU chips are per-host hardware, not a shared pool to race over, and the
platform already knows its own topology.  So this module:

- **discovers** what this process can see (``device_summary`` — platform,
  chip kind, count, per-chip mesh coordinates from PJRT) for the node's
  coordinator registration payload;
- **assigns** race-free: ``plan_topology`` computes each host's process
  index and chip-coordinate block centrally (the coordinator calls it once,
  replacing gpu_info's randomized retries with deterministic assignment);
- **scopes visibility** for subprocesses: ``chip_visibility_env`` returns
  the env (``TPU_VISIBLE_CHIPS``/``TPU_PROCESS_BOUNDS``-style, or
  ``JAX_PLATFORMS``/``XLA_FLAGS`` for CPU simulation) that makes a child
  process see only its slice — the ``CUDA_VISIBLE_DEVICES`` analogue.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence


def is_tpu_available() -> bool:
    """Reference parity: ``gpu_info.is_gpu_available()``."""
    try:
        import jax

        return any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        return False


def _forced_cpu_device_count() -> int:
    """CPU device count jax will create, from env alone.

    ``JAX_NUM_CPU_DEVICES`` wins (it is what ``chip_visibility_env`` emits
    per node and overrides the flag inside jax); else the conftest-style
    ``--xla_force_host_platform_device_count`` in XLA_FLAGS; else 1."""
    import os
    import re

    n = os.environ.get("JAX_NUM_CPU_DEVICES")
    if n:
        return int(n)
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                  os.environ.get("XLA_FLAGS", ""))
    return int(m.group(1)) if m else 1


def device_summary() -> dict:
    """What this process sees; goes into the coordinator registration payload
    so the driver's ``cluster_info`` reports real hardware per node."""
    import os
    import sys

    # Env-forced CPU platform and jax not loaded yet: synthesize the summary
    # instead of paying a ~3s jax import + backend init in every node
    # process — control-plane-only nodes (and every CPU test node) never
    # need the backend, and the env already states exactly what it would
    # report.  Once jax IS loaded (compute nodes), report live state.
    if "jax" not in sys.modules and os.environ.get(
            "JAX_PLATFORMS", "").split(",")[0] == "cpu":
        return {
            "platform": "cpu",
            "device_kind": "cpu",
            "num_devices": _forced_cpu_device_count(),
            "coords": [],
            "process_index": 0,
        }
    try:
        import jax

        # local_devices/process_index, NOT jax.devices(): after
        # jax.distributed.initialize the latter is pod-global, and every node
        # would report the whole pod's chips instead of its own.
        devices = jax.local_devices()
        return {
            "platform": jax.default_backend(),
            "device_kind": devices[0].device_kind if devices else "none",
            "num_devices": len(devices),
            "coords": [list(getattr(d, "coords", ()) or ()) for d in devices],
            "process_index": jax.process_index(),
        }
    except Exception:
        return {"platform": "none", "device_kind": "none", "num_devices": 0,
                "coords": [], "process_index": 0}


@dataclasses.dataclass(frozen=True)
class HostAssignment:
    """One host's slot in the pod: its process id and global chip slice."""

    executor_id: int
    process_id: int
    chip_start: int      # first global chip index owned by this host
    num_chips: int

    @property
    def chip_ids(self) -> tuple[int, ...]:
        return tuple(range(self.chip_start, self.chip_start + self.num_chips))


def plan_topology(chip_counts: Sequence[int]) -> list[HostAssignment]:
    """Deterministic global chip numbering from per-host chip counts.

    Called centrally (driver/coordinator) with each registered node's
    ``device_summary()["num_devices"]``, in executor-id order.  No retries,
    no races — the reference's gpu_info randomized-pick loop is replaced by
    one authoritative assignment (SURVEY.md §5.2 disposition).
    """
    out = []
    start = 0
    for i, n in enumerate(chip_counts):
        out.append(HostAssignment(executor_id=i, process_id=i,
                                  chip_start=start, num_chips=int(n)))
        start += int(n)
    return out


def total_chips(assignments: Sequence[HostAssignment]) -> int:
    return sum(a.num_chips for a in assignments)


def default_mesh_axes(n_chips: int, *, model_parallel: int = 1) -> dict:
    """Recommended mesh axis sizes for a chip count: everything on ``dp``
    except an optional ``tp`` factor (must divide the chip count)."""
    if n_chips % model_parallel:
        raise ValueError(f"model_parallel {model_parallel} does not divide "
                         f"chip count {n_chips}")
    return {"dp": n_chips // model_parallel, "tp": model_parallel}


def chip_visibility_env(chip_ids: Sequence[int], *, platform: str = "tpu",
                        simulate_chips: int | None = None,
                        bounds: str | None = None) -> dict[str, str]:
    """Env for a child process that must see only ``chip_ids``.

    On TPU hosts this is the ``CUDA_VISIBLE_DEVICES`` analogue
    (``TPU_VISIBLE_CHIPS`` plus single-process bounds, the libtpu
    convention for carving a host's chips between processes).  With
    ``platform='cpu'`` it returns the virtual-device simulation env used by
    tests and the multi-process local launcher.

    ``bounds`` overrides ``TPU_CHIPS_PER_PROCESS_BOUNDS`` ("x,y,z").  Pass it
    whenever real host topology is known (e.g. derived from discovered device
    coords — v2/v3 hosts are ``2,2,1``); without it the value is a
    *best-effort guess* (square grid, else ``1,n,1``) which libtpu may reject
    or mis-map on hosts whose physical layout differs.
    """
    if platform == "cpu":
        n = simulate_chips if simulate_chips is not None else len(chip_ids)
        return {
            "JAX_PLATFORMS": "cpu",
            # Both spellings: JAX_NUM_CPU_DEVICES is the authoritative config
            # knob (survives plugins that rewrite XLA_FLAGS); the flag form
            # covers older JAX versions that only read XLA_FLAGS.
            "JAX_NUM_CPU_DEVICES": str(max(1, n)),
            "XLA_FLAGS": f"--xla_force_host_platform_device_count={max(1, n)}",
            # Cross-process CPU collectives (the ICI/DCN simulation for
            # multi-process jax.distributed runs): gloo is the only portable
            # in-tree implementation.  Harmless for single-process use.
            "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
        }
    ids = ",".join(str(int(c)) for c in chip_ids)
    n = len(chip_ids)
    if bounds is None:
        side = max(1, int(math.isqrt(n)))
        if side * side != n:
            side = 1  # non-square slice: 1 x n bounds
        bounds = f"{side},{n // side},1"
    return {
        "TPU_VISIBLE_CHIPS": ids,
        "TPU_CHIPS_PER_PROCESS_BOUNDS": bounds,
        "TPU_PROCESS_BOUNDS": "1,1,1",
        "ALLOW_MULTIPLE_LIBTPU_LOAD": "1",
    }


def bounds_from_coords(coords: Sequence[Sequence[int]]) -> str | None:
    """Derive ``TPU_CHIPS_PER_PROCESS_BOUNDS`` from discovered device coords
    (``device_summary()["coords"]``).

    Returns None when coords are unavailable, malformed, or do not form a
    dense axis-aligned box (a non-contiguous chip selection has no valid
    bounds string — the span's volume would disagree with the chip count and
    libtpu would mis-map).
    """
    if not coords:
        return None
    pts = {tuple(int(x) for x in c) for c in coords}
    if len(pts) != len(list(coords)) or any(len(p) != 3 for p in pts):
        return None
    lo = [min(p[i] for p in pts) for i in range(3)]
    hi = [max(p[i] for p in pts) for i in range(3)]
    span = [hi[i] - lo[i] + 1 for i in range(3)]
    if span[0] * span[1] * span[2] != len(pts):
        return None  # holes: the selection is not a dense box
    return ",".join(str(s) for s in span)
