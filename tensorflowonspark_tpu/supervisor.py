"""Supervised worker restart — the elastic half of fault tolerance.

The reference outsourced this to Spark: a dead executor's task was rerun by
the scheduler, and the reservation server simply saw a fresh registration
(PAPER.md §5.3).  With no Spark layer, detection already lives in the driver
(heartbeats → ``CoordinatorServer.dead_nodes`` → the cluster monitor); this
module adds *recovery*: when the monitor declares a data node dead, the
supervisor reaps whatever is left of the process, waits out a bounded
exponential backoff (with jitter, so a correlated failure doesn't respawn a
whole fleet in lockstep), and relaunches the node into the same slot via
``launcher.respawn``.  The replacement re-registers with
``replace_executor_id`` and adopts the slot's bumped *incarnation number* —
the coordinator fences everything the dead predecessor might still send
("TensorFlow: A system for large-scale machine learning" treats checkpoint
restart as the baseline contract; TF-Replicator adds the generation fencing
this implements).

Classification keeps restarts honest:

- a node that *reported a map_fun error* before dying failed on the
  application, not the infrastructure — restarting would just crash-loop the
  same bug, so the death stays fatal;
- a node past ``max_restarts`` is permanently failed: the supervisor records
  a node error (surfacing through the same channel map_fun errors use) and
  signals stop, restoring the non-elastic fail-fast behaviour.

Scope: restartable jobs are the streaming/DIRECT per-host-mesh kind.  A
``jax.distributed`` job cannot readmit a process into a live XLA world —
``cluster.run(elastic=...)`` refuses the combination up front.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_lock
import time

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu.telemetry import trace as ttrace
from tensorflowonspark_tpu.utils.envtune import env_float, env_int
from tensorflowonspark_tpu.utils.net import backoff_delay

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class RestartPolicy:
    """Per-node restart budget + backoff schedule (env-overridable)."""

    max_restarts: int = 2
    backoff_base: float = 0.5
    backoff_factor: float = 2.0
    backoff_max: float = 10.0
    jitter: float = 0.25

    @classmethod
    def from_env(cls) -> "RestartPolicy":
        return cls(
            max_restarts=env_int("TOS_MAX_RESTARTS", 2, minimum=0),
            backoff_base=env_float("TOS_RESTART_BACKOFF_BASE", 0.5),
            backoff_factor=env_float("TOS_RESTART_BACKOFF_FACTOR", 2.0),
            backoff_max=env_float("TOS_RESTART_BACKOFF_MAX", 10.0),
        )

    def delay(self, attempt: int) -> float:
        """Backoff before restart ``attempt`` (0-based), jittered ±jitter."""
        return backoff_delay(attempt, self.backoff_base, self.backoff_factor,
                             self.backoff_max, self.jitter)


class CoordinatorSupervisor:
    """Supervised restart of the control-plane server ITSELF (ISSUE 13).

    The coordinator was the last unsupervised failure domain: node death,
    severed sockets, and mid-drain kills all recover, but a coordinator
    crash used to kill the run.  With the write-ahead journal
    (``journal.py``) the server can be rebuilt from disk; this class reuses
    the node supervisor's budgeted-backoff machinery (same
    :class:`RestartPolicy` / ``TOS_MAX_RESTARTS`` / ``TOS_RESTART_BACKOFF_*``
    knobs) to drive ``CoordinatorServer.restore()`` after a ``crash()``:
    wait out a jittered backoff, replay the journal, resume under a bumped
    coordinator epoch.  Budget exhausted (or restore itself raising past
    the budget) fails the run through the node-error channel — the
    non-supervised fail-fast behaviour, delayed by the budget, not removed.
    """

    def __init__(self, server, policy: RestartPolicy | None = None):
        self.server = server
        self.policy = policy or RestartPolicy.from_env()
        self._lock = tos_named_lock("supervisor.coord._lock")
        self._stopped = threading.Event()
        self._restarts = 0
        self._permanent: str | None = None
        self._inflight = False
        self._threads: list[threading.Thread] = []
        server.add_crash_listener(self._on_crash)

    def restart_count(self) -> int:
        with self._lock:
            return self._restarts

    def permanently_failed(self) -> str | None:
        with self._lock:
            return self._permanent

    def _on_crash(self) -> None:
        if self._stopped.is_set():
            return
        with self._lock:
            if self._inflight or self._permanent is not None:
                return
            self._inflight = True
            t = threading.Thread(target=self._recover, daemon=True,
                                 name="coordinator-supervisor")
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
        t.start()

    def _recover(self) -> None:
        try:
            while True:
                with self._lock:
                    attempt = self._restarts
                if attempt >= self.policy.max_restarts:
                    self._fail_permanently(
                        f"coordinator exhausted its restart budget "
                        f"({self.policy.max_restarts} restart(s)); giving up")
                    return
                delay = self.policy.delay(attempt)
                logger.warning("restarting coordinator in %.2fs "
                               "(attempt %d/%d)", delay, attempt + 1,
                               self.policy.max_restarts)
                if self._stopped.wait(delay):
                    return
                with self._lock:
                    self._restarts = attempt + 1
                try:
                    self.server.restore()
                    return
                except Exception:
                    logger.exception("coordinator restore failed; spending "
                                     "another budget unit")
        finally:
            with self._lock:
                self._inflight = False

    def _fail_permanently(self, reason: str) -> None:
        telemetry.counter("coordinator.permanent_failures").inc()
        ttrace.event("permanent_failure", executor=-1, reason=reason[:200])
        logger.error("control plane permanently failed: %s", reason)
        # surface through the node-error channel (executor -1 = the control
        # plane itself) so shutdown()'s error propagation raises it; the
        # _permanent flag is set LAST — it is the observable "verdict is in"
        # signal, and a watcher acting on it must find the error recorded
        self.server.record_failure(
            -1, f"control plane permanently failed: {reason}")
        self.server.signal_stop()
        with self._lock:
            self._permanent = reason

    def stop(self, timeout: float = 10.0) -> None:
        """No coordinator restarts past this point (shutdown owns teardown)."""
        self._stopped.set()
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout)


class Supervisor:
    """Watches launcher children and restarts failed nodes under a policy."""

    def __init__(self, coordinator, launcher, policy: RestartPolicy | None = None):
        self.coordinator = coordinator
        self.launcher = launcher
        self.policy = policy or RestartPolicy.from_env()
        # How long a respawned replacement gets to re-register before the
        # supervisor treats its boot as another death (the monitor can only
        # re-detect nodes that made it into liveness tracking).
        self._reregister_timeout = env_float("TOS_REREGISTER_TIMEOUT", 60.0)
        self._lock = tos_named_lock("supervisor._lock")
        self._stopped = threading.Event()
        self._restarts: dict[int, int] = {}
        self._permanent: dict[int, str] = {}
        self._inflight: set[int] = set()
        # Slots being retired ON PURPOSE (cluster.resize scale-in): their
        # death — clean exit, or a kill mid-drain — is classified as
        # retirement, never recovery: no respawn, no restart-budget charge,
        # no elastic.restarts_total increment.
        self._retired: set[int] = set()
        # Slots EVICTED from a collective group at quorum (gray failure):
        # the process is alive, benched in probation — respawning a
        # replacement into the slot would split-brain it, so recovery is
        # declined until the coordinator readmits (unpark) or the slot is
        # definitively dead after probation.
        self._parked: set[int] = set()
        self._threads: list[threading.Thread] = []

    # -- status (consumed by the partition ledger's recovery waits) ----------

    def permanently_failed(self, executor_id: int) -> str | None:
        """The recorded reason when the slot is beyond recovery, else None."""
        with self._lock:
            return self._permanent.get(executor_id)

    def retire(self, executor_id: int) -> None:
        """Mark the slot's upcoming death INTENTIONAL (scale-in drain has
        begun): ``handle_death`` will decline to recover it.  Distinct from
        a permanent failure — retirement records no node error and signals
        no stop; the cluster simply got smaller on purpose."""
        with self._lock:
            self._retired.add(executor_id)
        telemetry.counter("elastic.retirements_total").inc()

    def retired(self, executor_id: int) -> bool:
        with self._lock:
            return executor_id in self._retired

    def park(self, executor_id: int) -> None:
        """Collective eviction (gray failure): bench the slot.  Its process
        is ALIVE — slow or wedged, not dead — so ``handle_death`` declines
        to respawn while parked: a replacement would split-brain the slot
        against the still-running original.  The coordinator's readmission
        (probation health probe passed) unparks it."""
        with self._lock:
            if executor_id in self._parked:
                return
            self._parked.add(executor_id)
        telemetry.counter("elastic.parked_total").inc()
        logger.warning("executor %d parked in probation (collective "
                       "eviction); supervised restart declined while its "
                       "process is alive", executor_id)

    def unpark(self, executor_id: int) -> None:
        """The evicted process passed its probation health probe and was
        readmitted — normal death recovery applies again."""
        with self._lock:
            if executor_id not in self._parked:
                return
            self._parked.discard(executor_id)
        logger.info("executor %d unparked (readmitted after probation)",
                    executor_id)

    def parked(self, executor_id: int) -> bool:
        with self._lock:
            return executor_id in self._parked

    def restart_count(self, executor_id: int) -> int:
        with self._lock:
            return self._restarts.get(executor_id, 0)

    def restarting(self, executor_id: int) -> bool:
        with self._lock:
            return executor_id in self._inflight

    # -- lifecycle -----------------------------------------------------------

    def handle_death(self, executor_id: int) -> None:
        """Non-blocking: schedule recovery of a node the monitor just
        declared dead (its incarnation is already fenced).  Backoff and
        respawn run on their own thread so one slot's restart window never
        delays detection or recovery of its peers."""
        if self._stopped.is_set():
            return
        with self._lock:
            if executor_id in self._retired:
                # intentional retirement (scale-in): the death IS the plan —
                # no respawn, no budget charge, no restart counted
                logger.info("executor %d died while retiring; not recovering "
                            "(intentional scale-in)", executor_id)
                return
            if executor_id in self._parked:
                # evicted to probation: the original process is (or was
                # moments ago) alive — respawning would split-brain the
                # slot; readmission or an explicit unpark re-enables
                # recovery
                logger.warning("executor %d declared dead while parked in "
                               "probation; not respawning (eviction parks, "
                               "it never refills the slot)", executor_id)
                return
            if executor_id in self._inflight or executor_id in self._permanent:
                return
            self._inflight.add(executor_id)
            self._threads = [t for t in self._threads if t.is_alive()]
            t = threading.Thread(target=self._restart, args=(executor_id,),
                                 daemon=True, name=f"supervisor-restart-{executor_id}")
            self._threads.append(t)
        t.start()

    def stop(self, timeout: float = 10.0) -> None:
        """No restarts past this point (shutdown owns escalation now)."""
        self._stopped.set()
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout)

    # -- the restart path ----------------------------------------------------

    def _fail_permanently(self, executor_id: int, reason: str) -> None:
        with self._lock:
            self._permanent[executor_id] = reason
        telemetry.counter("elastic.permanent_failures").inc()
        ttrace.event("permanent_failure", executor=executor_id,
                     reason=reason[:200])
        logger.error("executor %d permanently failed: %s", executor_id, reason)
        # Surface through the node-error channel and fail fast, exactly like
        # the non-elastic path would have on first death.
        self.coordinator.record_failure(executor_id, reason)
        self.coordinator.signal_stop()

    def _classify(self, executor_id: int, attempt: int) -> str | None:
        """Reason this death is NOT restartable, else None."""
        if attempt >= self.policy.max_restarts:
            return (f"node {executor_id} exhausted its restart budget "
                    f"({self.policy.max_restarts} restart(s)); giving up")
        if any(e.get("executor_id") == executor_id for e in self.coordinator.errors()):
            return (f"node {executor_id} reported a map_fun error before dying; "
                    "an application failure is not restartable")
        return None

    def _await_reregister(self, executor_id: int) -> bool:
        """True once the replacement is liveness-tracked (it re-registered);
        False when the re-register window expires or the supervisor stops."""
        deadline = time.monotonic() + self._reregister_timeout
        while time.monotonic() < deadline and not self._stopped.is_set():
            _, tracked = self.coordinator.registered_incarnation(executor_id)
            if tracked:
                return True
            time.sleep(0.25)
        return False

    def _restart(self, executor_id: int) -> None:
        try:
            # Loop rather than fire-and-forget: a replacement that dies
            # DURING BOOT (before registering) never enters liveness
            # tracking, so the monitor cannot re-detect it — the supervisor
            # itself must notice and spend the remaining budget on it.
            while True:
                if self.retired(executor_id):
                    # a resize retired this slot while recovery was pending:
                    # the restart is no longer wanted
                    return
                attempt = self.restart_count(executor_id)
                reason = self._classify(executor_id, attempt)
                if reason is not None:
                    self._fail_permanently(executor_id, reason)
                    return
                delay = self.policy.delay(attempt)
                logger.warning("restarting executor %d in %.2fs (attempt %d/%d)",
                               executor_id, delay, attempt + 1, self.policy.max_restarts)
                if self._stopped.wait(delay):
                    return
                meta = self.coordinator.node_meta(executor_id)
                launch_index = (meta or {}).get("launch_index", -1)
                if not 0 <= launch_index < len(self.launcher.processes):
                    self._fail_permanently(
                        executor_id,
                        f"node {executor_id} has no launch_index mapping; cannot respawn")
                    return
                config = dataclasses.replace(self.launcher.configs[launch_index],
                                             replace_executor_id=executor_id)
                # Last look before reaping: a replacement that booted slower
                # than the re-register window (cold jax/TPU init) may have
                # registered DURING the backoff we just waited out — killing
                # it now would burn the budget on a recovered slot (and its
                # stale liveness entry would make the next replacement's
                # register(replace=...) be refused as still-tracked).  A
                # registration landing in the microseconds between this check
                # and respawn() is still reaped — that residual race is not
                # closed, only narrowed: the reaped slot goes heartbeat-silent,
                # the monitor re-declares the death, and recovery re-enters
                # here at the cost of one extra budget unit.
                _, tracked = self.coordinator.registered_incarnation(executor_id)
                if tracked:
                    logger.info("executor %d re-registered late; restart "
                                "attempt %d not needed", executor_id, attempt + 1)
                    return
                with self._lock:
                    if self._stopped.is_set():
                        return
                    self._restarts[executor_id] = attempt + 1
                # respawn reaps the predecessor first: a fenced-but-alive
                # zombie (network partition, dropped heartbeats) must release
                # the slot's ports/devices before its replacement takes them.
                self.launcher.respawn(launch_index, config)
                telemetry.counter("elastic.restarts_total").inc()
                ttrace.event("restart", executor=executor_id,
                             attempt=attempt + 1)
                logger.info("executor %d respawned (launch_index %d, restart %d)",
                            executor_id, launch_index, attempt + 1)
                if self._await_reregister(executor_id):
                    return
                if self._stopped.is_set():
                    return
                logger.warning(
                    "replacement for executor %d died before re-registering "
                    "(%.0fs window); treating as another death",
                    executor_id, self._reregister_timeout)
        except Exception:
            logger.exception("supervised restart of executor %d failed", executor_id)
            self._fail_permanently(
                executor_id, f"supervised restart of node {executor_id} raised; see driver log")
        finally:
            with self._lock:
                self._inflight.discard(executor_id)
