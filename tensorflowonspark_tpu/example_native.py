"""ctypes bindings for the native Example parser (``native/example_parser.cc``).

The data-loader hot path: per-record ``tf.train.Example`` decoding done in
C++ over a whole shard at once — Python makes TWO ctypes calls per
(shard, column) instead of walking proto bytes per record (the reference's
equivalent work lived in the native tensorflow-hadoop/TF runtime).

Importing this module raises if the library cannot be built/loaded; callers
(``dfutil.read_shard_columns``) treat that as "fall back to pure Python".
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from tensorflowonspark_tpu.native.build import build_native_lib

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native",
                    "example_parser.cc")

_lib = ctypes.CDLL(build_native_lib(_SRC, "libexample_parser.so"))

_U64P = ctypes.POINTER(ctypes.c_uint64)
_lib.tos_count_feature.restype = ctypes.c_int64
_lib.tos_count_feature.argtypes = [
    ctypes.c_char_p, _U64P, _U64P, ctypes.c_int64,
    ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
    ctypes.POINTER(ctypes.c_int), _U64P,
]
_lib.tos_fill_feature.restype = ctypes.c_int64
_lib.tos_fill_feature.argtypes = [
    ctypes.c_char_p, _U64P, _U64P, ctypes.c_int64,
    ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
    ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
    _U64P, _U64P,
]

KINDS = {"bytes": 1, "float": 2, "int64": 3}


def _u64(a: np.ndarray):
    return a.ctypes.data_as(_U64P)


def span_arrays(spans: list[tuple[int, int]]) -> tuple[np.ndarray, np.ndarray]:
    """(offsets, lengths) uint64 arrays for a span list — build ONCE per
    shard and reuse across every extract_column call (the conversion is an
    O(n_records) Python walk that must not repeat per column)."""
    n = len(spans)
    offs = np.fromiter((o for o, _ in spans), np.uint64, count=n)
    lens = np.fromiter((l for _, l in spans), np.uint64, count=n)
    return offs, lens


def extract_column(buf: bytes, spans, name: str, dtype: str):
    """Extract one feature column across all records of a shard buffer.

    ``spans`` is either the (offset, length) list from ``tfrecord`` scanning
    or a prebuilt ``span_arrays`` result.  Returns ``(values, counts)``:
    ``counts`` is the per-record value count (uint64, 0 where the feature is
    absent) and ``values`` is a ``float32``/``int64`` ndarray of all values
    concatenated, or for ``dtype='bytes'`` a list of ``bytes`` (sliced from
    ``buf``).
    """
    kind = KINDS[dtype]
    if isinstance(spans, tuple) and len(spans) == 2 \
            and isinstance(spans[0], np.ndarray):
        offs, lens = spans
    else:
        offs, lens = span_arrays(spans)
    n = len(offs)
    counts = np.zeros(n, np.uint64)
    found = ctypes.c_int(0)
    bname = name.encode("utf-8")
    total = _lib.tos_count_feature(buf, _u64(offs), _u64(lens), n, bname,
                                   len(bname), kind, ctypes.byref(found),
                                   _u64(counts))
    if total == -2:
        raise TypeError(f"feature {name!r} is not of dtype {dtype!r}")
    if total < 0:
        raise ValueError(f"corrupt Example record while reading {name!r}")

    f32 = np.empty(total if kind == 2 else 0, np.float32)
    i64 = np.empty(total if kind == 3 else 0, np.int64)
    boffs = np.empty(total if kind == 1 else 0, np.uint64)
    blens = np.empty(total if kind == 1 else 0, np.uint64)
    wrote = _lib.tos_fill_feature(
        buf, _u64(offs), _u64(lens), n, bname, len(bname), kind,
        f32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        i64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        _u64(boffs), _u64(blens))
    if wrote != total:
        raise ValueError(f"corrupt Example record while reading {name!r}")
    if kind == 1:
        values = [bytes(buf[int(o):int(o) + int(l)])
                  for o, l in zip(boffs, blens)]
    elif kind == 2:
        values = f32
    else:
        values = i64
    return values, counts
