"""Checkpointing + model export — the SavedModel/HopsFS path plumbing.

Reference behaviour (SURVEY.md §5.4): checkpointing is delegated to TF +
HDFS/HopsFS; TFoS contributes path resolution (``TFNode.hdfs_path``) and a
SavedModel export used by the inference side (``TFNode.export_saved_model``
``TFNode.py:~160-230``; ``pipeline.TFModel`` loads it).

TPU-native: Orbax for sharded/async checkpoints of pytrees, plus a
"bundle" export format for inference — a directory holding the params
checkpoint and a JSON model config, the pytree+apply-fn analogue of a
SavedModel.  ``hdfs://``/``hopsfs://`` URIs resolve through
``utils.paths.register_fs_root`` ("HopsFS checkpointing stays unchanged",
BASELINE.json:5).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable

from tensorflowonspark_tpu.utils.paths import resolve_uri


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(path: str, tree: Any, force: bool = True) -> str:
    """Save a pytree checkpoint to a (possibly hdfs://-mapped) path."""
    local = os.path.abspath(resolve_uri(path))
    _checkpointer().save(local, tree, force=force)
    return local


def restore_checkpoint(path: str, target: Any | None = None) -> Any:
    """Restore a pytree; ``target`` (a matching pytree) restores dtypes/shapes
    and device placement exactly."""
    local = os.path.abspath(resolve_uri(path))
    import orbax.checkpoint as ocp

    if target is not None:
        restore_args = ocp.checkpoint_utils.construct_restore_args(target)
        return _checkpointer().restore(local, restore_args=restore_args)
    return _checkpointer().restore(local)


def _step_dirs(model_dir: str) -> list[tuple[int, str]]:
    """Sorted (step, uri_path) pairs for ``step_N`` dirs under model_dir."""
    local = resolve_uri(model_dir)
    if not os.path.isdir(local):
        return []
    steps = sorted(
        int(n[5:]) for n in os.listdir(local) if n.startswith("step_") and n[5:].isdigit()
    )
    return [(s, os.path.join(model_dir, f"step_{s}")) for s in steps]


def latest_step_dir(model_dir: str) -> str | None:
    """Find the latest ``step_N`` checkpoint under ``model_dir``."""
    dirs = _step_dirs(model_dir)
    return dirs[-1][1] if dirs else None


class CheckpointManager:
    """Step-indexed checkpoints under one model_dir (keeps the newest K)."""

    def __init__(self, model_dir: str, max_to_keep: int = 3):
        self.model_dir = model_dir
        self.max_to_keep = max_to_keep
        os.makedirs(resolve_uri(model_dir), exist_ok=True)

    def save(self, step: int, tree: Any) -> str:
        path = os.path.join(self.model_dir, f"step_{int(step)}")
        save_checkpoint(path, tree)
        self._gc()
        return path

    def restore_latest(self, target: Any | None = None) -> tuple[Any, int] | None:
        dirs = _step_dirs(self.model_dir)
        if not dirs:
            return None
        step, path = dirs[-1]
        return restore_checkpoint(path, target), step

    def _gc(self) -> None:
        import shutil

        for _, path in _step_dirs(self.model_dir)[: -self.max_to_keep]:
            shutil.rmtree(resolve_uri(path), ignore_errors=True)


# -- inference bundles (SavedModel analogue) ---------------------------------

def export_bundle(export_dir: str, params: Any, model_config: dict) -> str:
    """Export params + config for serving (reference ``export_saved_model``).

    ``model_config`` must contain everything needed to rebuild the apply fn
    (e.g. ``{"model": "mnist_cnn", "num_classes": 10}``); the model registry
    in ``models/`` resolves it at load time.
    """
    local = resolve_uri(export_dir)
    os.makedirs(local, exist_ok=True)
    save_checkpoint(os.path.join(export_dir, "params"), params)
    with open(os.path.join(local, "bundle.json"), "w") as f:
        json.dump(model_config, f, indent=2, sort_keys=True)
    return local


def load_bundle(export_dir: str) -> tuple[Any, dict]:
    """Load an exported bundle -> (params, model_config)."""
    local = resolve_uri(export_dir)
    with open(os.path.join(local, "bundle.json")) as f:
        config = json.load(f)
    params = restore_checkpoint(os.path.join(export_dir, "params"))
    return params, config


_BUNDLE_CACHE: dict[str, tuple[Any, dict, Callable]] = {}


def load_bundle_cached(export_dir: str, build_apply: Callable[[dict], Callable]) -> tuple[Any, dict, Callable]:
    """Per-process cached bundle load (reference ``pipeline._run_model``'s
    per-executor singleton SavedModel load, ``pipeline.py:~600-700``)."""
    key = os.path.abspath(resolve_uri(export_dir))
    if key not in _BUNDLE_CACHE:
        params, config = load_bundle(export_dir)
        _BUNDLE_CACHE[key] = (params, config, build_apply(config))
    return _BUNDLE_CACHE[key]
