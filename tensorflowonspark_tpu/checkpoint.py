"""Checkpointing + model export — the SavedModel/HopsFS path plumbing.

Reference behaviour (SURVEY.md §5.4): checkpointing is delegated to TF +
HDFS/HopsFS; TFoS contributes path resolution (``TFNode.hdfs_path``) and a
SavedModel export used by the inference side (``TFNode.export_saved_model``
``TFNode.py:~160-230``; ``pipeline.TFModel`` loads it).

TPU-native: Orbax for sharded/async checkpoints of pytrees, plus a
"bundle" export format for inference — a directory holding the params
checkpoint and a JSON model config, the pytree+apply-fn analogue of a
SavedModel.  ``hdfs://``/``hopsfs://`` URIs resolve through
``utils.paths.register_fs_root`` ("HopsFS checkpointing stays unchanged",
BASELINE.json:5).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_lock
from typing import Any, Callable

from tensorflowonspark_tpu.utils.paths import resolve_uri

logger = logging.getLogger(__name__)


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


_ASYNC_CKPTR = None


def _async_checkpointer():
    """Process-wide async checkpointer (orbax serializes to a background
    thread pool; the train loop keeps stepping while bytes hit disk —
    SURVEY.md §5.4 'sharded, async')."""
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        import orbax.checkpoint as ocp

        _ASYNC_CKPTR = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    return _ASYNC_CKPTR


def wait_for_saves() -> None:
    """Block until every in-flight async checkpoint save has committed."""
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()


def save_checkpoint(path: str, tree: Any, force: bool = True,
                    async_save: bool = False) -> str:
    """Save a pytree checkpoint to a (possibly hdfs://-mapped) path.

    ``async_save=True`` returns as soon as the tree is snapshotted to host
    memory; call ``wait_for_saves()`` (or ``CheckpointManager.wait()``)
    before reading the checkpoint back or exiting the process.
    """
    local = os.path.abspath(resolve_uri(path))
    ckptr = _async_checkpointer() if async_save else _checkpointer()
    ckptr.save(local, tree, force=force)
    return local


def restore_checkpoint(path: str, target: Any | None = None) -> Any:
    """Restore a pytree; ``target`` (a matching pytree) recovers the exact
    container structure (NamedTuples, tuples) that serialization flattened.

    Orbax canonicalizes tuples/NamedTuples (optax states are full of them) to
    lists on disk, so the raw restore comes back list-shaped; re-flattening
    into the target's treedef restores the real types.  Leaf order is stable
    under that canonicalization (both sides sort dict keys), and a count or
    shape mismatch means the checkpoint doesn't belong to this model — fail
    loudly rather than load garbage.
    """
    local = os.path.abspath(resolve_uri(path))
    import jax
    import numpy as np
    import orbax.checkpoint as ocp

    ckptr = _checkpointer()
    # Restore to HOST numpy explicitly: the default path rebuilds the
    # save-time shardings, which fails whenever the restoring process has a
    # different topology than the saver — e.g. the driver reading a
    # checkpoint written collectively by a 2-process jax.distributed mesh,
    # or a TPU checkpoint opened on CPU.  Callers re-place the tree on
    # their own mesh (dp.replicate / mesh.shard_tree) anyway.
    # orbax >= 0.9 wraps the saved tree's metadata (.item_metadata.tree);
    # 0.7.x returns the metadata tree directly — accept both.
    meta = ckptr.metadata(local)
    item = getattr(meta, "item_metadata", None)
    if item is not None:
        meta = item.tree
    restore_args = jax.tree.map(
        lambda _: ocp.RestoreArgs(restore_type=np.ndarray), meta,
        is_leaf=lambda x: not isinstance(x, (dict, list, tuple)))
    raw = ckptr.restore(local, restore_args=restore_args)
    if target is None:
        return raw

    leaves = jax.tree.leaves(raw)
    treedef = jax.tree.structure(target)
    if treedef.num_leaves != len(leaves):
        raise ValueError(
            f"checkpoint at {path} has {len(leaves)} leaves but the restore "
            f"target expects {treedef.num_leaves} — wrong model/optimizer?")
    for got, want in zip(leaves, jax.tree.leaves(target)):
        gs = getattr(got, "shape", ())
        ws = getattr(want, "shape", ())
        if tuple(gs) != tuple(ws):
            raise ValueError(
                f"checkpoint leaf shape {tuple(gs)} != target shape {tuple(ws)} "
                f"at {path}")
    return jax.tree.unflatten(treedef, leaves)


def _step_dirs(model_dir: str) -> list[tuple[int, str]]:
    """Sorted (step, uri_path) pairs for ``step_N`` dirs under model_dir."""
    local = resolve_uri(model_dir)
    if not os.path.isdir(local):
        return []
    steps = sorted(
        int(n[5:]) for n in os.listdir(local) if n.startswith("step_") and n[5:].isdigit()
    )
    return [(s, os.path.join(model_dir, f"step_{s}")) for s in steps]


def latest_step_dir(model_dir: str) -> str | None:
    """Find the latest ``step_N`` checkpoint under ``model_dir``."""
    dirs = _step_dirs(model_dir)
    return dirs[-1][1] if dirs else None


class CheckpointManager:
    """Step-indexed checkpoints under one model_dir (keeps the newest K).

    Saves are **async by default**: the device→host snapshot happens before
    ``save`` returns (so the train loop may donate/overwrite its state), and
    serialization overlaps subsequent steps.  Orbax commits atomically
    (write-to-tmp + rename), so a crash mid-save never leaves a readable
    partial ``step_N`` directory and ``restore_latest`` only ever sees
    complete checkpoints.
    """

    def __init__(self, model_dir: str, max_to_keep: int = 3, async_save: bool = True):
        self.model_dir = model_dir
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        os.makedirs(resolve_uri(model_dir), exist_ok=True)

    def save(self, step: int, tree: Any) -> str:
        path = os.path.join(self.model_dir, f"step_{int(step)}")
        save_checkpoint(path, tree, async_save=self.async_save)
        self._gc(pending_step=int(step))
        return path

    def wait(self) -> None:
        """Block until in-flight async saves are committed."""
        wait_for_saves()

    def restore_latest(self, target: Any | None = None) -> tuple[Any, int] | None:
        wait_for_saves()  # an in-flight save may be the latest step
        dirs = _step_dirs(self.model_dir)
        if not dirs:
            return None
        step, path = dirs[-1]
        return restore_checkpoint(path, target), step

    def _gc(self, pending_step: int | None = None) -> None:
        import shutil
        import sys

        # Single-writer deletion: in the collective-save regime every data
        # node calls save() and would rmtree the same step dirs on shared
        # storage concurrently — a half-deleted dir can transiently look
        # like the newest committed checkpoint to a concurrent reader
        # (restore_latest / the evaluator).  All processes compute the same
        # keep-K set, so only process 0 deletes.
        jax = sys.modules.get("jax")
        if jax is not None and jax.process_count() > 1 and jax.process_index() != 0:
            return
        # Only committed dirs appear in _step_dirs; an async save still in
        # flight is invisible, so count it explicitly (``pending_step``) or
        # the keep-K window would run one checkpoint too large.
        dirs = _step_dirs(self.model_dir)
        pending = 1 if (pending_step is not None
                        and pending_step not in [s for s, _ in dirs]) else 0
        excess = len(dirs) + pending - self.max_to_keep
        for _, path in dirs[: max(0, excess)]:
            shutil.rmtree(resolve_uri(path), ignore_errors=True)


def restore_for_restart(ctx, manager: CheckpointManager,
                        target: Any | None = None) -> tuple[Any, int] | None:
    """Elastic-recovery resume: load the newest committed checkpoint before
    (re-)entering the feed loop.

    Call this at the top of a restartable map_fun.  On a first launch with an
    empty model_dir it returns None (train from init); on a supervised
    restart (``ctx.is_restart``) — or a rerun over a warm model_dir — it
    returns ``(tree, step)`` from the latest ``step_N`` so the replacement
    continues instead of repeating finished work.  The checkpoint-restart
    contract of "TensorFlow: A system for large-scale machine learning"
    (PAPERS.md); orbax's atomic commit guarantees the result is never a
    torn mid-save state.
    """
    out = manager.restore_latest(target)
    if out is None:
        if ctx.is_restart:
            logger.warning(
                "node %d restarted (incarnation %d) but %s holds no committed "
                "checkpoint; restarting the work from scratch",
                ctx.executor_id, ctx.incarnation, manager.model_dir)
        return None
    _, step = out
    logger.info("node %d (incarnation %d) resuming from step %d of %s",
                ctx.executor_id, ctx.incarnation, step, manager.model_dir)
    return out


def chief_save(ctx, manager: CheckpointManager, step: int, tree: Any,
               timeout: float = 600.0) -> None:
    """Multi-host save coordination.

    Two regimes, selected automatically:

    - **host-local state** (each process holds full values — pure DP
      replication, or independent single-process meshes): the chief writes,
      everyone barriers.  N hosts writing the same bytes would race on the
      commit rename — reference's equivalent hazard: every Spark executor
      writing the same HDFS SavedModel path.
    - **multi-process global arrays** (``jax.distributed`` mesh spanning
      hosts, e.g. FSDP/tp-sharded state): the save itself is a collective —
      EVERY data node calls it; orbax serializes each process's addressable
      shards and commits atomically on the primary.  A chief-only save
      would be unable to fetch remote shards.

    Either way the barrier releases only after the save has *committed*, so
    a host that crashes right after this call can still restart from the
    step just written.
    """
    import jax

    # Under jax.distributed ANY orbax save is a collective: orbax runs
    # sync_global_processes over the whole jax process group internally, so
    # a chief-only save would deadlock even for host-local numpy trees.
    # (The evaluator is not in the jax process group — node.py initializes
    # data nodes only — so "all jax processes" == "all data nodes" here.)
    collective = jax.process_count() > 1
    if collective or ctx.executor_id == 0:
        manager.save(step, tree)
        manager.wait()
    # Data-node scope: the evaluator role never trains and never calls this,
    # so an all-nodes barrier would deadlock any cluster running one.
    ctx.barrier("checkpoint", timeout=timeout, group="data")


# -- inference bundles (SavedModel analogue) ---------------------------------

def _flatten_tree(tree: Any, prefix: str = "") -> dict:
    """Nested dict-of-arrays -> flat {'a/b/c': array} (bundle npz keys)."""
    flat = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            key = f"{prefix}/{k}" if prefix else str(k)
            flat.update(_flatten_tree(v, key))
    else:
        flat[prefix] = tree
    return flat


def _unflatten_tree(flat: dict) -> Any:
    tree: dict = {}
    for key, value in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return tree


def export_bundle(export_dir: str, params: Any, model_config: dict) -> str:
    """Export params + config for serving (reference ``export_saved_model``).

    ``model_config`` must contain everything needed to rebuild the apply fn
    (e.g. ``{"model": "mnist_cnn", "num_classes": 10}``); the model registry
    in ``models/`` resolves it at load time.

    Params ride in a single ``params.npz`` (atomic rename commit), NOT an
    orbax checkpoint: inference nodes then never import orbax, whose import
    alone costs ~7s of CPU — a real tax when a cluster spawns a scoring
    process per executor (train-state checkpoints keep orbax: they are
    sharded, async, and large; bundles are small flat trees).

    Cross-process-sharded leaves (multi-host FSDP/tp params, not fetchable
    via ``np.asarray``) fall back to the orbax layout, which serializes
    sharded jax.Arrays natively; ``load_bundle`` reads either layout.
    """
    import numpy as np

    local = resolve_uri(export_dir)
    os.makedirs(local, exist_ok=True)
    flat_leaves = _flatten_tree(params)
    if any(not getattr(v, "is_fully_addressable", True)
           for v in flat_leaves.values()):
        save_checkpoint(os.path.join(export_dir, "params"), params)
        # A re-export over a directory that previously held an npz bundle
        # must not leave the stale npz behind — load_bundle prefers it.
        # Every process runs this branch (the sharded save is a collective);
        # on shared storage only one unlink wins, the rest must not crash.
        import contextlib

        with contextlib.suppress(FileNotFoundError):
            os.remove(os.path.join(local, "params.npz"))
        with open(os.path.join(local, "bundle.json"), "w") as f:
            json.dump(model_config, f, indent=2, sort_keys=True)
        return local
    flat = {k: np.asarray(v) for k, v in flat_leaves.items()}
    # npz writes ml_dtypes arrays (bfloat16/float8 — numpy kind 'V') as raw
    # void bytes and np.load hands back unusable '|V2' arrays; record their
    # dtype names so load_bundle can .view() the bytes back.  Keys ride in
    # bundle.json under a reserved field (the npz itself stays pure arrays).
    extended = {k: a.dtype.name for k, a in flat.items() if a.dtype.kind == "V"}
    tmp = os.path.join(local, "params.npz.tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **flat)
    os.replace(tmp, os.path.join(local, "params.npz"))
    with open(os.path.join(local, "bundle.json"), "w") as f:
        json.dump({**model_config, "_param_dtypes": extended} if extended
                  else model_config, f, indent=2, sort_keys=True)
    return local


def load_bundle(export_dir: str) -> tuple[Any, dict]:
    """Load an exported bundle -> (params, model_config)."""
    import numpy as np

    local = resolve_uri(export_dir)
    with open(os.path.join(local, "bundle.json")) as f:
        config = json.load(f)
    extended = config.pop("_param_dtypes", {})
    npz = os.path.join(local, "params.npz")
    if os.path.exists(npz):
        with np.load(npz) as data:
            flat = {k: data[k] for k in data.files}
        if extended:
            import ml_dtypes

            flat = {k: (v.view(np.dtype(getattr(ml_dtypes, extended[k])))
                        if k in extended else v)
                    for k, v in flat.items()}
        params = _unflatten_tree(flat)
    else:  # bundles written before the npz format: orbax layout
        params = restore_checkpoint(os.path.join(export_dir, "params"))
    return params, config


def bundle_signature(export_dir: str) -> tuple:
    """Cheap change signature of an exported bundle: (name, mtime_ns, size)
    per bundle file.  ``export_bundle`` commits params.npz by atomic rename,
    so a changed signature is a COMPLETE newer export, never a torn one.
    The gateway's version watcher polls this to detect new exports, and the
    rollout/promotion path compares each replica's reload-ack signature
    against it to prove the whole fleet converged on one bundle (a replica
    acking a different signature is flight-recorded as a laggard)."""
    local = resolve_uri(export_dir)
    sig = []
    for name in ("bundle.json", "params.npz", "params"):
        try:
            st = os.stat(os.path.join(local, name))
        except OSError:
            continue
        sig.append((name, st.st_mtime_ns, st.st_size))
    return tuple(sig)


def export_stablehlo(export_dir: str, params: Any, model_config: dict,
                     input_shape: tuple, input_dtype: Any = None,
                     batch_polymorphic: bool = True,
                     platforms: tuple = ("cpu", "tpu")) -> str:
    """Serving interop: export a self-contained StableHLO artifact.

    The reference's SavedModel was consumable by anything speaking TF serving
    (``TFNode.py:~160-230``); the bundle format is registry-bound to this
    repo.  This writes ``model.stablehlo`` — the jitted apply fn with the
    params **baked in as constants**, serialized via ``jax.export`` — so a
    consumer needs only ``jax`` (any version with the same serialization
    era), no model registry, no flax, no this-package:

        exp = jax.export.deserialize(open("model.stablehlo", "rb").read())
        logits = exp.call(images)

    ``input_shape`` excludes the batch dim when ``batch_polymorphic`` (the
    default): the artifact then scores any batch size via a symbolic
    dimension.  ``platforms`` bakes in the lowerings to ship (cpu + tpu by
    default, so the same artifact serves on either).
    """
    import jax
    import jax.numpy as jnp

    from jax import export as jexport
    from tensorflowonspark_tpu.models.registry import build_apply

    apply_fn = build_apply(model_config)
    dtype = input_dtype or jnp.float32
    device_params = jax.tree.map(jnp.asarray, params)

    if batch_polymorphic:
        (b,) = jexport.symbolic_shape("b")
        spec = jax.ShapeDtypeStruct((b, *input_shape), dtype)
    else:
        spec = jax.ShapeDtypeStruct(tuple(input_shape), dtype)
    exp = jexport.export(
        jax.jit(lambda x: apply_fn(device_params, x)),
        platforms=list(platforms))(spec)

    local = resolve_uri(export_dir)
    os.makedirs(local, exist_ok=True)
    with open(os.path.join(local, "model.stablehlo"), "wb") as f:
        f.write(exp.serialize())
    meta = {"model_config": model_config, "platforms": list(platforms),
            "input_shape": list(input_shape),
            "batch_polymorphic": batch_polymorphic}
    with open(os.path.join(local, "stablehlo.json"), "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
    return local


_BUNDLE_CACHE: dict[str, tuple[Any, dict, Callable]] = {}
_BUNDLE_LOCK = tos_named_lock("checkpoint._bundle_lock")
# single-flight per export_dir: the loader-elect's event, waited on by every
# concurrent caller of the same key so N serving threads cost ONE load
_BUNDLE_LOADING: dict[str, threading.Event] = {}
# per-key invalidation generation: a load that STARTED before an
# invalidate_bundle call must not re-cache its (now stale) result after it
_BUNDLE_GEN: dict[str, int] = {}


def load_bundle_cached(export_dir: str, build_apply: Callable[[dict], Callable]) -> tuple[Any, dict, Callable]:
    """Per-process cached bundle load (reference ``pipeline._run_model``'s
    per-executor singleton SavedModel load, ``pipeline.py:~600-700``).

    Thread-safe with single-flight semantics: concurrent callers of the
    same ``export_dir`` (the serving gateway's replica workers, a reload
    racing a request) share ONE load — one thread loads while the rest
    wait on its completion event, then read the cache.  A failed load
    releases the key so the next caller retries rather than caching the
    error.  ``invalidate_bundle`` is the hot-reload hook.
    """
    key = os.path.abspath(resolve_uri(export_dir))
    while True:
        with _BUNDLE_LOCK:
            hit = _BUNDLE_CACHE.get(key)
            if hit is not None:
                return hit
            pending = _BUNDLE_LOADING.get(key)
            if pending is None:
                _BUNDLE_LOADING[key] = threading.Event()
                gen = _BUNDLE_GEN.get(key, 0)
        if pending is not None:
            pending.wait()  # loader finished (or failed); re-check the cache
            continue
        try:
            params, config = load_bundle(export_dir)
            value = (params, config, build_apply(config))
            with _BUNDLE_LOCK:
                if _BUNDLE_GEN.get(key, 0) == gen:
                    _BUNDLE_CACHE[key] = value
                # else: invalidate_bundle ran while this load was reading the
                # OLD export files — hand the stale value to THIS caller (it
                # started before the swap) but never cache it, or the hot
                # reload would be silently undone
            return value
        finally:
            with _BUNDLE_LOCK:
                done = _BUNDLE_LOADING.pop(key, None)
            if done is not None:
                done.set()


def invalidate_bundle(export_dir: str | None = None) -> None:
    """Drop cached bundle(s) so the next ``load_bundle_cached`` re-reads
    from disk — the serving hot-reload hook (``serving_loop``'s reload
    control round calls this before swapping in the newer export).
    ``None`` clears the whole cache.  Also fences out loads already in
    flight: their results are returned to their callers but not cached."""
    with _BUNDLE_LOCK:
        if export_dir is None:
            _BUNDLE_CACHE.clear()
            for key in _BUNDLE_LOADING:
                _BUNDLE_GEN[key] = _BUNDLE_GEN.get(key, 0) + 1
            return
        key = os.path.abspath(resolve_uri(export_dir))
        _BUNDLE_CACHE.pop(key, None)
        _BUNDLE_GEN[key] = _BUNDLE_GEN.get(key, 0) + 1


# ---------------------------------------------------------------------------
# Embedding shard checkpoints (sharded embedding tier)
#
# One logical table's rows are range-sharded across the training world; the
# full-tree checkpoints above never see them.  Each node instead commits its
# own resident range as a single npz under
#
#     <model_dir>/embed_<table>/step_<N>/shard_<lo>_<hi>.npz
#
# (atomic tmp-write + os.replace, matching export_bundle).  Restore is by
# RANGE, not by file: any requested [lo, hi) is reassembled from whatever
# shard files cover it, so a re-shard — eviction shrinking the world, a
# serve fleet sized differently from the train world — restores new bounds
# from old files without a repartition pass.
# ---------------------------------------------------------------------------


def _embed_step_dir(model_dir: str, table: str, step: int) -> str:
    return os.path.join(resolve_uri(model_dir), f"embed_{table}",
                        f"step_{int(step)}")


def save_embedding_shard(model_dir: str, table: str, step: int,
                         lo: int, hi: int, rows) -> str:
    """Atomically commit one shard's rows ``[lo, hi)`` at ``step``."""
    import numpy as np

    d = _embed_step_dir(model_dir, table, step)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"shard_{int(lo)}_{int(hi)}.npz")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, lo=np.int64(lo), hi=np.int64(hi),
                 rows=np.ascontiguousarray(np.asarray(rows, np.float32)))
    os.replace(tmp, path)
    return path


def _embed_shard_files(model_dir: str, table: str,
                       step: int) -> list[tuple[int, int, str]]:
    """(lo, hi, path) triples at ``step``, sorted by lo; [] if none."""
    d = _embed_step_dir(model_dir, table, step)
    out = []
    try:
        names = os.listdir(d)
    except FileNotFoundError:
        return out
    for name in names:
        if not (name.startswith("shard_") and name.endswith(".npz")):
            continue
        try:
            lo_s, hi_s = name[len("shard_"):-len(".npz")].split("_")
            out.append((int(lo_s), int(hi_s), os.path.join(d, name)))
        except ValueError:
            continue
    out.sort()
    return out


def restore_embedding_shard(model_dir: str, table: str, step: int,
                            lo: int, hi: int, dim: int):
    """Reassemble the row range ``[lo, hi)`` from the shard files at
    ``step``.  Raises ``FileNotFoundError`` if the files present do not
    fully cover the range (a partial checkpoint must not restore silently)."""
    import numpy as np

    out = np.empty((int(hi) - int(lo), int(dim)), np.float32)
    need = int(lo)
    for f_lo, f_hi, path in _embed_shard_files(model_dir, table, step):
        if f_hi <= need or f_lo >= hi:
            continue
        if f_lo > need:
            break  # gap before this file — range not covered
        with np.load(path) as z:
            rows = z["rows"]
        take_lo, take_hi = need, min(f_hi, int(hi))
        out[take_lo - int(lo):take_hi - int(lo)] = \
            rows[take_lo - f_lo:take_hi - f_lo]
        need = take_hi
        if need >= hi:
            break
    if need < hi:
        raise FileNotFoundError(
            f"embedding checkpoint for table {table!r} step {step} covers "
            f"only up to row {need}, need [{lo}, {hi}) under {model_dir}")
    return out


def embedding_steps(model_dir: str, table: str) -> list[int]:
    """All step numbers with at least one shard file, ascending."""
    base = os.path.join(resolve_uri(model_dir), f"embed_{table}")
    steps = []
    try:
        names = os.listdir(base)
    except FileNotFoundError:
        return steps
    for name in names:
        if name.startswith("step_"):
            try:
                steps.append(int(name[len("step_"):]))
            except ValueError:
                continue
    steps.sort()
    return steps


def latest_embedding_step(model_dir: str, table: str) -> int | None:
    """Newest checkpointed step for ``table``, or None."""
    steps = embedding_steps(model_dir, table)
    return steps[-1] if steps else None
