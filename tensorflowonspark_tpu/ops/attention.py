"""Attention ops: Pallas TPU flash attention + blockwise-JAX fallback.

The reference framework has no attention anywhere (SURVEY.md §5.7 — its
models are CNNs/wide-and-deep), but long-context support is first-class in
this build, so the hot op gets a real TPU kernel:

- ``flash_attention`` — public entry.  On TPU it runs a Pallas online-softmax
  kernel (forward) with a memory-efficient recompute backward; elsewhere it
  lowers to ``blockwise_attention`` (a ``lax.scan`` over KV blocks with
  per-block rematerialisation, so memory stays O(S·block) instead of O(S²)).
- ``chunk_attention`` / ``merge_attention`` — the (output, logsumexp)
  chunk-compute and online-softmax merge primitives that
  ``parallel/sp.py``'s ring attention composes over ICI neighbours.

Array convention: ``[batch, seq, heads, head_dim]`` (flax-style).  All
softmax accumulation is float32 regardless of input dtype (bf16 inputs keep
the MXU fed; the VPU-side accumulators must not lose mass).
"""

from __future__ import annotations

import functools
import math
from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # finite stand-in for -inf: keeps exp() exactly 0 without nan


def match_vma(x, like):
    """Mark a freshly-created array as device-varying over the same shard_map
    axes as ``like`` (no-op outside shard_map).  Scan carries must type-match
    their per-step outputs under jax's varying-manual-axes tracking."""
    vma = getattr(jax.typeof(like), "vma", frozenset())
    if vma:
        return jax.lax.pcast(x, axis_name=tuple(vma), to="varying")
    return x


# ---------------------------------------------------------------------------
# Reference (dense) attention — the spec the kernels are tested against.
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, *, causal: bool = True, sm_scale: float | None = None,
                  kv_offset: int = 0):
    """Dense O(S²) attention.  ``kv_offset`` is the global position of
    ``k[:, 0]`` relative to ``q[:, 0]`` (ring attention passes non-zero
    offsets so causal masks stay globally consistent across chunks)."""
    *_, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        qpos = jnp.arange(sq)[:, None]
        kpos = kv_offset + jnp.arange(sk)[None, :]
        logits = jnp.where(kpos <= qpos, logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v.astype(jnp.float32)).astype(q.dtype)


# ---------------------------------------------------------------------------
# Chunk + merge primitives (shared with ring attention in parallel/sp.py).
# ---------------------------------------------------------------------------

def chunk_attention(q, k, v, *, causal: bool = True, sm_scale: float | None = None,
                    kv_offset=0):
    """Attend q over one KV chunk; return ``(out, lse)``.

    ``out`` is the softmax-normalised output **for this chunk alone** and
    ``lse`` its log-sum-exp (``[B, Sq, H]``, float32).  Two chunk results
    combine exactly via ``merge_attention`` — the online-softmax identity
    ring attention is built on.  ``kv_offset`` may be a traced scalar.
    """
    *_, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    sq, sk = q.shape[1], k.shape[1]
    if causal:
        qpos = jnp.arange(sq)[:, None]
        kpos = kv_offset + jnp.arange(sk)[None, :]
        logits = jnp.where(kpos <= qpos, logits, NEG_INF)
    m = jnp.max(logits, axis=-1)                                   # [B,H,Sq]
    # Rows with every position masked (pure-future chunk): exp underflows to
    # 0 row-wise; guard the max so exp(NEG_INF - NEG_INF) doesn't become 1.
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(logits - m_safe[..., None])
    p = jnp.where(logits <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)                                        # [B,H,Sq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    lse = m_safe + jnp.log(jnp.maximum(l, 1e-30))
    lse = jnp.where(l > 0.0, lse, NEG_INF)
    o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return o.astype(q.dtype), lse.transpose(0, 2, 1)               # [B,Sq,H]


def merge_attention(o1, lse1, o2, lse2):
    """Merge two chunk results (online-softmax combine); fully-masked chunks
    (lse == NEG_INF) drop out exactly."""
    lse = jnp.logaddexp(lse1, lse2)
    lse = jnp.maximum(lse, NEG_INF)  # logaddexp(-inf,-inf) guard
    w1 = jnp.exp(lse1 - lse)[..., None]
    w2 = jnp.exp(lse2 - lse)[..., None]
    o = o1.astype(jnp.float32) * w1 + o2.astype(jnp.float32) * w2
    return o.astype(o1.dtype), lse


# ---------------------------------------------------------------------------
# Blockwise attention — differentiable lax.scan over KV blocks (any backend).
# ---------------------------------------------------------------------------

def blockwise_attention(q, k, v, *, causal: bool = True,
                        sm_scale: float | None = None, block_k: int = 512,
                        kv_offset: int = 0):
    """Flash-style attention as a ``lax.scan`` over KV blocks.

    Differentiable, runs on every backend, and with the per-block
    ``jax.checkpoint`` memory is O(Sq·block_k) — this is both the CPU test
    path and the recompute backward for the Pallas kernel.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    block_k = min(block_k, sk)
    nblocks = -(-sk // block_k)
    pad = nblocks * block_k - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    kb = k.reshape(b, nblocks, block_k, h, d).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(b, nblocks, block_k, h, d).transpose(1, 0, 2, 3, 4)

    qpos = jnp.arange(sq)[:, None]

    @jax.checkpoint
    def block(carry, inputs):
        o_acc, m_acc, l_acc = carry
        kc, vc, start = inputs
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kc).astype(jnp.float32) * scale
        kpos = kv_offset + start + jnp.arange(block_k)[None, :]
        mask = kpos < kv_offset + sk  # padded tail
        if causal:
            mask = mask & (kpos <= qpos)
        logits = jnp.where(mask, logits, NEG_INF)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m_acc, m_blk)
        m_safe = jnp.maximum(m_new, NEG_INF / 2)
        p = jnp.exp(logits - m_safe[..., None])
        p = jnp.where(logits <= NEG_INF / 2, 0.0, p)
        alpha = jnp.where(m_acc <= NEG_INF / 2, 0.0, jnp.exp(m_acc - m_safe))
        l_new = l_acc * alpha + jnp.sum(p, axis=-1)
        o_new = (o_acc * alpha.transpose(0, 2, 1)[..., None]
                 + jnp.einsum("bhqk,bkhd->bqhd", p, vc.astype(jnp.float32)))
        return (o_new, m_new, l_new), None

    o0 = match_vma(jnp.zeros((b, sq, h, d), jnp.float32), q)
    m0 = match_vma(jnp.full((b, h, sq), NEG_INF, jnp.float32), q)
    l0 = match_vma(jnp.zeros((b, h, sq), jnp.float32), q)
    starts = jnp.arange(nblocks) * block_k
    (o, m, l), _ = jax.lax.scan(block, (o0, m0, l0), (kb, vb, starts))
    o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return o.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel (forward) — online softmax over a sequential k-block grid.
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref,
                      *, sm_scale: float, causal: bool, kv_offset: int,
                      block_q: int, block_k: int, sq: int, sk: int):
    # m/l scratch and the lse output are lane-replicated to 128 lanes (column
    # 0 is authoritative) — TPU tiling requires the last dim be 128-aligned.
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = iq * block_q
    k_start = kv_offset + ik * block_k
    # Skip blocks that are entirely in the causal future or entirely padding.
    live = (k_start + 0) < kv_offset + sk
    if causal:
        live = jnp.logical_and(live, k_start <= q_start + block_q - 1)

    @pl.when(live)
    def _attend():
        qb = q_ref[0].astype(jnp.float32)              # [block_q, d]
        kb = k_ref[0].astype(jnp.float32)              # [block_k, d]
        logits = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0)
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        mask = kpos < kv_offset + sk
        if causal:
            mask = jnp.logical_and(mask, kpos <= qpos)
        logits = jnp.where(mask, logits, NEG_INF)
        m_prev = m_ref[:]                               # [block_q, 128]
        m_blk = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        m_safe = jnp.maximum(m_new, NEG_INF / 2)
        p = jnp.exp(logits - m_safe[:, 0:1])
        p = jnp.where(logits <= NEG_INF / 2, 0.0, p)
        alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0, jnp.exp(m_prev - m_safe))
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha[:, 0:1] + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(ik == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l[:, 0:1]).astype(o_ref.dtype)
        lse = m_ref[:] + jnp.log(l)
        lse_ref[0] = jnp.where(l_ref[:] > 0.0, lse, NEG_INF)


def _flash_fwd_pallas(q, k, v, *, causal, sm_scale, kv_offset,
                      block_q, block_k, interpret):
    """Run the Pallas forward; returns (out, lse).  Head-major internally."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)

    # Head-major [B*H, S, D]; pad S to block multiples and D to the 128 lane.
    def to_bh(x, s):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    qt, kt, vt = to_bh(q, sq), to_bh(k, sk), to_bh(v, sk)
    block_q = min(block_q, max(8, sq))
    block_k = min(block_k, max(8, sk))
    sq_p = -(-sq // block_q) * block_q
    sk_p = -(-sk // block_k) * block_k
    d_p = max(128, -(-d // 128) * 128) if not interpret else d
    qt = jnp.pad(qt, ((0, 0), (0, sq_p - sq), (0, d_p - d)))
    kt = jnp.pad(kt, ((0, 0), (0, sk_p - sk), (0, d_p - d)))
    vt = jnp.pad(vt, ((0, 0), (0, sk_p - sk), (0, d_p - d)))

    grid = (b * h, sq_p // block_q, sk_p // block_k)
    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=scale, causal=causal, kv_offset=kv_offset,
        block_q=block_q, block_k=block_k, sq=sq, sk=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d_p), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, block_k, d_p), lambda bh, iq, ik: (bh, ik, 0)),
            pl.BlockSpec((1, block_k, d_p), lambda bh, iq, ik: (bh, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d_p), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, block_q, 128), lambda bh, iq, ik: (bh, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq_p, d_p), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq_p, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d_p), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    out = out[:, :sq, :d].reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    lse = lse[:, :sq, 0].reshape(b, h, sq).transpose(0, 2, 1)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_attention_tpu(q, k, v, causal, sm_scale, kv_offset,
                         block_q, block_k, interpret):
    out, _ = _flash_fwd_pallas(q, k, v, causal=causal, sm_scale=sm_scale,
                               kv_offset=kv_offset, block_q=block_q,
                               block_k=block_k, interpret=interpret)
    return out


def _flash_fwd_rule(q, k, v, causal, sm_scale, kv_offset, block_q, block_k,
                    interpret):
    out = _flash_attention_tpu(q, k, v, causal, sm_scale, kv_offset,
                               block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd_rule(causal, sm_scale, kv_offset, block_q, block_k, interpret,
                    res, g):
    # Memory-efficient recompute backward: VJP through the blockwise scan
    # (each block is checkpointed, so peak memory stays O(S·block_k)).
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, causal=causal, sm_scale=sm_scale,
            block_k=block_k, kv_offset=kv_offset),
        q, k, v)
    return vjp(g)


_flash_attention_tpu.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ---------------------------------------------------------------------------
# Public entry.
# ---------------------------------------------------------------------------

Impl = Literal["pallas", "pallas_interpret", "xla", "reference"]


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: float | None = None, kv_offset: int = 0,
                    block_q: int = 512, block_k: int = 512,
                    impl: Impl | None = None):
    """Multi-head attention, ``[B, S, H, D]`` in and out.

    ``impl=None`` auto-selects: Pallas kernel on TPU, blockwise XLA scan
    elsewhere.  ``pallas_interpret`` runs the kernel in interpreter mode (CPU
    tests of the kernel itself).
    """
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "reference":
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                             kv_offset=kv_offset)
    if impl == "xla":
        return blockwise_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                                   block_k=block_k, kv_offset=kv_offset)
    if impl in ("pallas", "pallas_interpret"):
        return _flash_attention_tpu(q, k, v, causal, sm_scale, kv_offset,
                                    block_q, block_k,
                                    impl == "pallas_interpret")
    raise ValueError(f"unknown attention impl {impl!r}")
