"""Blockwise (vocab-chunked) softmax cross-entropy for large-vocab LM heads.

The dense LM loss path materializes ``[B, S, V]`` float32 logits twice
(forward activations + backward cotangents) — at the bench shape
(8×2048×32000) that is ~2 GB of HBM traffic per direction for a loss whose
useful output is one scalar per token.  This op never materializes more
than ``[N, chunk]`` logits: the head matmul, online logsumexp, and the
softmax-minus-onehot backward are streamed over vocabulary chunks with
``lax.scan``, recomputing chunk logits in the backward instead of saving
them (the same recompute-over-residuals trade the flash-attention kernel
makes — SURVEY.md §5.7 is the design's cousin).

No counterpart exists in the reference (its models are CNNs/wide-and-deep;
losses are delegated to TF) — this exists because the LM family is
first-class here.  XLA-level implementation (``lax.scan`` + dot_general with
f32 accumulation), so it runs on TPU and CPU alike and GSPMD shards the
token axis; for tensor-parallel vocab sharding use the dense path instead
(the chunk scan would fight the tp partitioning of the head kernel).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30


def _pad_vocab(kernel: jax.Array, chunk: int) -> tuple[jax.Array, int]:
    """Reshape ``[D, V]`` → ``[n_chunks, D, chunk]``, zero-padding V up."""
    d, v = kernel.shape
    n_chunks = -(-v // chunk)
    pad = n_chunks * chunk - v
    if pad:
        kernel = jnp.pad(kernel, ((0, 0), (0, pad)))
    return kernel.reshape(d, n_chunks, chunk).transpose(1, 0, 2), n_chunks


def _chunk_logits(h: jax.Array, w_c: jax.Array, first_col: jax.Array,
                  vocab: int) -> jax.Array:
    """f32 ``[N, chunk]`` logits for one kernel chunk; padded cols → -inf."""
    logits = jax.lax.dot_general(
        h, w_c, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    cols = first_col + jnp.arange(w_c.shape[1])
    return jnp.where(cols[None, :] < vocab, logits, _NEG_INF)


def blockwise_cross_entropy(hidden: jax.Array, kernel: jax.Array,
                            targets: jax.Array, chunk: int = 4096) -> jax.Array:
    """Per-token ``-log softmax(hidden @ kernel)[target]`` without the
    ``[N, V]`` materialization.

    Args:
      hidden: ``[N, D]`` final hidden states (any float dtype; matmuls
        accumulate in f32).
      kernel: ``[D, V]`` LM-head kernel.
      targets: ``[N]`` int32 target ids in ``[0, V)``.
      chunk: vocab tile width (V is zero-padded up to a multiple).

    Returns: ``[N]`` float32 negative log-likelihoods.
    """
    chunk = min(chunk, kernel.shape[1])
    return _blockwise_xent(hidden, kernel, targets, chunk, kernel.shape[1])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _blockwise_xent(hidden, kernel, targets, chunk, vocab):
    nll, _ = _forward(hidden, kernel, targets, chunk, vocab)
    return nll


def _forward(hidden, kernel, targets, chunk, vocab):
    n = hidden.shape[0]
    w_chunks, n_chunks = _pad_vocab(kernel, chunk)

    def body(carry, scan_in):
        m, s, tgt = carry
        ci, w_c = scan_in
        first = ci * chunk
        logits = _chunk_logits(hidden, w_c, first, vocab)  # [N, chunk]
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        local = targets - first
        in_chunk = (local >= 0) & (local < chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=-1)[:, 0]
        tgt = jnp.where(in_chunk, picked, tgt)
        return (m_new, s, tgt), None

    init = (jnp.full((n,), _NEG_INF, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.full((n,), _NEG_INF, jnp.float32))
    (m, s, tgt), _ = jax.lax.scan(
        body, init, (jnp.arange(n_chunks), w_chunks))
    lse = m + jnp.log(s)
    return lse - tgt, lse


def _fwd(hidden, kernel, targets, chunk, vocab):
    nll, lse = _forward(hidden, kernel, targets, chunk, vocab)
    return nll, (hidden, kernel, targets, lse)


def _bwd(chunk, vocab, residuals, g):
    hidden, kernel, targets, lse = residuals
    w_chunks, n_chunks = _pad_vocab(kernel, chunk)

    def body(dh, scan_in):
        ci, w_c = scan_in
        first = ci * chunk
        logits = _chunk_logits(hidden, w_c, first, vocab)
        # d nll / d logits = softmax - onehot(target); scale by the incoming
        # per-token cotangent.  Padded columns have softmax exactly 0.
        p = jnp.exp(logits - lse[:, None])
        local = targets - first
        onehot = ((local[:, None] == jnp.arange(chunk)[None, :])
                  .astype(jnp.float32))
        dlogits = (p - onehot) * g[:, None].astype(jnp.float32)
        dh = dh + jax.lax.dot_general(
            dlogits, w_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dw_c = jax.lax.dot_general(
            hidden, dlogits, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dh, dw_c

    dh, dw_chunks = jax.lax.scan(
        body, jnp.zeros(hidden.shape, jnp.float32),
        (jnp.arange(n_chunks), w_chunks))
    d = kernel.shape[0]
    dw = dw_chunks.transpose(1, 0, 2).reshape(d, n_chunks * chunk)
    dw = dw[:, : kernel.shape[1]]
    dtargets = np.zeros(targets.shape, jax.dtypes.float0)  # int arg: float0
    return dh.astype(hidden.dtype), dw.astype(kernel.dtype), dtargets


_blockwise_xent.defvjp(_fwd, _bwd)
