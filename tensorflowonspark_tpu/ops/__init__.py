"""Custom ops: Pallas TPU kernels for the hot paths."""
