"""Custom ops: Pallas TPU kernels for the hot paths."""

from tensorflowonspark_tpu.ops.attention import (  # noqa: F401
    blockwise_attention,
    chunk_attention,
    flash_attention,
    merge_attention,
    mha_reference,
)
