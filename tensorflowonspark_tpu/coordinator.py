"""Cluster rendezvous / control-plane coordinator.

TPU-native replacement for ``tensorflowonspark/reservation.py`` (reference:
``MessageSocket`` 4-byte length framing ``:~20-60``; REG/QUERY/QINFO/STOP
``:~100-200``; ``Server.await_reservations`` ``:~120-160``).  Differences by
design (SURVEY.md §5.2, §5.8):

- **Race-free identity**: the server *assigns* ``executor_id`` and the job
  role (chief/worker/evaluator) at registration, instead of deriving it from a
  Spark partition id — this is the ``CUDA_VISIBLE_DEVICES``-handout replaced
  by mesh-coordinate handout (BASELINE.json:5).
- **Barrier + reduce primitives**: sync SPMD needs *global* agreement (e.g.
  the end-of-data consensus of SURVEY.md §7.3-1), which the reference's async
  PS design never needed.  ``reduce`` implements an all-reduce over the
  control plane (DCN), not the tensor plane.
- **Heartbeats**: the reference relied on Spark noticing dead executors;
  with no Spark layer the coordinator tracks liveness itself (SURVEY.md §5.3).
- **JSON framing, not pickle**: the control plane carries only small metadata
  dicts; JSON avoids arbitrary-object deserialization on the driver.

The *tensor* plane never touches this module: device-to-device traffic is XLA
collectives over ICI emitted by jit-compiled SPMD programs (SURVEY.md §5.8-2).
"""

from __future__ import annotations

import contextlib
import json
import logging
import socket
import socketserver
import struct
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_condition, tos_named_lock
import time
from typing import Any

from tensorflowonspark_tpu import faultinject, telemetry
from tensorflowonspark_tpu.telemetry import trace as ttrace
from tensorflowonspark_tpu.telemetry.registry import percentile_of

logger = logging.getLogger(__name__)

# Per-node "recent span samples" kept for cluster-wide percentile pooling
# (each heartbeat delta ships up to telemetry.OUTBOX_SIZE new samples per
# histogram; the store keeps a bounded tail per (node, metric)).
_HIST_RECENT_CAP = 256
# Per-node trace-stream store bounds: spans a run keeps for the merged
# trace.json, flight events for the run report's timeline.
_TRACE_SPAN_CAP = 16384
_TRACE_EVENT_CAP = 1024
# Rolling-stats history: one entry per heartbeat merge (nodes) / sampler
# tick (driver); 240 entries at ~1-2s cadence cover several minutes of
# window, far past any sensible `cluster.stats(window=...)`.
_STATS_HISTORY_CAP = 240
# Write-ahead journal snapshot cadence: after this many appended records the
# stats thread folds the full control-plane state into <journal>.snap and
# truncates the tail, so crash recovery replays O(delta) records.
_JOURNAL_SNAPSHOT_EVERY = 256
# Straggler-suspicion vote freshness: votes older than this never count
# toward an eviction quorum (a live straggler's accusers re-file every
# second; a one-off hiccup's vote must age out, not lie in ambush).
_SUSPECT_VOTE_TTL = 30.0
# Eviction confirmation hold: quorum against a suspect must SURVIVE this
# window before the eviction fires.  Uniform slowness makes everyone blame
# their upstream at once, but the votes arrive one by one — a partial
# blame cycle is indistinguishable from a genuine chain until the would-be
# suspect's own vote lands and dissolves it.  A true straggler files
# nothing (it is busy being wedged), so it only costs ~this much detection
# latency; accusers re-file every second, which re-evaluates the hold.
_EVICT_CONFIRM_SECS = 2.0

_LEN = struct.Struct(">I")
_MAX_FRAME = 64 * 1024 * 1024


class CoordinatorRestarted(RuntimeError):
    """The control plane crashed and restarted under this call: the
    connection (or rendezvous generation) the request rode is gone, or the
    request carried a pre-crash coordinator epoch and was fenced.  The
    client has already reconnected and learned the new epoch — callers own
    the retry at their own abstraction level (a collective group re-forms
    at the next generation barrier; idempotent ops are retried
    transparently and never raise this)."""


class CoordinatorFenced(RuntimeError):
    """This client's (executor_id, incarnation) is FENCED: the slot was
    declared dead and re-fenced, or — the gray-failure case — the process
    was EVICTED from its collective group at quorum and parked in
    probation.  A RuntimeError subclass so existing retry loops keep
    working; typed so a collective ``form`` can tell "ride out probation,
    readmission will hand me a fresh incarnation" apart from transient
    rendezvous churn."""


def _send_msg(sock: socket.socket, obj: dict) -> None:
    # chaos seam: `delay_net:ms=M` injects latency on every control-plane
    # send in the armed process (no-op unless TOS_FAULTINJECT armed it)
    faultinject.net_delay()
    data = json.dumps(obj).encode("utf-8")
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_msg(sock: socket.socket) -> dict:
    from tensorflowonspark_tpu.utils.net import recv_exact

    (n,) = _LEN.unpack(recv_exact(sock, 4))
    if n > _MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return json.loads(recv_exact(sock, n).decode("utf-8"))


class _Rendezvous:
    """One barrier/reduce *generation* shared by ``count`` participants.

    Lifecycle: participants join until ``count`` values arrive, the last one
    computes the result and marks ``done`` (popping the registry entry, so a
    subsequent same-named call starts a fresh generation while waiters still
    hold this object).  A participant that times out marks the generation
    ``aborted`` and pops it, so retries never observe stale values.
    """

    def __init__(self, count: int):
        self.count = count
        self.cond = tos_named_condition("coordinator.rendezvous._cond")
        self.values: list[Any] = []
        self.result: Any = None
        self.done = False
        self.aborted = False
        # span anchor: generation open -> last participant closes it
        self.t0 = time.monotonic()


def _window_stats(entries: list, now: float, window: float) -> dict | None:
    """Rolling-window view of one stream's history entries
    ``(t, cumulative_counters, gauges, hist_samples)``: counter rates over
    the window, percentiles pooled from in-window samples only, latest
    gauges.  None when the stream has no history at all."""
    if not entries:
        return None
    start = now - window
    last_t, last_counters, last_gauges, _ = entries[-1]
    # baseline: the newest entry at/before the window start (so the delta
    # spans the whole window); with a short history, the earliest entry
    base = entries[0]
    for e in entries:
        if e[0] <= start:
            base = e
        else:
            break
    rates: dict[str, float] = {}
    if last_t <= start:
        # nothing moved inside the window: every rate is flat zero (a stale
        # delta must not report phantom load after traffic stops)
        rates = {name: 0.0 for name in last_counters}
    else:
        dt = last_t - base[0]
        if dt > 0:
            for name, v in last_counters.items():
                # clamp: a counter reset inside the window (process restart
                # the history clear raced) must read as idle, never negative
                delta = max(0, v - base[1].get(name, 0))
                if delta:
                    rates[name] = round(delta / dt, 3)
                else:
                    rates[name] = 0.0
    pool: dict[str, list[float]] = {}
    for t, _c, _g, samples in entries:
        if t < start:
            continue
        for name, vals in samples.items():
            pool.setdefault(name, []).extend(vals)
    percentiles = {
        name: {"n": len(vals),
               "p50": percentile_of(vals, 50.0),
               "p99": percentile_of(vals, 99.0)}
        for name, vals in ((n, sorted(v)) for n, v in pool.items()) if vals}
    return {"age_secs": round(now - last_t, 3), "rates": rates,
            "gauges": dict(last_gauges), "percentiles": percentiles}


def _pct_ms(stream: dict, name: str, q: str) -> float | None:
    v = ((stream.get("percentiles") or {}).get(name) or {}).get(q)
    return round(v * 1e3, 3) if v is not None else None


def _reduce(kind: str, values: list[Any]) -> Any:
    if kind == "any":
        return any(values)
    if kind == "all":
        return all(values)
    if kind == "sum":
        return sum(values)
    if kind == "min":
        return min(values)
    if kind == "max":
        return max(values)
    if kind == "gather":
        return values
    if kind == "form":
        # Collective-group formation rendezvous (collective/group.py): each
        # participant contributes {eid, host, port, gen, step}; everyone
        # gets back the SAME membership view — eid-sorted members (rank =
        # index of own eid), the max proposed generation (survivors propose
        # cur+1, a cold joiner proposes 1 — max keeps generations monotone
        # across any mix), and the max step vote (the resume point
        # sync_state levels the group onto).  Arrival order never matters.
        members = sorted((dict(v) for v in values),
                         key=lambda m: int(m["eid"]))
        return {"members": members,
                "generation": max(int(m.get("gen", 1)) for m in members),
                "step": max(int(m.get("step", 0)) for m in members)}
    raise ValueError(f"unknown reduce kind: {kind}")


class CoordinatorServer:
    """Driver-side rendezvous server for ``expected`` node processes.

    Mirrors ``reservation.Server`` but also assigns identities/roles and
    provides barrier/reduce/heartbeat/error channels.
    """

    def __init__(self, expected: int, roles: list[tuple[str, int]] | None = None,
                 authkey: bytes | None = None, stats_interval: float = 1.0,
                 journal_path: str | None = None):
        if roles is not None and len(roles) != expected:
            raise ValueError("roles must have one entry per expected node")
        self.expected = expected
        # Shared cluster authkey: when set, every connection must pass the
        # HMAC challenge-response before its first frame is read.  The control
        # plane accepts register/stop from the network once it binds a
        # routable interface, so it gets the same gate the pickle-carrying
        # data plane always had (utils/net.py handshake).
        self.authkey = authkey
        # role for executor i; default: executor 0 is chief, rest workers.
        self.roles = roles or [("chief", 0)] + [("worker", i) for i in range(1, expected)]
        self._lock = tos_named_lock("coordinator._lock")
        self._nodes: list[dict] = []
        self._complete = threading.Event()
        self._stop_flag = threading.Event()
        self._errors: list[dict] = []
        self._rdv: dict[str, _Rendezvous] = {}
        self._last_seen: dict[int, float] = {}
        # Generation fencing (TF-Replicator-style, PAPERS.md): each executor
        # slot has an incarnation number, bumped the moment the slot is
        # declared dead.  Every node-side message carries its incarnation;
        # anything from a stale incarnation — a zombie that lost its network,
        # not its life — is rejected, so a restarted replacement can never
        # race its predecessor on heartbeats, barriers, or reduces.
        self._incarnations: dict[int, int] = {}
        # Elastic membership (cluster.resize): slots being deliberately
        # drained out of service (no new work; death mid-drain finalizes the
        # retirement instead of triggering recovery) and slots already
        # retired for good (their executor_id is never reused — SPMD-style
        # positional identity stays stable across the cluster's lifetime).
        self._draining: set[int] = set()
        self._retired: set[int] = set()
        # Telemetry store: the latest raw registry snapshot per executor,
        # merged key-by-key from the compact deltas nodes piggyback on
        # heartbeats (and the final snapshot sent with deregister).  Values
        # are absolute cumulative per process, so merging is replacement and
        # a dropped heartbeat never loses counts; a restarted slot's
        # counters restart with its process (per-incarnation counters).
        self._node_metrics: dict[int, dict] = {}
        self._hist_recent: dict[int, dict[str, list[float]]] = {}
        # Trace-stream store: spans/flight events each node piggybacks on
        # heartbeats (and the final deregister), plus its latest clock
        # offset estimate, keyed by executor id; "driver" entries accumulate
        # from this process's own tracer on demand (bounded like the rest).
        self._node_trace: dict[str, dict] = {}
        # Rolling-stats history (cluster.stats): per node one timestamped
        # entry per heartbeat merge; the "driver" stream is appended by a
        # sampler thread started with the server (the driver sends no
        # heartbeats, and its registry holds the serving-gateway signals
        # the autoscaler wants).
        self._stats_history: dict[str, list] = {}
        self._stats_interval = max(0.05, float(stats_interval))
        self._stats_stop = threading.Event()
        self._stats_thread: threading.Thread | None = None
        # DIRECT-mode job manifest: what the driver's shard enumeration
        # produced for the current train() (shard/partition/epoch counts),
        # published so map_funs can read progress denominators without a
        # side channel (ctx.job_manifest()).
        self._manifest: dict = {}
        # Serving replica registry: each ReplicaRouter publishes its healthy
        # replica set here (journal-backed), so a control-plane failover
        # restores which replicas were serving — statz/run-report evidence
        # operators read after the fact.
        self._serving: dict[str, list[int]] = {}
        # Staged-rollout registry (ISSUE 16): each gateway journals its
        # in-flight rollout's state (candidate/prior/canary cohort/status)
        # here, so a control-plane failover restores what was mid-rollout
        # and statz shows promotions/rollbacks after the fact.
        self._rollouts: dict[str, dict] = {}
        # Gray-failure tolerance (ISSUE 15): suspicion votes per collective
        # group ({group: {suspect_eid: {voter_eid: mono_time}}}), the live
        # membership each group's last `form` produced, members EVICTED at
        # quorum and parked in probation ({eid: {"group", "probation_until",
        # "last_ping", "incarnation"}}), slots whose evicted process was
        # readmitted and must relearn its bumped incarnation over its next
        # round-trips ({eid: incarnation}), the event feed the cluster
        # monitor drains (park/unpark the supervisor, rebalance the
        # ledger), and the run-lifetime eviction log for stats/tests.
        self._suspicions: dict[str, dict[int, dict[int, float]]] = {}
        self._evict_pending: dict[tuple[str, int], float] = {}
        self._collective: dict[str, dict] = {}
        self._evicted: dict[int, dict] = {}
        self._readmit_pending: dict[int, int] = {}
        self._collective_events: list[dict] = []
        self._eviction_log: list[dict] = []
        self._readmits_total = 0
        # Write-ahead journal (ISSUE 13): every control-plane mutation
        # appends an fsync'd record (under self._lock, so record order IS
        # mutation order); crash() + restore() replay it into this same
        # object under a bumped COORDINATOR EPOCH carried on every reply.
        # truncate=True: a fresh server is a fresh run — a stale journal
        # from a previous cluster in the same log_dir must never replay.
        self._journal_path = journal_path
        self._journal = None
        if journal_path:
            from tensorflowonspark_tpu.journal import Journal

            self._journal = Journal(journal_path, truncate=True)
        self._epoch = 0
        self._crashed = threading.Event()
        self._crash_listeners: list = []
        # live handler connections, severed wholesale by crash() so every
        # client observes an abrupt coordinator death (ECONNRESET), exactly
        # like a real process kill would present
        self._conns: set[socket.socket] = set()
        # initial role template, the restore() fallback when no snapshot
        # exists yet (the journal tail then replays every mutation since)
        self._init_roles = list(self.roles)
        self._init_expected = expected
        self._bind_host: str | None = None
        self._port = 0
        self._server: socketserver.ThreadingTCPServer | None = None
        self._thread: threading.Thread | None = None
        self.address: tuple[str, int] | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self, host: str | None = None) -> tuple[str, int]:
        """Bind and return the address nodes should dial.

        When an ``authkey`` is set, binds all interfaces by default so
        *remote* executors can register (reference parity:
        ``reservation.Server`` served the driver's routable address to every
        executor, ``reservation.py:~120-200``) — but **advertises** the
        routable ``local_ip()``, never the wildcard or loopback, because the
        returned address is baked into every ``NodeConfig.coordinator_addr``
        shipped to (possibly remote) nodes.  Without an authkey the default
        bind stays loopback: an unauthenticated register/stop channel must
        not be network-reachable.  Pass ``host`` (or set
        ``TOS_COORDINATOR_HOST``) to pin a specific interface; that exact
        address is then advertised.
        """
        # Chaos hooks (kill_coordinator / delay_net) arm from the driver's
        # own environment; idempotent when a test armed them explicitly.
        faultinject.init_from_env()
        if host is None:
            # Only an authenticated server may take a network bind from the
            # environment — TOS_COORDINATOR_HOST must never silently expose
            # an unauthenticated register/stop channel.
            from tensorflowonspark_tpu.utils.envtune import env_str

            host = (env_str("TOS_COORDINATOR_HOST", "")
                    if self.authkey is not None else "127.0.0.1")
        bind_host = "" if host in ("", "0.0.0.0") else host
        self._bind_host = bind_host
        self._start_server(bind_host, 0)
        if bind_host == "":
            from tensorflowonspark_tpu.utils.net import local_ip

            advertise = local_ip()
        else:
            advertise = bind_host
        self.address = (advertise, self._port)
        self._start_stats_thread()
        logger.info("coordinator listening on %s:%d (expecting %d nodes)", *self.address, self.expected)
        return self.address

    def _start_server(self, bind_host: str, port: int) -> None:
        """Bind + start the request server on ``(bind_host, port)`` (port 0
        = pick one; restore() passes the ORIGINAL port so recovering clients
        redial the address baked into every NodeConfig)."""
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one connection, many requests
                from tensorflowonspark_tpu.utils.net import set_nodelay

                # request/reply stream of small JSON frames: with Nagle on,
                # every barrier/reduce/heartbeat risks a ~40ms delayed-ACK
                # stall (the client side already dials with nodelay)
                set_nodelay(self.request)
                if outer.authkey is not None:
                    from tensorflowonspark_tpu.utils.net import hmac_handshake_server

                    # Bounded handshake: an idle peer (port scanner, half-open
                    # connect) must not pin this handler thread + fd forever.
                    try:
                        self.request.settimeout(10.0)
                        if not hmac_handshake_server(self.request, outer.authkey):
                            logger.warning("rejected control-plane connection: bad authkey")
                            return
                        self.request.settimeout(None)
                    except (ConnectionError, OSError):
                        return
                with outer._lock:
                    outer._conns.add(self.request)
                try:
                    while True:
                        msg = _recv_msg(self.request)
                        resp = outer._dispatch(msg)
                        _send_msg(self.request, resp)
                        if msg.get("op") in ("stop", "bye"):
                            return
                except (ConnectionError, OSError):
                    return
                finally:
                    with outer._lock:
                        outer._conns.discard(self.request)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((bind_host, port), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="coordinator")
        self._thread.start()

    def _start_stats_thread(self) -> None:
        # driver stats sampler: the rolling-window half of cluster.stats()
        # for THIS process's registry (nodes sample themselves implicitly,
        # one history entry per heartbeat merge)
        self._stats_thread = threading.Thread(target=self._stats_loop,
                                              daemon=True,
                                              name="coordinator-stats")
        self._stats_thread.start()

    def stop(self) -> None:
        self._stop_flag.set()
        self._stats_stop.set()
        if self._stats_thread is not None:
            self._stats_thread.join(timeout=5.0)
            self._stats_thread = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._journal is not None:
            with contextlib.suppress(Exception):
                self._journal.close()

    # -- crash / journaled recovery (ISSUE 13) -------------------------------

    @property
    def epoch(self) -> int:
        """Coordinator epoch: bumped by every journaled recovery; carried on
        every control-plane reply so clients detect a failover (0 = the
        control plane has never crashed)."""
        return self._epoch

    @property
    def journal_enabled(self) -> bool:
        return self._journal_path is not None

    def live_journal(self):
        """The current Journal instance, or None while crashed / journal-
        less — the indirection ledger riders use so they never append to a
        pre-crash journal generation's closed fd."""
        if self._crashed.is_set():
            return None
        return self._journal

    def add_crash_listener(self, callback) -> None:
        """Register a zero-arg callable invoked (once, from the crashing
        thread) when the control plane crashes — the CoordinatorSupervisor's
        wake-up."""
        self._crash_listeners.append(callback)

    def crashed(self) -> bool:
        return self._crashed.is_set()

    def _log(self, rec_kind: str, sync: bool = True, **payload) -> None:
        """Append one journal record.  Caller MUST hold ``self._lock`` when
        journaling a state mutation (record order is replay order).
        ``sync=False`` is for the purely observational rendezvous-lifecycle
        records replay treats as no-ops: they skip the fsync (the next
        synced mutation or snapshot flushes them), so the per-generation
        hot path never pays a disk flush for flight evidence."""
        j = self._journal
        if j is None or self._crashed.is_set():
            return
        try:
            j.append(rec_kind, payload, sync=sync)
        except Exception:  # noqa: BLE001 - a full disk must not kill the control plane
            logger.warning("journal append (%s) failed", rec_kind,
                           exc_info=True)

    def _snapshot_state_locked(self) -> dict:
        """Full control-plane state, JSON-safe, for a journal snapshot."""
        return {
            "epoch": self._epoch,
            "expected": self.expected,
            "roles": [[name, task] for name, task in self.roles],
            "nodes": [dict(m) for m in self._nodes],
            "incarnations": {str(k): v for k, v in self._incarnations.items()},
            "draining": sorted(self._draining),
            "retired": sorted(self._retired),
            "manifest": dict(self._manifest),
            "errors": [dict(e) for e in self._errors],
            "serving": {k: list(v) for k, v in self._serving.items()},
            "rollouts": {k: dict(v) for k, v in self._rollouts.items()},
            # gray-failure state: who sits in probation (probation clocks
            # are monotonic and restart conservatively at restore) and who
            # is mid-relearn of a readmitted incarnation
            "evicted": {str(e): d["group"] for e, d in self._evicted.items()},
            "readmit_pending": {str(e): i
                                for e, i in self._readmit_pending.items()},
            "complete": self._complete.is_set(),
            # registered slots with no liveness clock (declared dead, or
            # cleanly deregistered): restore must NOT re-seed them, or a
            # finished node would later be re-declared dead and fail the job
            "untracked": sorted(int(m["executor_id"]) for m in self._nodes
                                if m["executor_id"] not in self._last_seen),
        }

    def _maybe_snapshot(self) -> None:
        """Periodic snapshot (stats-thread cadence): fold the journal tail
        into ``<journal>.snap`` once it grows past the threshold, holding
        ``_lock`` across build-and-write so the snapshot is consistent with
        every mutation record it truncates."""
        j = self._journal
        if j is None or self._crashed.is_set():
            return
        if j.appended_since_snapshot() < _JOURNAL_SNAPSHOT_EVERY:
            return
        try:
            with self._lock:
                j.snapshot(self._snapshot_state_locked())
        except Exception:  # noqa: BLE001 - snapshotting is an optimization, never fatal
            logger.warning("journal snapshot failed", exc_info=True)

    def crash(self) -> None:
        """Kill the control-plane server component abruptly (chaos /
        ``kill_coordinator``): sever every live connection, stop the server
        and sampler threads, abort in-flight rendezvous, and WIPE the
        in-memory control-plane state — everything a real coordinator
        process death would take with it.  The fsync'd journal on disk is
        the only survivor; :meth:`restore` rebuilds from it.  Telemetry /
        trace stores are process-local observability, kept so the run's
        postmortem spans the failover."""
        if self._crashed.is_set():
            return
        self._crashed.set()
        logger.error("coordinator control plane CRASHED (epoch %d); journal "
                     "at %s", self._epoch, self._journal_path)
        telemetry.counter("coordinator.crashes_total").inc()
        ttrace.event("coordinator_crash", epoch=self._epoch)
        if self._journal is not None:
            with contextlib.suppress(Exception):
                self._journal.close()
        # sever: listening socket + every accepted connection, abruptly
        server, self._server = self._server, None
        if server is not None:
            with contextlib.suppress(Exception):
                server.shutdown()
                server.server_close()
        with self._lock:
            conns, self._conns = list(self._conns), set()
        for conn in conns:
            with contextlib.suppress(OSError):
                conn.close()
        # waiters blocked inside _op_reduce would otherwise ride out their
        # full timeout against a server that no longer exists
        self._abort_rendezvous()
        self._stats_stop.set()
        if self._stats_thread is not None:
            self._stats_thread.join(timeout=5.0)
            self._stats_thread = None
        with self._lock:
            self._nodes = []
            self._last_seen = {}
            self._incarnations = {}
            self._draining = set()
            self._retired = set()
            self._errors = []
            self._manifest = {}
            self._serving = {}
            self._rollouts = {}
            self._rdv = {}
            self._suspicions = {}
            self._evict_pending = {}
            self._collective = {}
            self._evicted = {}
            self._readmit_pending = {}
            self._collective_events = []
        for cb in list(self._crash_listeners):
            try:
                cb()
            except Exception:  # noqa: BLE001 - a listener bug must not mask the crash
                logger.warning("coordinator crash listener failed",
                               exc_info=True)

    def restore(self) -> int:
        """Recover from :meth:`crash`: replay the journal (snapshot + tail)
        into this object, bump the coordinator epoch, rebind the ORIGINAL
        port, and seed every registered live slot's liveness clock so
        reconnecting nodes get the full death-declaration window to
        re-assert themselves.  Returns the new epoch."""
        if not self._crashed.is_set():
            raise RuntimeError("restore() is only valid after crash()")
        if self._journal_path is None:
            raise RuntimeError("cannot restore a journal-less coordinator")
        from tensorflowonspark_tpu import journal as journal_mod

        snap, records = journal_mod.replay(self._journal_path)
        snap = snap or {}
        with self._lock:
            self.roles = [tuple(r) for r in snap.get("roles",
                                                     self._init_roles)]
            self.expected = int(snap.get("expected", self._init_expected))
            self._epoch = int(snap.get("epoch", self._epoch))
            self._nodes = [dict(m) for m in snap.get("nodes") or []]
            self._incarnations = {int(k): int(v) for k, v in
                                  (snap.get("incarnations") or {}).items()}
            self._draining = set(snap.get("draining") or [])
            self._retired = set(snap.get("retired") or [])
            self._manifest = dict(snap.get("manifest") or {})
            self._errors = [dict(e) for e in snap.get("errors") or []]
            self._serving = {k: [int(x) for x in v] for k, v in
                             (snap.get("serving") or {}).items()}
            self._rollouts = {k: dict(v) for k, v in
                              (snap.get("rollouts") or {}).items()}
            self._evicted = {}
            for e, group in (snap.get("evicted") or {}).items():
                self._restore_evicted_locked(int(e), str(group))
            self._readmit_pending = {
                int(e): int(i)
                for e, i in (snap.get("readmit_pending") or {}).items()}
            complete = bool(snap.get("complete", False))
            untracked = {int(x) for x in snap.get("untracked") or []}
            for rec in records:
                complete = self._apply_record_locked(rec, complete, untracked)
            # Re-emit eviction/readmission events for the restored state:
            # the crash wiped any not-yet-drained event (and the monitor
            # may have missed the originals entirely if the crash raced its
            # tick), so the cluster-side side effects — supervisor
            # park/unpark, ledger rebalance, train re-attach — are replayed
            # from scratch.  All of them are idempotent by construction.
            for eid, d in self._evicted.items():
                self._collective_events.append(
                    {"kind": "evicted", "eid": eid, "group": d["group"]})
            for eid in self._readmit_pending:
                if eid not in self._evicted:
                    self._collective_events.append(
                        {"kind": "readmitted", "eid": eid, "group": ""})
            self._epoch += 1
            epoch = self._epoch
            if complete or (self._nodes and len(self._nodes) >= self.expected):
                self._complete.set()
            # re-admit grace: every slot that was liveness-tracked at the
            # crash is treated as alive NOW — its node has the full
            # dead-node window to reconnect and re-assert itself.  Slots
            # already dead / deregistered / retired pre-crash stay
            # untracked: re-seeding a finished node would get it
            # re-declared dead later and fail a healthy run.
            now = time.monotonic()
            for m in self._nodes:
                eid = int(m["executor_id"])
                if eid not in self._retired and eid not in untracked:
                    self._last_seen[eid] = now
            live = len(self._last_seen)
        # fresh journal generation anchored by a snapshot of the restored
        # state (carries the bumped epoch; keeps the replay tail O(delta))
        self._journal = journal_mod.Journal(self._journal_path)
        with self._lock:
            self._journal.snapshot(self._snapshot_state_locked())
        self._start_server(self._bind_host or "", self._port)
        self._stats_stop.clear()
        self._start_stats_thread()
        self._crashed.clear()
        telemetry.counter("coordinator.recoveries_total").inc()
        telemetry.gauge("coordinator.epoch").set(epoch)
        telemetry.gauge("coordinator.live_slots").set(live)
        ttrace.event("coordinator_replay", epoch=epoch,
                     records=len(records), nodes=len(self._nodes))
        ttrace.event("coordinator_up", epoch=epoch)
        logger.warning("coordinator RECOVERED at epoch %d (%d slot(s) "
                       "replayed, %d tail record(s)); clients re-admit over "
                       "the next heartbeats", epoch, len(self._nodes),
                       len(records))
        return epoch

    def _apply_record_locked(self, rec: dict, complete: bool,
                             untracked: set[int]) -> bool:
        """Replay one journal tail record into live state (``untracked``
        accumulates slots that must NOT get a liveness clock re-seeded);
        returns the updated formation-complete flag.  Purely-observational
        kinds (rendezvous lifecycle, ledger riders) replay as no-ops."""
        kind, d = rec.get("k"), rec.get("d") or {}
        if kind == "register":
            meta = dict(d["meta"])
            eid = int(meta["executor_id"])
            untracked.discard(eid)
            self._evicted.pop(eid, None)
            slot = next((m for m in self._nodes
                         if m["executor_id"] == eid), None)
            if d.get("replace") and slot is not None:
                slot.clear()
                slot.update(meta)
            elif slot is None:
                self._nodes.append(meta)
            if len(self._nodes) >= self.expected:
                complete = True
        elif kind == "dead":
            for eid in d.get("eids") or []:
                untracked.add(int(eid))
                self._incarnations[int(eid)] = \
                    self._incarnations.get(int(eid), 0) + 1
                # death wins over any probation/relearn record before it
                # (mirrors mark_dead and the silent-probation reap)
                self._evicted.pop(int(eid), None)
                self._readmit_pending.pop(int(eid), None)
        elif kind == "deregister":
            untracked.add(int(d["eid"]))
        elif kind == "open_slots":
            self.roles.extend((name, int(task))
                              for name, task in d.get("roles") or [])
            self.expected += len(d.get("roles") or [])
        elif kind == "cancel_slots":
            for eid in d.get("cancelled") or []:
                if int(eid) == len(self.roles) - 1:
                    self.roles.pop()
                    self.expected -= 1
            for eid in d.get("retired") or []:
                self._retire_replay_locked(int(eid))
        elif kind == "draining":
            self._draining.update(int(e) for e in d.get("eids") or [])
        elif kind == "retired":
            self._retire_replay_locked(int(d["eid"]))
        elif kind == "manifest":
            self._manifest = dict(d.get("manifest") or {})
        elif kind == "error":
            self._errors.append({"executor_id": d.get("executor_id"),
                                 "traceback": d.get("traceback", "")})
        elif kind == "serving":
            self._serving[str(d.get("gateway"))] = \
                [int(x) for x in d.get("replicas") or []]
        elif kind == "rollout":
            self._rollouts[str(d.get("gateway"))] = dict(d.get("state") or {})
        elif kind == "evict":
            eid = int(d["eid"])
            untracked.add(eid)
            self._incarnations[eid] = self._incarnations.get(eid, 0) + 1
            self._restore_evicted_locked(eid, str(d.get("group") or "train"))
            self._readmit_pending.pop(eid, None)
        elif kind == "readmit":
            eid = int(d["eid"])
            untracked.discard(eid)
            self._evicted.pop(eid, None)
            self._readmit_pending[eid] = self._incarnations.get(eid, 0)
        # rdv_open / rdv_close / rdv_abort / form / ledger: flight-record
        # riders — the generations they describe died with the crash and
        # re-form client-side at the next generation barrier.  The epoch
        # itself persists exclusively through snapshots (restore() writes
        # one immediately after every bump), never through tail records.
        return complete

    def _restore_evicted_locked(self, executor_id: int, group: str) -> None:
        """The ONE probation-entry constructor (live eviction AND crash
        replay — the two must never diverge on shape or clock semantics):
        the window starts NOW relative to this process's monotonic clock.
        For a journal replay that is conservative — the original eviction's
        clock died with the crash, and a failover never shortens a
        straggler's bench time — and the readmission health probe works
        unchanged either way."""
        from tensorflowonspark_tpu.utils.envtune import env_float

        probation = max(0.0, env_float("TOS_COLLECTIVE_PROBATION_SECS", 30.0))
        now = time.monotonic()
        self._evicted[executor_id] = {
            "group": group, "at": now, "last_ping": now,
            "probation_until": now + probation,
            "incarnation": self._incarnations.get(executor_id, 0)}

    def _retire_replay_locked(self, executor_id: int) -> None:
        self._incarnations[executor_id] = \
            self._incarnations.get(executor_id, 0) + 1
        self._draining.discard(executor_id)
        self._retired.add(executor_id)
        for m in self._nodes:
            if m["executor_id"] == executor_id:
                m["retired"] = True

    # -- serving replica registry (journal-backed) ----------------------------

    def note_serving_replicas(self, gateway: str, replicas: list[int]) -> None:
        """Record one router's healthy replica set (journaled, restored
        across a control-plane failover)."""
        with self._lock:
            self._serving[str(gateway)] = sorted(int(r) for r in replicas)
            self._log("serving", gateway=str(gateway),
                      replicas=self._serving[str(gateway)])

    def serving_replicas(self) -> dict[str, list[int]]:
        with self._lock:
            return {k: list(v) for k, v in self._serving.items()}

    def note_rollout(self, gateway: str, state: dict) -> None:
        """Record one gateway's staged-rollout state (journaled, restored
        across a control-plane failover): the full payload on start, then
        re-noted on every transition (promoted / rolled_back / aborted)."""
        with self._lock:
            self._rollouts[str(gateway)] = dict(state or {})
            self._log("rollout", gateway=str(gateway),
                      state=self._rollouts[str(gateway)])

    def rollout_state(self) -> dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._rollouts.items()}

    # -- gray-failure eviction (straggler suspicion -> quorum -> probation) ---

    @staticmethod
    def _resolve_blame_locked(reports: dict[int, dict[int, float]],
                              blamed: int) -> int | None:
        """Transitive blame resolution: a blamed member that is ITSELF
        filing suspicion against its own upstream is a pipeline victim
        (in a ring, everyone downstream of the straggler stalls in order),
        so the blame shifts upstream until it lands on a member that is
        blamed but not blaming.  A CYCLE — the walk revisiting a member —
        is the uniform-slowness signature (everyone waiting on everyone)
        and resolves to None: no clear straggler, nobody evicted.  The
        walk follows blame edges without excluding visited nodes (that
        exclusion would make every cycle terminate on an arbitrary member
        and falsely convict it; the revisit IS the terminator)."""
        seen: set[int] = set()
        cur = blamed
        while cur not in seen:
            seen.add(cur)
            upstream = [b for b, voters in reports.items() if cur in voters]
            if not upstream:
                return cur
            cur = min(upstream)  # deterministic walk on fan-out
        return None  # cycle: no clear straggler

    def _op_suspect(self, msg: dict) -> dict:
        """Record one survivor's suspicion vote and evaluate the quorum.

        Votes are keyed (group, suspect, voter) — refiling refreshes, never
        double-counts — cleared wholesale at each formation (a new
        generation is a fresh slate).  Zombie voters never reach here
        (standard incarnation fencing), so an evicted member cannot vote
        its survivors out in revenge."""
        from tensorflowonspark_tpu.utils.envtune import env_int

        group = str(msg.get("group") or "train")
        suspect = int(msg["suspect"])
        voter = int(msg.get("executor_id", -1))
        wait = float(msg.get("wait_secs") or 0.0)
        now = time.monotonic()
        evicted_now: int | None = None
        with self._lock:
            info = self._collective.get(group)
            members = list(info["members"]) if info else []
            reports = self._suspicions.setdefault(group, {})
            if suspect != voter and voter >= 0:
                reports.setdefault(suspect, {})[voter] = now
            # vote freshness: a live straggler's accusers renew every
            # second; a cold-start hiccup's lone vote must not linger and
            # later combine with an unrelated incident into a bogus quorum
            cutoff = now - _SUSPECT_VOTE_TTL
            for blamed in list(reports):
                voters_at = reports[blamed]
                for v in [v for v, t in voters_at.items() if t < cutoff]:
                    del voters_at[v]
                if not voters_at:
                    del reports[blamed]
            # resolve every report's transitive blame, then tally distinct
            # voters per FINAL suspect (a transferred vote still counts —
            # in a ring only the straggler's direct neighbor observes it
            # first-hand, so quorum must credit downstream victims too)
            tally: dict[int, set[int]] = {}
            for blamed, voters in reports.items():
                final = self._resolve_blame_locked(reports, blamed)
                if final is None:
                    continue
                tally.setdefault(final, set()).update(voters)
            survivors = max(1, len(members) - 1)
            quorum = env_int("TOS_COLLECTIVE_EVICT_QUORUM", 0) \
                or (survivors // 2 + 1)
            min_world = max(1, env_int("TOS_COLLECTIVE_MIN_WORLD", 1))
            votes = 0
            confirmed: set[tuple[str, int]] = set()
            for target in sorted(tally):
                voters = {v for v in tally[target] if v != target}
                if not (target in members and target not in self._evicted
                        and len(voters) >= quorum
                        and len(members) - 1 >= min_world):
                    continue
                key = (group, target)
                confirmed.add(key)
                pending_since = self._evict_pending.setdefault(key, now)
                if now - pending_since < _EVICT_CONFIRM_SECS:
                    # hold: a partial blame cycle (uniform slowness, votes
                    # still in flight) must get the chance to dissolve
                    continue
                del self._evict_pending[key]
                self._evict_locked(target, group, wait)
                evicted_now = target
                votes = len(voters)
                break
            # any hold whose quorum evaporated (the cycle completed, votes
            # aged out, membership changed) is dropped, not left armed
            for key in [k for k in self._evict_pending
                        if k[0] == group and k not in confirmed]:
                del self._evict_pending[key]
            evicted = sorted(e for e, d in self._evicted.items()
                             if d["group"] == group)
        if evicted_now is not None:
            telemetry.counter("collective.evictions_total").inc()
            telemetry.gauge("coordinator.live_slots").set(
                len(self._last_seen))
            ttrace.event("evicted", executor=evicted_now, group=group,
                         votes=votes, wait_secs=round(wait, 2))
            logger.error(
                "executor %d EVICTED from collective group %r at quorum "
                "(%d survivor vote(s); gray failure — slow or wedged, not "
                "dead); parked in probation, group continues at world %d",
                evicted_now, group, votes, len(members) - 1)
            # survivors may be blocked in a formation sized for the full
            # world — abort so they re-enter at the degraded count
            self._abort_rendezvous()
        return {"ok": True, "evicted": evicted, "quorum": quorum}

    def _evict_locked(self, executor_id: int, group: str,
                      wait_secs: float) -> None:
        """State half of a quorum eviction (caller holds ``_lock``): fence
        the incarnation, stop liveness tracking (the monitor must not ALSO
        declare a death — the process is alive, just benched), start the
        probation clock, and shrink the group's live membership."""
        self._last_seen.pop(executor_id, None)
        self._incarnations[executor_id] = \
            self._incarnations.get(executor_id, 0) + 1
        self._stats_history.pop(str(executor_id), None)
        self._readmit_pending.pop(executor_id, None)
        self._restore_evicted_locked(executor_id, group)
        info = self._collective.get(group)
        if info and executor_id in info["members"]:
            info["members"].remove(executor_id)
        sus = self._suspicions.get(group)
        if sus:
            sus.pop(executor_id, None)
            for voters in sus.values():
                voters.pop(executor_id, None)
        self._collective_events.append(
            {"kind": "evicted", "eid": executor_id, "group": group})
        self._eviction_log.append(
            {"eid": executor_id, "group": group,
             "wait_secs": round(wait_secs, 2)})
        self._log("evict", eid=executor_id, group=group)

    def _maybe_readmit_locked(self, executor_id: int) -> int | None:
        """Probation check on a fenced heartbeat from an evicted process:
        once the probation window expired — and the heartbeat arriving IS
        the health probe: the process is alive and can reach us — readmit
        the slot.  Returns the incarnation the process must adopt, or None
        while probation holds."""
        ent = self._evicted.get(executor_id)
        if ent is None:
            return None
        now = time.monotonic()
        ent["last_ping"] = now
        if now < ent["probation_until"]:
            return None
        del self._evicted[executor_id]
        inc = self._incarnations.get(executor_id, 0)
        # every stale client of the readmitted process relearns the bumped
        # incarnation on its next served round-trip (see _dispatch_inner)
        self._readmit_pending[executor_id] = inc
        self._last_seen[executor_id] = now
        self._readmits_total += 1
        self._collective_events.append(
            {"kind": "readmitted", "eid": executor_id,
             "group": ent["group"]})
        self._log("readmit", eid=executor_id)
        return inc

    def reap_silent_probation(self, heartbeat_timeout: float) -> list[int]:
        """Probation entries whose process went HEARTBEAT-SILENT: an
        evicted member is untracked by normal liveness (eviction popped its
        clock so the monitor never double-declares), so if it genuinely
        dies while benched nothing else would ever notice — the world would
        stay degraded forever with a ghost probation entry.  Called from
        the cluster monitor's tick: silent entries convert into ordinary
        deaths (fenced again, probation record dropped, journaled) and are
        returned for the caller to hand to the supervisor — which unparks
        and respawns, exactly as if the death had never hidden behind the
        eviction."""
        newly: list[int] = []
        now = time.monotonic()
        with self._lock:
            for eid in [e for e, d in self._evicted.items()
                        if now - d["last_ping"] > heartbeat_timeout]:
                del self._evicted[eid]
                self._incarnations[eid] = self._incarnations.get(eid, 0) + 1
                self._readmit_pending.pop(eid, None)
                self._collective_events.append(
                    {"kind": "probation_death", "eid": eid})
                self._log("dead", eids=[eid])
                newly.append(eid)
        for eid in newly:
            telemetry.counter("coordinator.deaths_total").inc()
            ttrace.event("death", executor=eid)
            logger.error("evicted node %d went silent in probation "
                         "(>%.0fs without a heartbeat); its bench death is "
                         "now an ordinary death", eid, heartbeat_timeout)
        return newly

    def evicted_members(self) -> dict[int, dict]:
        """Slots currently evicted to probation (diagnostic + tests)."""
        with self._lock:
            return {e: dict(d) for e, d in self._evicted.items()}

    def evictions(self) -> list[dict]:
        """Run-lifetime eviction log (survives readmission)."""
        with self._lock:
            return [dict(x) for x in self._eviction_log]

    def drain_collective_events(self) -> list[dict]:
        """One-shot drain of eviction/readmission events — the cluster
        monitor's feed for parking/unparking the supervisor and
        rebalancing the evicted slot's ledger work."""
        with self._lock:
            events, self._collective_events = self._collective_events, []
        return events

    # -- driver-side queries -------------------------------------------------

    def await_registrations(self, timeout: float | None = None) -> list[dict]:
        """Block until all nodes registered (``Server.await_reservations``)."""
        if not self._complete.wait(timeout):
            with self._lock:
                n = len(self._nodes)
            raise TimeoutError(f"only {n}/{self.expected} nodes registered within {timeout}s")
        return self.cluster_info()

    def cluster_info(self) -> list[dict]:
        with self._lock:
            return [dict(m) for m in sorted(self._nodes, key=lambda m: m["executor_id"])]

    def node_meta(self, executor_id: int) -> dict | None:
        """Current meta of one slot (a replacement rewrites it wholesale) —
        the single lookup the supervisor and the driver's data-plane recovery
        both use, so they can never disagree on a slot's host/port."""
        with self._lock:
            meta = next((m for m in self._nodes
                         if m["executor_id"] == executor_id), None)
            return dict(meta) if meta is not None else None

    def errors(self) -> list[dict]:
        with self._lock:
            return list(self._errors)

    def dead_nodes(self, heartbeat_timeout: float) -> list[int]:
        """Nodes whose heartbeat went silent (deregistered nodes excluded).
        Empty while the control plane is mid-failover: liveness was wiped
        with the crash, and declaring anyone dead before recovery re-seeds
        the clocks would fence every healthy reconnecting node."""
        if self._crashed.is_set():
            return []
        now = time.monotonic()
        with self._lock:
            return [i for i, t in self._last_seen.items() if now - t > heartbeat_timeout]

    def forget(self, executor_ids: list[int]) -> None:
        """Stop liveness-tracking nodes WITHOUT recording an error (used for
        non-fatal sidecar deaths, e.g. the evaluator)."""
        with self._lock:
            for i in executor_ids:
                self._last_seen.pop(i, None)

    def mark_dead(self, executor_ids: list[int],
                  record_error: bool = True) -> list[int]:
        """Declare heartbeat-silent nodes dead: stop tracking them, FENCE
        their incarnation (everything the old process sends from now on is
        rejected), and abort any in-flight barrier/reduce generation — the
        dead peer will never arrive, so waiters would only ride out their
        full timeout.  Idempotent: only nodes still being tracked are
        processed, so the monitor thread and shutdown's death-aware join
        racing on the same death act exactly once; the newly-declared ids
        are returned for the caller to escalate (or hand to the supervisor).

        ``record_error=False`` is the elastic path: a death the supervisor
        will recover from must not leave a fatal node error behind."""
        newly: list[int] = []
        with self._lock:
            for i in executor_ids:
                if self._last_seen.pop(i, None) is None:
                    continue
                newly.append(i)
                self._incarnations[i] = self._incarnations.get(i, 0) + 1
                # a readmitted-then-dead slot forfeits its relearn window
                # (and any straggler probation record): death wins
                self._readmit_pending.pop(i, None)
                self._evicted.pop(i, None)
                # a restarted slot's counters restart at 0: its rolling-stats
                # stream must restart with them, or the first post-restart
                # window computes negative rates against the old cumulatives
                self._stats_history.pop(str(i), None)
                if record_error:
                    self._errors.append({
                        "executor_id": i,
                        "traceback": (f"node {i} stopped heartbeating (process died "
                                      "or host unreachable); detected by driver "
                                      "monitor (SURVEY.md §5.3)"),
                    })
                    self._log("error", **self._errors[-1])
            if newly:
                self._log("dead", eids=newly)
            live = len(self._last_seen)
        if newly:
            telemetry.counter("coordinator.deaths_total").inc(len(newly))
            telemetry.gauge("coordinator.live_slots").set(live)
            for eid in newly:
                ttrace.event("death", executor=eid)
            self._abort_rendezvous()
        return newly

    def set_manifest(self, manifest: dict) -> None:
        """Publish the DIRECT-mode shard manifest (driver-side; replaced
        wholesale per train() call — JSON-serializable values only, the
        control plane is JSON-framed)."""
        with self._lock:
            self._manifest = dict(manifest)
            self._log("manifest", manifest=self._manifest)

    def manifest_state(self) -> dict:
        """Driver-side view of the published job manifest (the ``manifest``
        op's payload)."""
        with self._lock:
            return dict(self._manifest)

    def record_failure(self, executor_id: int, reason: str) -> None:
        """Driver-side synthesized node error (e.g. supervised restart budget
        exhausted) — surfaces through the same channel map_fun errors use."""
        with self._lock:
            self._errors.append({"executor_id": executor_id, "traceback": reason})
            self._log("error", executor_id=executor_id, traceback=reason)

    def is_tracked(self, executor_id: int) -> bool:
        """Whether the executor is currently liveness-tracked (alive)."""
        with self._lock:
            return executor_id in self._last_seen

    def registered_incarnation(self, executor_id: int) -> tuple[int, bool]:
        """(current incarnation, is currently liveness-tracked)."""
        with self._lock:
            return (self._incarnations.get(executor_id, 0),
                    executor_id in self._last_seen)

    def role_of(self, executor_id: int) -> str | None:
        """The slot's assigned role name ('chief'/'worker'/'evaluator'/
        'ingest'/...), or None for an unknown id — the role-aware half of
        the slot registry (executor ids index the role table by
        construction: ids are assigned in registration order)."""
        with self._lock:
            if 0 <= executor_id < len(self.roles):
                return self.roles[executor_id][0]
            return None

    def role_ids(self, job_name: str) -> list[int]:
        """Executor ids whose slot carries the named role."""
        with self._lock:
            return [i for i, (name, _t) in enumerate(self.roles)
                    if name == job_name]

    # -- elastic membership (cluster.resize) ---------------------------------

    def open_slots(self, count: int, job_name: str = "worker") -> list[int]:
        """Admit ``count`` NEW executor slots mid-run (scale-out): extend the
        role template and raise ``expected`` so the next ``count``
        registrations are assigned the fresh ids.  Returns the executor ids
        the newcomers will receive (registration order).  The initial
        formation barrier (``await_registrations``) is unaffected — it
        completed long ago; latecomers join a cluster that is already live.
        """
        if count < 1:
            raise ValueError("open_slots needs count >= 1")
        with self._lock:
            if not self._complete.is_set():
                raise RuntimeError("cannot open slots before the cluster formed")
            next_task = 1 + max(
                (t for name, t in self.roles if name == job_name), default=-1)
            new_ids = list(range(len(self.roles), len(self.roles) + count))
            new_roles = [(job_name, next_task + i) for i in range(count)]
            self.roles.extend(new_roles)
            self.expected += count
            self._log("open_slots", ids=new_ids,
                      roles=[[n, t] for n, t in new_roles])
        logger.info("opened %d new executor slot(s): ids %s", count, new_ids)
        return new_ids

    def await_slots(self, executor_ids: list[int], timeout: float) -> None:
        """Block until every listed slot has registered (scale-out join)."""
        deadline = time.monotonic() + timeout
        pending = set(executor_ids)
        while True:
            with self._lock:
                have = {m["executor_id"] for m in self._nodes}
            pending -= have
            if not pending:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"new node slot(s) {sorted(pending)} did not register "
                    f"within {timeout}s")
            time.sleep(0.1)

    def cancel_slots(self, executor_ids: list[int]) -> None:
        """Roll back :meth:`open_slots` for slots that never registered (a
        scale-out that timed out): pop the unfilled tail roles and lower
        ``expected`` so the NEXT scale-out's promised ids line up with
        registration order again.  ``_op_register`` assigns
        ``executor_id = len(_nodes)`` while ``open_slots`` promises ids from
        ``len(roles)`` — without this rollback one failed scale-out leaves
        them desynchronized forever (every later ``await_slots`` waits on
        ids no registration can ever be assigned).  Slots that DID register
        before the timeout are RETIRED in the same lock hold — doing the
        registered-check driver-side would race a register RPC landing in
        between, leaving a ghost that every default-count barrier/reduce
        waits on forever."""
        retired: list[int] = []
        with self._lock:
            taken = {m["executor_id"] for m in self._nodes}
            cancelled: list[int] = []
            # ids are assigned in registration order, so the unregistered
            # promised slots are always the tail of the role table
            for eid in sorted(executor_ids, reverse=True):
                if eid in taken:
                    live = self._retire_locked(eid)
                    retired.append(eid)
                    continue
                if eid == len(self.roles) - 1:
                    self.roles.pop()
                    self.expected -= 1
                    cancelled.append(eid)
            if cancelled or retired:
                self._log("cancel_slots", cancelled=cancelled, retired=retired)
        if retired:
            telemetry.gauge("coordinator.live_slots").set(live)
        for eid in retired:
            ttrace.event("retired", executor=eid)
            logger.info("executor %d retired (failed scale-out reaped it)",
                        eid)

    def mark_draining(self, executor_ids: list[int]) -> None:
        """Flag slots as DRAINING (scale-in in progress): still alive and
        serving their in-flight work, but no new assignments — and a death
        mid-drain finalizes the retirement instead of scheduling recovery."""
        with self._lock:
            self._draining.update(executor_ids)
            self._log("draining", eids=list(executor_ids))

    def draining_nodes(self) -> list[int]:
        with self._lock:
            return sorted(self._draining)

    def is_draining(self, executor_id: int) -> bool:
        with self._lock:
            return executor_id in self._draining

    def _retire_locked(self, executor_id: int) -> int:
        """State half of :meth:`retire_node` (caller holds ``_lock``);
        returns the live-slot count for the gauge."""
        self._last_seen.pop(executor_id, None)
        self._incarnations[executor_id] = \
            self._incarnations.get(executor_id, 0) + 1
        self._draining.discard(executor_id)
        self._retired.add(executor_id)
        self._readmit_pending.pop(executor_id, None)
        self._evicted.pop(executor_id, None)
        self._stats_history.pop(str(executor_id), None)
        for m in self._nodes:
            if m["executor_id"] == executor_id:
                m["retired"] = True
        return len(self._last_seen)

    def retire_node(self, executor_id: int) -> None:
        """Finalize an INTENTIONAL retirement (scale-in): stop liveness
        tracking with no error recorded, fence the incarnation so any
        straggler process is rejected, flag the slot meta ``retired`` (the
        executor_id is never reused), and drop the slot's rolling-stats
        stream so dashboards stop averaging a ghost."""
        with self._lock:
            live = self._retire_locked(executor_id)
            self._log("retired", eid=executor_id)
        telemetry.gauge("coordinator.live_slots").set(live)
        ttrace.event("retired", executor=executor_id)
        logger.info("executor %d retired (intentional scale-in)", executor_id)

    def is_retired(self, executor_id: int) -> bool:
        with self._lock:
            return executor_id in self._retired

    # -- telemetry (cluster metrics transport) -------------------------------

    def _merge_metrics_locked(self, executor_id: int, payload: dict) -> None:
        """Fold one node's heartbeat delta into its stored snapshot.  Every
        value in the payload is absolute-cumulative, so the merge is plain
        replacement per key; histogram ``recent`` samples append to a
        bounded per-(node, metric) pool for cluster-wide percentiles."""
        store = self._node_metrics.setdefault(
            executor_id, {"counters": {}, "gauges": {}, "histograms": {}})
        store["counters"].update(payload.get("counters") or {})
        store["gauges"].update(payload.get("gauges") or {})
        window_samples: dict[str, list[float]] = {}
        for name, d in (payload.get("histograms") or {}).items():
            store["histograms"][name] = {
                k: d.get(k) for k in ("count", "sum", "min", "max")}
            recent = d.get("recent")
            if recent:
                pool = self._hist_recent.setdefault(
                    executor_id, {}).setdefault(name, [])
                pool.extend(float(v) for v in recent)
                del pool[:-_HIST_RECENT_CAP]
                window_samples[name] = [float(v) for v in recent]
        # rolling-stats history: the heartbeat cadence IS the node's sample
        # clock — one timestamped cumulative snapshot per merge
        self._append_stats_locked(str(executor_id),
                                  dict(store["counters"]),
                                  dict(store["gauges"]), window_samples)

    # -- trace streams (span/flight-event transport) --------------------------

    def _merge_trace_locked(self, key: str, payload: dict) -> None:
        """Fold one process's heartbeat trace delta (spans + flight events +
        clock offset) into its bounded stream store."""
        store = self._node_trace.setdefault(
            key, {"spans": [], "events": [], "offset": None, "rtt": None,
                  "dropped": 0})
        spans = payload.get("spans")
        if spans:
            store["spans"].extend(spans)
            del store["spans"][:-_TRACE_SPAN_CAP]
        events = payload.get("events")
        if events:
            store["events"].extend(events)
            del store["events"][:-_TRACE_EVENT_CAP]
        if payload.get("offset") is not None:
            store["offset"] = float(payload["offset"])
            store["rtt"] = payload.get("rtt")
        if payload.get("dropped"):
            store["dropped"] = int(payload["dropped"])

    def _drain_driver_trace(self) -> None:
        """Accumulate this process's own tracer into the store under
        ``"driver"`` (the driver sends no heartbeats; offset is 0 by
        definition — its clock IS the merge timeline)."""
        delta = ttrace.collect_final()  # uncapped: no next beat ships the rest
        if delta is not None:
            delta["offset"] = 0.0
            with self._lock:
                self._merge_trace_locked("driver", delta)

    def clear_trace_streams(self) -> None:
        """Drop every accumulated trace stream (driver included) — phase
        isolation for benches that run several differently-shaped loads on
        one cluster and must not pool spans across them."""
        ttrace.collect_final()  # discard the driver tracer's whole backlog
        with self._lock:
            self._node_trace.clear()

    def trace_streams(self) -> dict[str, dict]:
        """Every process's trace stream, export-ready: ``{key: {"spans",
        "events", "clock_offset", ...}}`` (``trace_export.build_stream``
        shape).  Driver spans are drained into the store first."""
        self._drain_driver_trace()
        with self._lock:
            out: dict[str, dict] = {}
            for key, store in self._node_trace.items():
                out[key] = {"schema": "tos-trace-stream-v1", "node": key,
                            "clock_offset": store["offset"],
                            "spans": list(store["spans"]),
                            "events": list(store["events"]),
                            "dropped": store["dropped"]}
            return out

    # -- rolling-window stats (cluster.stats / the `statz` op) ----------------

    def _append_stats_locked(self, key: str, counters: dict, gauges: dict,
                             samples: dict[str, list[float]]) -> None:
        hist = self._stats_history.setdefault(key, [])
        hist.append((time.monotonic(), counters, gauges, samples))
        del hist[:-_STATS_HISTORY_CAP]

    def _stats_loop(self) -> None:
        while not self._stats_stop.wait(self._stats_interval):
            try:
                self._sample_driver_stats()
            except Exception:  # noqa: BLE001 - observability must not kill jobs
                logger.debug("driver stats sample failed", exc_info=True)
            # journal housekeeping rides the same tick: fold the tail into a
            # snapshot once it grows past the threshold (keeps recovery
            # replay O(delta) without adding a thread)
            self._maybe_snapshot()

    def _sample_driver_stats(self) -> None:
        """One driver history entry: cumulative counters + gauges + the
        histogram samples observed since the last tick (outbox drain — the
        driver's outboxes have no heartbeat consumer, so this is their one
        reader)."""
        if not telemetry.enabled():
            return
        reg = telemetry.get_registry()
        snap = reg.snapshot()
        samples = reg.drain_recent()
        with self._lock:
            self._append_stats_locked("driver", snap.get("counters") or {},
                                      snap.get("gauges") or {}, samples)

    def cluster_stats(self, window: float = 10.0) -> dict:
        """Rolling-window live stats — the signals replica autoscaling will
        consume, NOT run-lifetime aggregates: per-key windowed counter
        rates (qps and friends), windowed histogram percentiles (p50/p99
        over the last ``window`` seconds' samples only), and latest gauges
        (serve-queue depth, feed-queue occupancy).  ``"driver"`` carries
        the gateway-side view; node keys carry each node's own."""
        self._sample_driver_stats()  # stats() must be current, not ticked
        window = max(0.1, float(window))
        now = time.monotonic()
        with self._lock:
            history = {k: list(v) for k, v in self._stats_history.items()}
        out: dict = {"schema": "tos-statz-v1", "window_secs": window,
                     "streams": {}}
        for key, entries in history.items():
            stream = _window_stats(entries, now, window)
            if stream is not None:
                out["streams"][key] = stream
        driver = out["streams"].get("driver") or {}
        # headline: the exact autoscaler inputs, pre-plucked
        out["serving"] = {
            "qps": (driver.get("rates") or {}).get("serve.requests_total"),
            "p50_ms": _pct_ms(driver, "serve.request_secs", "p50"),
            "p99_ms": _pct_ms(driver, "serve.request_secs", "p99"),
            "queue_depth": (driver.get("gauges") or {}).get(
                "serve.queue_depth"),
            "inflight_batches": (driver.get("gauges") or {}).get(
                "serve.inflight_batches"),
            "replicas_healthy": (driver.get("gauges") or {}).get(
                "serve.replicas_healthy"),
            # "shrinking on purpose" vs "losing replicas": draining replicas
            # are a deliberate scale-in in progress, not a failure signal
            "replicas_draining": (driver.get("gauges") or {}).get(
                "serve.replicas_draining"),
            "draining_nodes": self.draining_nodes(),
            # the journal-backed registry: which replicas each router had
            # healthy as of its last publish (survives a coordinator
            # failover — the epoch shows whether one happened)
            "replica_registry": self.serving_replicas(),
            # staged rollouts: what each gateway has in flight (or last
            # resolved) — same journal-backed failover story
            "rollouts": self.rollout_state(),
            "coordinator_epoch": self._epoch,
            "feed_queue_depth": {
                key: (s.get("gauges") or {}).get("feed.queue_depth")
                for key, s in out["streams"].items() if key != "driver"},
        }
        ingest_ids = self.role_ids("ingest")
        if ingest_ids:
            out["ingest"] = self._ingest_stats_block(out["streams"],
                                                     ingest_ids)
        with self._lock:
            if self._collective or self._evicted or self._eviction_log:
                # the gray-failure block: which formations stand, who sits
                # in probation (and for how much longer), live suspicion
                # votes, and the run-lifetime eviction/readmit tallies —
                # the evidence operators read when a sync run degrades
                out["collective"] = {
                    "groups": {g: {"members": list(i["members"]),
                                   "generation": i["generation"]}
                               for g, i in self._collective.items()},
                    "evicted": {str(e): {
                        "group": d["group"],
                        "probation_secs_left": round(max(
                            0.0, d["probation_until"] - now), 1)}
                        for e, d in self._evicted.items()},
                    "suspicion_votes": {
                        g: {str(s): sorted(v) for s, v in sus.items()}
                        for g, sus in self._suspicions.items() if sus},
                    "evictions_total": len(self._eviction_log),
                    "readmits_total": self._readmits_total,
                }
        return out

    def _ingest_stats_block(self, streams: dict, ingest_ids: list[int]) -> dict:
        """The data-service tier's headline stats: per-worker decode MB/s
        and cache hit rate, plus the starved-trainer gauge — ONE surface
        the ingest autoscale policy and operators both read (satellite of
        the disaggregated-ingest tier)."""
        workers: dict[str, dict] = {}
        hits = misses = 0.0
        for eid in ingest_ids:
            s = streams.get(str(eid))
            if s is None:
                continue
            rates = s.get("rates") or {}
            gauges = s.get("gauges") or {}
            h = rates.get("ingest.cache_hits") or 0.0
            m = rates.get("ingest.cache_misses") or 0.0
            hits += h
            misses += m
            workers[str(eid)] = {
                "decode_mb_per_s": round(
                    (rates.get("ingest.bytes_read") or 0.0) / 1e6, 3),
                "rows_per_s": rates.get("ingest.records_read"),
                "forwarded_rows_per_s": rates.get("ingest.rows_forwarded"),
                "cache_hit_rate": (round(h / (h + m), 4)
                                   if (h + m) > 0 else None),
                "cache_bytes": gauges.get("ingest.cache_bytes"),
            }
        ingest_set = set(ingest_ids)
        trainer_keys = [key for key in streams
                        if key != "driver" and key.isdigit()
                        and int(key) not in ingest_set
                        and self.role_of(int(key)) != "evaluator"]
        starved = sum(
            1 for key in trainer_keys
            if ((streams[key].get("gauges") or {}).get("feed.queue_depth")
                == 0))
        return {
            "workers": workers,
            "cache_hit_rate": (round(hits / (hits + misses), 4)
                               if (hits + misses) > 0 else None),
            # trainers whose prefetch queue gauge reads EMPTY right now —
            # the tier-is-undersized signal the autoscale policy scales on
            "starved_trainers": starved,
            # windowed rate of empty feed polls across the trainer fleet
            # (feed.starved_polls — the counter form of the same signal)
            "trainer_starved_polls_per_s": round(sum(
                (streams[key].get("rates") or {}).get("feed.starved_polls")
                or 0.0 for key in trainer_keys), 3),
            "trainers_reporting": len(trainer_keys),
            "draining_workers": sorted(
                eid for eid in self.draining_nodes() if eid in ingest_set),
        }

    def cluster_metrics(self) -> dict:
        """Aggregated cluster snapshot (the ``metrics`` op / the
        ``cluster.metrics()`` driver API): per-node registry snapshots as
        last reported over heartbeats, plus THIS process's registry under
        ``"driver"`` (the coordinator runs in the driver, whose registry
        holds the feed-pump, supervisor, and rendezvous-span metrics)."""
        with self._lock:
            nodes: dict[str, dict] = {}
            for eid, snap in self._node_metrics.items():
                hists = {}
                for name, d in snap["histograms"].items():
                    d = dict(d)
                    recent = self._hist_recent.get(eid, {}).get(name)
                    if recent:
                        d["recent"] = list(recent)
                    hists[name] = d
                nodes[str(eid)] = {"counters": dict(snap["counters"]),
                                   "gauges": dict(snap["gauges"]),
                                   "histograms": hists}
        driver = telemetry.snapshot(include_samples=True)
        if any(driver.values()):
            nodes["driver"] = driver
        return telemetry.aggregate_snapshots(nodes)

    def _abort_rendezvous(self) -> None:
        """Abort every in-flight barrier/reduce generation (peer death)."""
        with self._lock:
            rdvs = list(self._rdv.values())
            self._rdv.clear()
        for rdv in rdvs:
            with rdv.cond:
                if not rdv.done:
                    rdv.aborted = True
                    rdv.cond.notify_all()

    def signal_stop(self) -> None:
        """Make subsequent heartbeats tell nodes to stop (zombie-free teardown)."""
        self._stop_flag.set()

    # -- request dispatch ----------------------------------------------------

    def _is_fenced(self, msg: dict) -> bool:
        """True when the message comes from a stale incarnation of a slot
        that was declared dead (the sender is a zombie predecessor of a
        restarted node).  Messages that carry no incarnation pass — only a
        peer that knows the fencing protocol can be fenced by it, and a
        slot that never died has incarnation 0 which every fresh client
        stamps anyway."""
        eid, inc = msg.get("executor_id"), msg.get("incarnation")
        if eid is None or inc is None:
            return False
        with self._lock:
            return int(inc) < self._incarnations.get(int(eid), 0)

    def _dispatch(self, msg: dict) -> dict:
        # chaos seam (`kill_coordinator:after_ops=N`): the Nth control-plane
        # request crashes the server BEFORE being served — its reply dies
        # with the connection, exactly like a request in flight at a real
        # coordinator death
        if faultinject.coordinator_op():
            self.crash()
            return {"ok": False, "error": "coordinator crashed (fault injection)"}
        if self._crashed.is_set():
            # a request raced the crash on a not-yet-severed socket: refuse
            # it rather than serving wiped state; the client's reconnect
            # backoff owns riding out the restart window
            return {"ok": False, "error": "coordinator is mid-failover; retry"}
        resp = self._dispatch_inner(msg)
        # coordinator epoch rides EVERY reply: clients detect a failover by
        # the bump and re-assert (idempotent ops retry; rendezvous re-form)
        resp.setdefault("epoch", self._epoch)
        return resp

    def _readmit_relearn(self, msg: dict) -> int | None:
        """The post-eviction identity hand-back: once a parked process is
        READMITTED, its slot's incarnation was bumped past every client the
        process already holds (main, heartbeat, consensus, collective) —
        and there is no replacement process to race, because eviction parks
        instead of respawning.  So a stale-incarnation message from a
        readmit-pending slot is served NORMALLY and its reply carries
        ``readmit_incarnation``: every client self-heals on its next
        round-trip.  Returns the incarnation to advertise, or None (no
        relearn in progress / the sender already caught up)."""
        eid, inc = msg.get("executor_id"), msg.get("incarnation")
        if eid is None or inc is None:
            return None
        with self._lock:
            pend = self._readmit_pending.get(int(eid))
            if pend is None or int(inc) != pend - 1:
                # No relearn in progress, this client already caught up, or
                # the sender is an OLDER incarnation than the one evicted —
                # i.e. a pre-eviction zombie from an ordinary death/respawn
                # cycle, which must stay fenced (only the readmitted
                # process's clients hold exactly pend-1).  The window stays
                # OPEN for those clients (main/consensus/collective relearn
                # at their own pace) and closes only when the slot dies,
                # retires, or re-evicts — safe, because eviction never
                # respawns: the readmitted process is the slot's only owner.
                return None
            return pend

    def _fenced_reply(self, op: str, msg: dict) -> dict:
        """Replies for a fenced (stale-incarnation) sender.

        Two populations land here: a dead slot's zombie predecessor
        (classic fencing — heartbeats answer stop so it winds down) and an
        EVICTED-but-alive gray member parked in probation.  The evicted
        process must NOT stop: its heartbeats are the probation health
        probe, and the first one past the probation window readmits the
        slot (handing back a fresh incarnation for every stale client to
        adopt)."""
        eid = int(msg.get("executor_id", -1))
        sender_inc = int(msg.get("incarnation", -1))
        with self._lock:
            ent = self._evicted.get(eid)
            # The probation probe is ONLY the evicted process itself: its
            # clients hold exactly the pre-eviction incarnation.  An even
            # older zombie (a predecessor from an ordinary death/respawn
            # before the eviction) must neither refresh the probe clock —
            # it would mask a real probation death from the reaper — nor,
            # at expiry, be handed the slot: it gets the classic fenced
            # stop reply below.
            if ent is not None and sender_inc != ent["incarnation"] - 1:
                ent = None
            if ent is not None and op == "heartbeat":
                inc = self._maybe_readmit_locked(eid)
                if inc is not None:
                    readmitted = True
                else:
                    readmitted = False
                    remaining = max(
                        0.0, ent["probation_until"] - time.monotonic())
                # the benched process is the slot's legitimate owner: its
                # telemetry/trace riders merge as usual — the probation
                # window is exactly the stretch a postmortem needs (the
                # classic fenced-ZOMBIE drop below stays a drop)
                if msg.get("metrics"):
                    self._merge_metrics_locked(eid, msg["metrics"])
                if msg.get("trace"):
                    self._merge_trace_locked(str(eid), msg["trace"])
            evicted = ent is not None
        if evicted and op == "heartbeat":
            if readmitted:
                telemetry.counter("collective.readmits_total").inc()
                ttrace.event("readmitted", executor=eid)
                logger.warning(
                    "executor %d passed its probation health probe; "
                    "READMITTED at incarnation %d — the group grows back "
                    "at its next generation barrier", eid, inc)
                return {"ok": True, "stop": self._stop_flag.is_set(),
                        "evicted": False, "readmit_incarnation": inc,
                        "now": time.monotonic()}
            return {"ok": True, "stop": self._stop_flag.is_set(),
                    "evicted": True,
                    "probation_secs": round(remaining, 3),
                    "now": time.monotonic()}
        if op == "heartbeat":
            return {"ok": True, "stop": True, "fenced": True}
        if op in ("barrier", "reduce"):
            if evicted:
                return {"ok": False, "fenced": True, "evicted": True,
                        "error": (f"executor {eid} was evicted from "
                                  f"collective group {ent['group']!r} (gray "
                                  "failure) and is parked in probation; "
                                  "rejoin follows readmission")}
            return {"ok": False, "fenced": True,
                    "error": (f"stale incarnation {msg.get('incarnation')} for "
                              f"executor {msg.get('executor_id')}: slot was "
                              "declared dead and re-fenced")}
        return {"ok": True, "fenced": True}

    def _dispatch_inner(self, msg: dict) -> dict:
        op = msg.get("op")
        try:
            ep = msg.get("coordinator_epoch")
            if ep is not None and int(ep) < self._epoch \
                    and op in ("barrier", "reduce"):
                # Epoch fencing, the failover twin of incarnation fencing: a
                # barrier/reduce composed against a pre-crash epoch belongs
                # to a generation that died with the crash — joining a live
                # one could satisfy (and corrupt) a rendezvous its sender
                # never meant.  Idempotent ops pass: the reply's epoch
                # re-syncs the client.
                return {"ok": False, "stale_epoch": True,
                        "error": (f"request from coordinator epoch {ep} fenced "
                                  f"(current epoch {self._epoch}): the control "
                                  "plane restarted; re-sync and retry")}
            relearn = self._readmit_relearn(msg)
            if op != "register" and relearn is None and self._is_fenced(msg):
                # TF-Replicator-style generation fencing: the zombie must
                # never influence live state — with the one carve-out of a
                # readmitted-from-eviction process relearning its identity
                # (relearn above; there is no replacement to race).
                return self._fenced_reply(op, msg)
            resp = self._serve_op(op, msg)
            if relearn is not None and resp.get("ok"):
                resp["readmit_incarnation"] = relearn
            return resp
        except Exception as e:  # keep the server alive on handler bugs
            logger.exception("coordinator op %s failed", op)
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    def _serve_op(self, op: str, msg: dict) -> dict:
        if op == "register":
            return self._op_register(msg)
        if op == "query":
            return {"ok": True, "complete": self._complete.is_set(), "count": len(self._nodes)}
        if op == "cluster_info":
            if not self._complete.is_set():
                return {"ok": False, "error": "cluster incomplete"}
            return {"ok": True, "nodes": self.cluster_info()}
        if op == "barrier":
            msg = dict(msg, kind="all", value=True)
            return self._op_reduce(msg)
        if op == "reduce":
            return self._op_reduce(msg)
        if op == "update_meta":
            with self._lock:
                for m in self._nodes:
                    if m["executor_id"] == msg["executor_id"]:
                        m.update(msg.get("patch") or {})
            return {"ok": True}
        if op == "heartbeat":
            with self._lock:
                # a deregistered (cleanly exited) node sends no further
                # beats; never resurrect one from a late in-flight ping —
                # and never let such a ping's metric delta overwrite the
                # FINAL snapshot the deregister already merged (the
                # heartbeat thread races teardown on its own connection)
                if msg["executor_id"] in self._last_seen:
                    self._last_seen[msg["executor_id"]] = time.monotonic()
                    if msg.get("metrics"):
                        self._merge_metrics_locked(int(msg["executor_id"]),
                                                   msg["metrics"])
                # trace deltas are append-only (spans/events, never a
                # snapshot overwrite), so keep one even from a ping that
                # raced deregister — it holds spans the final delta
                # doesn't, and the node-side restore path never sees a
                # reply that said ok.  Zombies never reach here (fenced).
                if msg.get("trace"):
                    self._merge_trace_locked(str(msg["executor_id"]),
                                             msg["trace"])
            # "now" is this process's monotonic clock at reply build —
            # the client's RTT-midpoint clock-offset estimate hangs off
            # it (trace timeline merging, trace_export.py)
            return {"ok": True, "stop": self._stop_flag.is_set(),
                    "now": time.monotonic()}
        if op == "metrics":
            return {"ok": True, "snapshot": self.cluster_metrics()}
        if op == "statz":
            return {"ok": True, "stats": self.cluster_stats(
                float(msg.get("window") or 10.0))}
        if op == "manifest":
            with self._lock:
                return {"ok": True, "manifest": dict(self._manifest)}
        if op == "deregister":
            # node exiting deliberately (map_fun done, or error already
            # reported): stop liveness tracking so the driver's dead-node
            # monitor never flags a clean exit as a death.  The final
            # metrics snapshot rides along — work done after the last
            # heartbeat must still reach the cluster view.
            with self._lock:
                if self._last_seen.pop(msg["executor_id"], None) is not None:
                    self._log("deregister",
                              eid=int(msg["executor_id"]))
                if msg.get("metrics"):
                    self._merge_metrics_locked(int(msg["executor_id"]),
                                               msg["metrics"])
                if msg.get("trace"):
                    self._merge_trace_locked(str(msg["executor_id"]),
                                             msg["trace"])
            return {"ok": True}
        if op == "error":
            with self._lock:
                self._errors.append({"executor_id": msg.get("executor_id"), "traceback": msg.get("traceback", "")})
            logger.error("node %s reported error:\n%s", msg.get("executor_id"), msg.get("traceback", ""))
            return {"ok": True}
        if op == "suspect":
            return self._op_suspect(msg)
        if op == "cworld":
            # effective-world adjudication for a degraded formation:
            # nominal world minus the group's members parked in probation
            group = str(msg.get("group") or "train")
            nominal = int(msg.get("world") or 0)
            with self._lock:
                ev = sorted(e for e, d in self._evicted.items()
                            if d["group"] == group)
            return {"ok": True, "evicted": ev,
                    "effective": (max(1, nominal - len(ev))
                                  if nominal else None)}
        if op == "stop":
            self._stop_flag.set()
            return {"ok": True}
        if op == "bye":
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _op_register(self, msg: dict) -> dict:
        meta = dict(msg.get("meta") or {})
        replace = msg.get("replace")
        if replace is not None:
            return self._op_register_replacement(int(replace), meta)
        with self._lock:
            if len(self._nodes) >= self.expected:
                # complete AND no opened scale-out slots outstanding
                return {"ok": False, "error": "cluster already complete"}
            executor_id = len(self._nodes)
            job_name, task_index = self.roles[executor_id]
            meta.update(executor_id=executor_id, job_name=job_name, task_index=task_index)
            self._nodes.append(meta)
            self._log("register", meta=dict(meta), replace=False)
            self._last_seen[executor_id] = time.monotonic()
            incarnation = self._incarnations.get(executor_id, 0)
            if len(self._nodes) == self.expected:
                self._complete.set()
            live = len(self._last_seen)
        telemetry.gauge("coordinator.live_slots").set(live)
        logger.info("registered node %d as %s:%d (%s)", executor_id, job_name, task_index, meta.get("host"))
        return {"ok": True, "executor_id": executor_id, "job_name": job_name,
                "task_index": task_index, "expected": self.expected,
                "incarnation": incarnation}

    def _op_register_replacement(self, executor_id: int, meta: dict) -> dict:
        """Re-register a supervised restart into its predecessor's slot.

        The slot keeps its executor_id/role (SPMD layout is positional), the
        meta (host/data_port/pid) is replaced wholesale, and the node adopts
        the slot's CURRENT incarnation — already bumped past the dead
        predecessor by ``mark_dead``, so the zombie stays fenced while the
        replacement is fully live."""
        with self._lock:
            if not self._complete.is_set():
                return {"ok": False, "error": "cannot replace before the cluster formed"}
            slot = next((m for m in self._nodes if m["executor_id"] == executor_id), None)
            if slot is None:
                return {"ok": False, "error": f"no executor slot {executor_id} to replace"}
            if executor_id in self._retired:
                # a supervised respawn racing retire_node: the slot was
                # scaled in while the replacement booted — admitting it
                # would resurrect a ghost member nobody feeds or retires
                return {"ok": False, "error": (f"executor slot {executor_id} "
                                               "was retired (scale-in); "
                                               "refusing replacement")}
            if executor_id in self._evicted:
                # an evicted slot's PROCESS IS ALIVE (parked in probation);
                # registering a replacement would split-brain the slot —
                # eviction parks, it never respawns
                return {"ok": False, "error": (f"executor slot {executor_id} "
                                               "is evicted to probation (its "
                                               "process is alive); refusing "
                                               "replacement")}
            if executor_id in self._last_seen:
                return {"ok": False, "error": (f"executor {executor_id} is still "
                                               "liveness-tracked; refusing replacement")}
            job_name, task_index = self.roles[executor_id]
            meta.update(executor_id=executor_id, job_name=job_name, task_index=task_index)
            slot.clear()
            slot.update(meta)
            self._log("register", meta=dict(meta), replace=True)
            self._last_seen[executor_id] = time.monotonic()
            incarnation = self._incarnations.get(executor_id, 0)
            live = len(self._last_seen)
        telemetry.gauge("coordinator.live_slots").set(live)
        logger.info("replacement registered for node %d as %s:%d (%s, incarnation %d)",
                    executor_id, job_name, task_index, meta.get("host"), incarnation)
        return {"ok": True, "executor_id": executor_id, "job_name": job_name,
                "task_index": task_index, "expected": self.expected,
                "incarnation": incarnation}

    def _op_reduce(self, msg: dict) -> dict:
        name, kind, value = msg["name"], msg.get("kind", "gather"), msg.get("value")
        timeout = msg.get("timeout", 300.0)
        # Participant count may be a subgroup (e.g. feedable nodes excluding
        # the evaluator); every participant must pass the same count.
        count = msg.get("count")
        with self._lock:
            if not count:
                # Default = LIVE membership: expected only ever grows, and
                # retired slots (scale-in) are gone for good — a barrier at
                # the pre-resize count would wait on ghosts forever.
                count = self.expected - len(self._retired)
            count = int(count)
            rdv = self._rdv.get(name)
            # done/aborted generations are popped by whoever finished them,
            # but guard anyway: never join a finished generation.
            if rdv is None or rdv.done or rdv.aborted:
                rdv = self._rdv[name] = _Rendezvous(count)
                self._log("rdv_open", sync=False, name=name, count=count,
                          kind=kind)
            elif rdv.count != count:
                return {"ok": False, "error": f"reduce {name!r}: conflicting participant counts "
                                              f"({rdv.count} vs {count})"}
        with rdv.cond:
            if rdv.done or rdv.aborted:
                # generation finished between registry lookup and here; the
                # caller raced a completed round — treat as a fresh failure
                # rather than returning another round's result.
                return {"ok": False, "error": f"barrier/reduce {name!r} generation closed; retry"}
            rdv.values.append(value)
            if len(rdv.values) == rdv.count:
                rdv.result = _reduce(kind, rdv.values)
                rdv.done = True
                # consensus latency span: generation open -> last arrival
                # (the SURVEY §5.8-3 number ops watch when scaling steps)
                telemetry.histogram("coordinator.rendezvous_secs").observe(
                    time.monotonic() - rdv.t0)
                with self._lock:
                    if self._rdv.get(name) is rdv:
                        del self._rdv[name]
                    self._log("rdv_close", sync=False, name=name, kind=kind)
                    if kind == "form":
                        # collective membership is control-plane state worth
                        # keeping: the postmortem (and a future cold-start
                        # resume) can see who stood at which generation
                        member_eids = [int(m["eid"])
                                       for m in rdv.result["members"]]
                        self._log("form", name=name, members=member_eids,
                                  generation=rdv.result["generation"],
                                  step=rdv.result["step"])
                        # live membership for the gray-failure machinery:
                        # suspicion quorums count against THIS formation,
                        # and a fresh generation is a fresh slate of votes
                        gname = name
                        if gname.startswith("cg.") and gname.endswith(".form"):
                            gname = gname[3:-5]
                        self._collective[gname] = {
                            "members": member_eids,
                            "generation": int(rdv.result["generation"])}
                        self._suspicions.pop(gname, None)
                        for key in [k for k in self._evict_pending
                                    if k[0] == gname]:
                            del self._evict_pending[key]
                rdv.cond.notify_all()
            else:
                deadline = time.monotonic() + timeout
                while not (rdv.done or rdv.aborted):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._stop_flag.is_set():
                        rdv.aborted = True
                        with self._lock:
                            if self._rdv.get(name) is rdv:
                                del self._rdv[name]
                            self._log("rdv_abort", sync=False, name=name)
                        rdv.cond.notify_all()
                        return {"ok": False, "error": f"barrier/reduce {name!r} timed out"}
                    rdv.cond.wait(min(remaining, 0.5))
                if rdv.aborted:
                    return {"ok": False, "error": f"barrier/reduce {name!r} aborted (peer timed out)"}
            return {"ok": True, "result": rdv.result}


class CoordinatorClient:
    """Node-side client (reference ``reservation.Client``), persistent socket.

    Failover behaviour (ISSUE 13): every reply carries the coordinator
    EPOCH; a bump means the control plane crashed and recovered from its
    journal.  On a broken connection the client redials with backoff
    (``TOS_CONNECT_ATTEMPTS``) and transparently retries IDEMPOTENT ops
    (heartbeat, queries, update_meta, deregister, error); a barrier/reduce
    instead raises :class:`CoordinatorRestarted` after reconnecting — its
    rendezvous generation died with the crash, and whether to re-enter (a
    fresh generation) is the caller's SPMD-consistency decision, never the
    transport's.
    """

    def __init__(self, address: tuple[str, int], connect_timeout: float = 30.0,
                 authkey: bytes | None = None,
                 connect_attempts: int | None = None,
                 call_timeout: float | None = None):
        from tensorflowonspark_tpu.utils.envtune import env_int

        self.address = (address[0], int(address[1]))
        self._lock = tos_named_lock("coordinator.client._lock")
        self._authkey = authkey
        self._connect_timeout = connect_timeout
        # Backoff on the dial (TOS_CONNECT_ATTEMPTS): a single-shot connect
        # fails hard during a coordinator restart window or early-boot race;
        # the elastic layer leans on clients riding that window out.
        self._connect_attempts = (env_int("TOS_CONNECT_ATTEMPTS", 3)
                                  if connect_attempts is None
                                  else int(connect_attempts))
        # None = block indefinitely (barriers/reduces legitimately wait
        # minutes).  The heartbeat channel passes a bound so a BLACKHOLED
        # coordinator (packets dropped, not refused) surfaces as a timeout
        # the self-fence logic can count, instead of wedging the liveness
        # thread forever — the zombie asymmetry ISSUE 13 closes.
        self._call_timeout = call_timeout
        self._sock = self._dial()
        self._gen = 0
        self._executor_id: int | None = None
        self._incarnation = 0
        # last coordinator epoch observed on a reply (None until the first
        # round-trip); a bump is flight-recorded once per change
        self.epoch: int | None = None
        # True when the last heartbeat reply said this slot is EVICTED to
        # probation (gray failure) — the node's heartbeat loop parks on it
        self.last_evicted = False
        # latest clock estimate from a heartbeat round-trip (driver-mono =
        # local-mono + offset, midpoint method); the node's heartbeat loop
        # feeds the best of these to the tracer for timeline merging
        self.last_clock_offset: float | None = None
        self.last_rtt: float | None = None

    def _dial(self) -> socket.socket:
        from tensorflowonspark_tpu.utils.net import connect_with_backoff

        sock = connect_with_backoff(
            self.address, timeout=self._connect_timeout,
            attempts=self._connect_attempts)
        if self._authkey is not None:
            from tensorflowonspark_tpu.utils.net import hmac_handshake_client

            # connect_timeout still governs the socket here, so a server
            # that never sends a nonce (authkey=None config mismatch) fails
            # within it rather than hanging; close the fd on ANY failure.
            try:
                accepted = hmac_handshake_client(sock, self._authkey)
            except (OSError, ConnectionError) as e:
                sock.close()
                raise ConnectionError(
                    f"coordinator handshake failed ({e}); authkey mismatch or "
                    "unauthenticated server?") from e
            if not accepted:
                sock.close()
                raise ConnectionError("coordinator rejected authkey")
        sock.settimeout(self._call_timeout)
        return sock

    def _reconnect_locked(self) -> None:
        """Redial (with backoff) after a broken connection — the coordinator
        may be mid-supervised-restart; caller holds ``_lock``."""
        with contextlib.suppress(OSError):
            self._sock.close()
        self._sock = self._dial()

    def set_identity(self, executor_id: int, incarnation: int = 0) -> None:
        """Adopt the registration-assigned identity: every subsequent message
        is stamped with (executor_id, incarnation) so the coordinator can
        fence this client the moment its slot is declared dead and handed to
        a replacement."""
        self._executor_id = int(executor_id)
        self._incarnation = int(incarnation)

    @property
    def incarnation(self) -> int:
        """The incarnation this client currently stamps — bumped in place
        when a readmission reply hands back ``readmit_incarnation``."""
        return self._incarnation

    def _stamp(self, msg: dict) -> dict:
        if self._executor_id is not None and msg.get("op") != "register":
            msg.setdefault("executor_id", self._executor_id)
            msg.setdefault("incarnation", self._incarnation)
        if self.epoch is not None:
            # epoch fencing: the server rejects barrier/reduce requests
            # composed against a pre-crash epoch (stale_epoch reply)
            msg.setdefault("coordinator_epoch", self.epoch)
        return msg

    def _note_epoch(self, resp: dict) -> None:
        ep = resp.get("epoch")
        if ep is None:
            return
        ep = int(ep)
        if self.epoch is not None and ep > self.epoch:
            ttrace.event("coordinator_epoch", epoch=ep,
                         executor=self._executor_id)
            logger.warning("coordinator epoch %d -> %d: the control plane "
                           "restarted; re-asserting over this connection",
                           self.epoch, ep)
        if self.epoch is None or ep > self.epoch:
            self.epoch = ep

    def _call(self, msg: dict, retry: bool = False) -> dict:
        """One request/reply round-trip.  On a broken connection the client
        reconnects with backoff either way; ``retry=True`` (idempotent ops
        only) then resends the request, while ``retry=False`` raises
        :class:`CoordinatorRestarted` — a non-idempotent request may have
        been served before the connection died, and blind replay could
        join (and corrupt) a fresh rendezvous generation."""
        msg = self._stamp(msg)
        with self._lock:
            try:
                _send_msg(self._sock, msg)
                resp = _recv_msg(self._sock)
            except (ConnectionError, OSError, ValueError) as e:
                try:
                    self._reconnect_locked()
                except Exception as e2:
                    raise ConnectionError(
                        f"coordinator unreachable ({e2}); original failure: "
                        f"{e}") from e
                if not retry:
                    raise CoordinatorRestarted(
                        f"control-plane connection lost mid-call ({e}); "
                        "reconnected, but a non-idempotent op is never "
                        "replayed — re-enter at the caller's barrier") from e
                _send_msg(self._sock, msg)
                resp = _recv_msg(self._sock)
        self._note_epoch(resp)
        ri = resp.get("readmit_incarnation")
        if ri is not None and self._executor_id is not None \
                and int(ri) > self._incarnation:
            # the slot was evicted (gray failure) and READMITTED: the
            # coordinator hands back the bumped incarnation on served
            # replies so every stale client of the process self-heals
            logger.warning("executor %d readmitted after eviction; this "
                           "client adopts incarnation %d",
                           self._executor_id, int(ri))
            self._incarnation = int(ri)
        return resp

    def _check(self, resp: dict) -> dict:
        if not resp.get("ok"):
            if resp.get("stale_epoch"):
                raise CoordinatorRestarted(
                    f"coordinator error: {resp.get('error')}")
            if resp.get("fenced"):
                raise CoordinatorFenced(
                    f"coordinator error: {resp.get('error')}")
            raise RuntimeError(f"coordinator error: {resp.get('error')}")
        return resp

    def register(self, meta: dict, replace: int | None = None) -> dict:
        """Register this node; returns assigned identity {executor_id,
        job_name, task_index, incarnation}.  ``replace`` re-registers a
        supervised restart into the named (dead) executor slot."""
        msg: dict = {"op": "register", "meta": meta}
        if replace is not None:
            msg["replace"] = int(replace)
        return self._check(self._call(msg))

    def await_cluster(self, timeout: float | None = None, poll: float = 0.1) -> list[dict]:
        """Poll QUERY until all nodes registered, then fetch cluster info (QINFO)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._check(self._call({"op": "query"}, retry=True))["complete"]:
                return self._check(self._call({"op": "cluster_info"}, retry=True))["nodes"]
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("cluster did not complete in time")
            time.sleep(poll)

    def barrier(self, name: str, executor_id: int, timeout: float = 300.0,
                count: int | None = None) -> None:
        self._check(self._call({"op": "barrier", "name": name, "executor_id": executor_id,
                                "timeout": timeout, "count": count}))

    def reduce(self, name: str, value: Any, kind: str = "gather", timeout: float = 300.0,
               count: int | None = None) -> Any:
        """Control-plane all-reduce; ``count`` scopes it to a subgroup of nodes."""
        return self._check(
            self._call({"op": "reduce", "name": name, "value": value, "kind": kind,
                        "timeout": timeout, "count": count})
        )["result"]

    def reduce_begin(self, name: str, value: Any, kind: str = "gather",
                     timeout: float = 300.0, count: int | None = None):
        """Pipelined reduce: send this participant's value NOW, collect the
        result later via the returned zero-arg callable.

        Lets a caller overlap the control-plane round-trip with its own work
        (e.g. a training step) instead of blocking one RTT per global step
        (SURVEY.md §5.8-3).  The client lock is HELD from begin to finish —
        strict request-reply ordering on the socket — so run pipelined
        reduces on a dedicated connection, never on a client shared with
        other mid-flight operations."""
        self._lock.acquire()
        sent = False
        try:
            _send_msg(self._sock, self._stamp(
                {"op": "reduce", "name": name, "value": value,
                 "kind": kind, "timeout": timeout, "count": count}))
            sent = True
        finally:
            if not sent:
                self._lock.release()

        def finish() -> Any:
            try:
                return self._check(_recv_msg(self._sock))["result"]
            finally:
                self._lock.release()

        return finish

    def collective_form(self, name: str, member: dict, count: int,
                        timeout: float = 300.0) -> dict:
        """Collective-group formation rendezvous (the ``form`` reduce kind):
        block until ``count`` members contributed their endpoint dicts, then
        return the shared view ``{"members": [...eid-sorted...],
        "generation": max, "step": max}``.  The caller's rank is the index
        of its eid in ``members``.  Incarnation fencing applies: a fenced
        zombie's join is rejected, so a dead predecessor can never occupy
        its replacement's seat at the barrier."""
        return self.reduce(name, dict(member), kind="form", timeout=timeout,
                           count=count)

    def suspect(self, group: str, suspect_eid: int,
                wait_secs: float) -> dict:
        """File one straggler-suspicion vote against ``suspect_eid`` (the
        peer this node has been waiting on).  Idempotent per voter —
        refiling refreshes the vote — so it retries transparently; the
        reply carries the group's current ``evicted`` list, which doubles
        as the "is my round doomed" poll."""
        return self._check(self._call(
            {"op": "suspect", "group": str(group),
             "suspect": int(suspect_eid),
             "wait_secs": float(wait_secs)}, retry=True))

    def collective_world(self, group: str, world: int) -> dict:
        """Effective-world adjudication for a degraded formation:
        ``{"effective": nominal - evicted, "evicted": [...]}``."""
        return self._check(self._call(
            {"op": "cworld", "group": str(group), "world": int(world)},
            retry=True))

    def next_collective_name(self, prefix: str) -> str:
        """Locally-generated unique name; callers must use it SPMD-consistently."""
        self._gen += 1
        return f"{prefix}:{self._gen}"

    def update_meta(self, executor_id: int, patch: dict) -> None:
        """Patch this node's registered metadata (e.g. tensorboard URL)."""
        self._check(self._call({"op": "update_meta", "executor_id": executor_id, "patch": patch}, retry=True))

    def heartbeat(self, executor_id: int, metrics: dict | None = None,
                  trace: dict | None = None) -> bool:
        """Send liveness ping; returns True if the driver asked us to stop.
        ``metrics`` piggybacks a compact telemetry delta
        (``telemetry.collect_changed``) and ``trace`` a span/flight-event
        delta (``telemetry.trace.collect_delta``) on the ping — the cluster
        observability transport costs no extra round-trips.  Each ping also
        refreshes ``last_clock_offset``/``last_rtt`` from the reply's
        server clock (NTP-style midpoint), the estimate trace export uses
        to merge per-node span streams onto the driver timeline."""
        msg: dict = {"op": "heartbeat", "executor_id": executor_id}
        if metrics:
            msg["metrics"] = metrics
        if trace:
            msg["trace"] = trace
        t0 = time.monotonic()
        resp = self._check(self._call(msg, retry=True))
        t1 = time.monotonic()
        server_now = resp.get("now")
        if server_now is not None:
            self.last_rtt = t1 - t0
            self.last_clock_offset = float(server_now) - (t0 + t1) / 2.0
        self.last_evicted = bool(resp.get("evicted"))
        return bool(resp["stop"])

    def metrics(self) -> dict:
        """Aggregated cluster metrics snapshot (the ``metrics`` op)."""
        return self._check(self._call({"op": "metrics"}, retry=True))["snapshot"]

    def stats(self, window: float = 10.0) -> dict:
        """Rolling-window cluster stats (the ``statz`` op): live qps /
        p50/p99 / queue depths over the last ``window`` seconds."""
        return self._check(self._call({"op": "statz", "window": float(window)},
                                       retry=True))["stats"]

    def manifest(self) -> dict:
        """The driver-published DIRECT-mode job manifest (empty dict until
        a DIRECT train() publishes one)."""
        return self._check(self._call({"op": "manifest"}, retry=True))["manifest"]

    def report_error(self, executor_id: int, traceback_str: str) -> None:
        self._call({"op": "error", "executor_id": executor_id,
                    "traceback": traceback_str}, retry=True)

    def deregister(self, executor_id: int, metrics: dict | None = None,
                   trace: dict | None = None) -> None:
        """Announce a deliberate exit (stops dead-node tracking for this id);
        ``metrics`` carries the node's final telemetry snapshot and
        ``trace`` its final span/flight-event delta."""
        msg: dict = {"op": "deregister", "executor_id": executor_id}
        if metrics:
            msg["metrics"] = metrics
        if trace:
            msg["trace"] = trace
        self._call(msg, retry=True)

    def request_stop(self) -> None:
        self._call({"op": "stop"}, retry=True)

    def close(self) -> None:
        try:
            with self._lock:
                _send_msg(self._sock, {"op": "bye"})
                try:
                    _recv_msg(self._sock)
                except (ConnectionError, OSError, ValueError):  # toslint: allow-silent(best-effort bye ack; the server may already be gone)
                    pass
        except OSError:  # toslint: allow-silent(best-effort teardown; socket close below is what matters)
            pass
        finally:
            self._sock.close()
