"""ctypes bindings for the native C++ codec (``native/tfrecord_codec.cc``).

Built on demand with g++ (pybind11 is not available in this environment;
the C ABI + ctypes keeps the toolchain to the baked-in compiler).  Importing
this module raises if the library cannot be built/loaded — callers
(``tfrecord._use_native``) treat that as "fall back to pure Python".
"""

from __future__ import annotations

import ctypes
import os

from tensorflowonspark_tpu.native.build import build_native_lib

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native", "tfrecord_codec.cc")

_lib = ctypes.CDLL(build_native_lib(_SRC, "libtfrecord_codec.so"))

_lib.tos_crc32c.restype = ctypes.c_uint32
_lib.tos_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32]
_lib.tos_scan_records.restype = ctypes.c_int64
_lib.tos_scan_records.argtypes = [
    ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
    ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64),
]
_lib.tos_frame_record.restype = ctypes.c_uint64
_lib.tos_frame_record.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p]


def crc32c(data: bytes, crc: int = 0) -> int:
    return _lib.tos_crc32c(data, len(data), crc)


_SCAN_CHUNK = 65536


def scan_records(buf, verify: bool = True):
    """Return ([(offset, length), ...], consumed_bytes); raises on corruption.

    ``buf`` is ``bytes`` or any read-only buffer-protocol object (an mmap
    or a memoryview of one — the zero-copy shard path): non-bytes buffers
    are passed by ADDRESS so the scan walks the mapped pages directly,
    with no copy into a bytes object."""
    offs = (ctypes.c_uint64 * _SCAN_CHUNK)()
    lens = (ctypes.c_uint64 * _SCAN_CHUNK)()
    consumed = ctypes.c_uint64()
    spans: list[tuple[int, int]] = []
    base = 0
    total = len(buf)
    addr = None
    if not isinstance(buf, bytes):
        import numpy as np

        anchor = np.frombuffer(buf, np.uint8)  # keeps the buffer pinned
        addr = anchor.ctypes.data
    while True:
        if addr is not None:
            view = ctypes.cast(addr + base, ctypes.c_char_p)
        else:
            view = buf if base == 0 else buf[base:]
        n = _lib.tos_scan_records(view, total - base, int(verify), offs, lens,
                                  _SCAN_CHUNK, ctypes.byref(consumed))
        if n < 0:
            raise ValueError(f"corrupt record at offset {base + consumed.value}")
        spans.extend((base + offs[i], lens[i]) for i in range(n))
        base += consumed.value
        if n < _SCAN_CHUNK:
            return spans, base


def frame_record(data: bytes) -> bytes:
    out = ctypes.create_string_buffer(16 + len(data))
    n = _lib.tos_frame_record(data, len(data), out)
    return out.raw[:n]
