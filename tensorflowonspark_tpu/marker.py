"""Sentinel markers for the streaming data plane.

Parity with ``tensorflowonspark/marker.py:~1-25`` (reference): ``Marker`` base
and ``EndPartition`` (end of one streamed partition).  We add an explicit
``EndOfFeed`` sentinel where the reference used a bare ``None`` pushed by
``TFSparkNode.shutdown`` (``TFSparkNode.py:~590-660``) — an explicit type is
safer when ``None`` may be legitimate user data.
"""


class Marker:
    """Base class for control markers placed in data queues."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - trivial
        return f"<{type(self).__name__}>"


class EndPartition(Marker):
    """End of a single streamed partition (reference ``marker.EndPartition``).

    ``key`` (optional) identifies WHICH logical partition this closes — the
    driver's ledger task, e.g. ``(epoch, partition)``.  The at-least-once
    re-feed path can legitimately place two EndPartitions for one logical
    partition in the same queue (end_partition reply lost after the server
    already queued the marker, then the same partition re-fed); the
    consumption watermark must count such a pair once, or it over-advances
    past still-buffered work that a later death would then fail to
    re-deliver.  ``None`` (legacy/no-ledger feeds) counts every pop.

    ``trace`` (optional) is the sampled request/partition's trace context
    ``(trace_id, span_id)``: the consumer's partition-consume span parents
    onto it, closing the cross-process loop (``telemetry.trace``).
    """

    __slots__ = ("key", "trace")

    def __init__(self, key=None, trace=None):
        self.key = key
        self.trace = trace


class EndOfFeed(Marker):
    """No more data will ever arrive; consumers should finish up."""

    __slots__ = ()


class ResultChunk(Marker):
    """A whole batch of inference results as ONE output-queue item.

    ``DataFeed.batch_results(..., chunk=True)`` wraps the batch in this and
    the data server's ``collect`` op flattens it back out, so a 64-row
    serving batch costs one queue put + one collect round-trip instead of
    64 puts and several partial-drain round-trips (the serving gateway's
    latency path).  Order within the chunk is result order, exactly-count
    is preserved by construction (the chunk holds one result per input
    row of its batch).
    """

    __slots__ = ("items",)

    def __init__(self, items):
        self.items = list(items)
