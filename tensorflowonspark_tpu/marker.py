"""Sentinel markers for the streaming data plane.

Parity with ``tensorflowonspark/marker.py:~1-25`` (reference): ``Marker`` base
and ``EndPartition`` (end of one streamed partition).  We add an explicit
``EndOfFeed`` sentinel where the reference used a bare ``None`` pushed by
``TFSparkNode.shutdown`` (``TFSparkNode.py:~590-660``) — an explicit type is
safer when ``None`` may be legitimate user data.
"""


class Marker:
    """Base class for control markers placed in data queues."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - trivial
        return f"<{type(self).__name__}>"


class EndPartition(Marker):
    """End of a single streamed partition (reference ``marker.EndPartition``)."""

    __slots__ = ()


class EndOfFeed(Marker):
    """No more data will ever arrive; consumers should finish up."""

    __slots__ = ()
