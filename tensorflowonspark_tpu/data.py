"""Partitioned dataset abstraction — the RDD stand-in.

The reference's data plane is a Spark RDD/DataFrame (partitions delivered by
Spark tasks, SURVEY.md §3.2).  This environment ships no Spark, and the
framework is standalone by design (SURVEY.md §7): ``PartitionedDataset`` is
the minimal partitioned collection the cluster API streams from.  Anything
that can yield partitions (list of lists, list of generators, glob of files)
adapts into it.
"""

from __future__ import annotations

import glob as _glob
from typing import Any, Callable, Iterable, Iterator, Sequence


class PartitionedDataset:
    """An ordered list of lazily-evaluated partitions."""

    def __init__(self, partition_fns: Sequence[Callable[[], Iterator[Any]]]):
        self._partition_fns = list(partition_fns)

    # -- constructors --------------------------------------------------------

    @classmethod
    def from_partitions(cls, partitions: Sequence[Iterable[Any]]) -> "PartitionedDataset":
        """From concrete per-partition iterables (each re-iterable)."""
        return cls([(lambda p=p: iter(p)) for p in partitions])

    @classmethod
    def from_iterable(cls, items: Iterable[Any], num_partitions: int) -> "PartitionedDataset":
        """Split a flat sequence into ``num_partitions`` contiguous partitions."""
        items = list(items)
        n = len(items)
        base, extra = divmod(n, num_partitions)
        parts, start = [], 0
        for i in range(num_partitions):
            size = base + (1 if i < extra else 0)
            parts.append(items[start : start + size])
            start += size
        return cls.from_partitions(parts)

    @classmethod
    def from_files(cls, pattern: str, reader: Callable[[str], Iterator[Any]]) -> "PartitionedDataset":
        """One partition per file matching ``pattern`` (sorted), read lazily."""
        files = sorted(_glob.glob(pattern))
        if not files:
            raise FileNotFoundError(f"no files match {pattern!r}")
        return cls([(lambda f=f: reader(f)) for f in files])

    @classmethod
    def from_file_references(cls, pattern: str,
                             num_partitions: int | None = None) -> "PartitionedDataset":
        """Partitions of file PATHS, not bytes: the driver streams only the
        references and each node reads its shards itself.

        The Spark data-locality analogue for ``InputMode.SPARK``
        (reference: executors read their HDFS blocks locally,
        ``TFSparkNode.py:~430-510``) and the way past the driver's fan-out
        ceiling (~190 MB/s pickled bytes per driver core, PERF_NOTES): a
        path is tens of bytes on the wire regardless of shard size, so the
        aggregate read bandwidth scales with the NODE count.  Node-side,
        pair with ``dfutil.read_shard``/``read_shard_columns``.  Paths are
        distributed round-robin so shard sizes even out.
        """
        files = sorted(_glob.glob(pattern))
        if not files:
            raise FileNotFoundError(f"no files match {pattern!r}")
        n = len(files) if num_partitions is None else num_partitions
        if not 0 < n <= len(files):
            # an empty partition would idle its node — and deadlock lockstep
            # SPMD consumption (a host with zero data cannot join a global
            # step); fail at construction, not mid-job
            raise ValueError(f"num_partitions={n} must be in 1..{len(files)} "
                             f"(number of matched files)")
        return cls.from_partitions([files[i::n] for i in range(n)])

    # -- accessors -----------------------------------------------------------

    @property
    def num_partitions(self) -> int:
        return len(self._partition_fns)

    def iter_partition(self, index: int) -> Iterator[Any]:
        return self._partition_fns[index]()

    def __iter__(self) -> Iterator[Any]:
        for i in range(self.num_partitions):
            yield from self.iter_partition(i)

    # -- transforms ----------------------------------------------------------

    def map(self, fn: Callable[[Any], Any]) -> "PartitionedDataset":
        return PartitionedDataset(
            [(lambda pf=pf: (fn(x) for x in pf())) for pf in self._partition_fns]
        )

    def repartition(self, num_partitions: int) -> "PartitionedDataset":
        return PartitionedDataset.from_iterable(list(self), num_partitions)

    def shuffle_partitions(self, seed: int) -> "PartitionedDataset":
        """Deterministically reorder partitions (lazy; contents untouched).

        The between-epochs shuffle the reference got from Spark/tf.data file
        shuffling: pass a per-epoch seed so every epoch streams partitions
        in a different order without materializing anything.
        """
        import random

        order = list(range(self.num_partitions))
        random.Random(seed).shuffle(order)
        return PartitionedDataset([self._partition_fns[i] for i in order])


def shuffle_buffer(items: Iterable[Any], buffer_size: int,
                   seed: int) -> Iterator[Any]:
    """Streaming buffered shuffle — the ``tf.data.Dataset.shuffle`` analogue.

    Fills a ``buffer_size`` reservoir, then yields a uniformly random buffer
    slot per incoming item (replacing it), draining the rest at the end.
    O(buffer_size) memory, deterministic under ``seed``, emits every input
    exactly once.  Perfect shuffling needs ``buffer_size >= len(items)``;
    smaller buffers trade randomness for memory exactly like tf.data.
    """
    import random

    if buffer_size < 1:
        raise ValueError("buffer_size must be >= 1")
    rng = random.Random(seed)
    buf: list[Any] = []
    for item in items:
        if len(buf) < buffer_size:
            buf.append(item)
            continue
        idx = rng.randrange(buffer_size)
        yield buf[idx]
        buf[idx] = item
    rng.shuffle(buf)
    yield from buf


def interleave(factories: Sequence[Callable[[], Iterator[Any]]],
               num_readers: int = 2, buffer_size: int = 256) -> Iterator[Any]:
    """Read several sources with background reader threads — the
    ``tf.data.Dataset.interleave(..., num_parallel_calls=N)`` analogue and
    the consumer of the pipeline layer's ``readers`` Param (reference:
    per-node reader threads in DIRECT/TENSORFLOW input mode).

    ``factories`` are zero-arg callables returning fresh iterators (e.g.
    per-TFRecord-shard readers).  ``num_readers`` threads each pull whole
    sources off a shared work queue and push items into one bounded buffer;
    IO/decode of shard N+1 overlaps the consumer's compute on shard N.
    Cross-source item order is nondeterministic (like tf.data's parallel
    interleave); within one source, order is preserved.  Reader exceptions
    re-raise at the consumer.  With ``num_readers <= 1`` reads happen inline
    (deterministic order, zero threads).
    """
    import queue as _queue
    import threading

    if num_readers <= 1:
        for f in factories:
            yield from f()
        return

    work: _queue.Queue = _queue.Queue()
    for f in factories:
        work.put(f)
    out: _queue.Queue = _queue.Queue(maxsize=buffer_size)
    stop = threading.Event()
    DONE = object()
    failure: list[BaseException] = []

    def _reader() -> None:
        try:
            while not stop.is_set():
                try:
                    factory = work.get_nowait()
                except _queue.Empty:
                    return
                for item in factory():
                    while not stop.is_set():
                        try:
                            out.put(item, timeout=0.1)
                            break
                        except _queue.Full:
                            continue
                    if stop.is_set():
                        return
        except BaseException as e:  # noqa: BLE001 - re-raised consumer-side
            failure.append(e)
        finally:
            # bounded put: if the consumer abandoned the generator nobody
            # drains the buffer, and a blocking put would strand this thread
            while True:
                try:
                    out.put(DONE, timeout=0.1)
                    break
                except _queue.Full:
                    if stop.is_set():
                        break

    n = min(num_readers, len(factories)) or 1
    threads = [threading.Thread(target=_reader, name=f"interleave-{i}",
                                daemon=True) for i in range(n)]
    for t in threads:
        t.start()
    done = 0
    try:
        while done < n:
            if failure:  # surface a reader crash NOW, not after the other
                raise failure[0]  # readers drain their (possibly huge) shards
            item = out.get()
            if item is DONE:
                done += 1
                continue
            yield item
        if failure:
            raise failure[0]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)


# -- columnar chunk packing (zero-copy wire format, dataserver.py) ------------
#
# A STREAMING feed chunk is usually HOMOGENEOUS: K bytes rows (image shards),
# K same-shape ndarrays, or K tuples/dicts of those.  Pickling such a chunk
# row-by-row pays per-row pickle machinery AND copies every payload byte into
# the pickle stream.  The classes below restructure a chunk so that pickle
# protocol 5 with ``buffer_callback`` serializes it as ONE small header plus
# K contiguous out-of-band buffers — which the data plane then scatter-gathers
# straight to the socket (``utils.net.sendmsg_all``) and receives into
# preallocated buffers (``recv_into``), with no per-row pickle work and no
# payload staging copies on the send side.


class _BytesColumn:
    """A column of ``bytes`` (or ``memoryview``) rows; each row travels as
    its own buffer.  Memoryview rows — the ingest zero-copy record views —
    scatter-gather straight from the shard buffer they slice; the receiver
    rebuilds real ``bytes`` either way."""

    __slots__ = ("rows",)

    def __init__(self, rows: list):
        self.rows = rows

    def __reduce_ex__(self, protocol):
        import pickle

        if protocol >= 5:
            return (_rebuild_bytes_column,
                    tuple(pickle.PickleBuffer(r) for r in self.rows))
        # protocol < 5 cannot pickle memoryview at all: materialize
        return (_rebuild_bytes_column,
                tuple(bytes(r) if type(r) is memoryview else r
                      for r in self.rows))


def _rebuild_bytes_column(*bufs) -> "_BytesColumn":
    # out-of-band buffers resolve to whatever the receiver handed pickle
    # (memoryview slices of the recv blob); normalize to real bytes rows
    return _BytesColumn([b if isinstance(b, bytes) else bytes(b) for b in bufs])


class _ArrayColumn:
    """A column of same-dtype/same-shape ndarrays: ONE header (dtype, shape)
    instead of K numpy pickle headers; each row is its own buffer."""

    __slots__ = ("rows",)

    def __init__(self, rows: list):
        self.rows = rows

    def __reduce_ex__(self, protocol):
        import pickle

        import numpy as np

        first = self.rows[0]
        if protocol >= 5:
            bufs = tuple(pickle.PickleBuffer(np.ascontiguousarray(r))
                         for r in self.rows)
            return (_rebuild_array_column,
                    (first.dtype.str, first.shape) + bufs)
        return (_rebuild_array_column,
                (first.dtype.str, first.shape)
                + tuple(np.ascontiguousarray(r).tobytes() for r in self.rows))


def _rebuild_array_column(dtype_str, shape, *bufs) -> "_ArrayColumn":
    import numpy as np

    rows = []
    for b in bufs:
        arr = np.frombuffer(b, dtype=np.dtype(dtype_str)).reshape(shape)
        if not arr.flags.writeable:
            # read-only receive buffers (bytes-backed ring records, in-band
            # fallback) must not leak into user code: pickled ndarrays were
            # always writable, and whether a map_fun may normalize in place
            # must not depend on which transport delivered the batch
            arr = arr.copy()
        rows.append(arr)
    return _ArrayColumn(rows)


# Rows below this size serialize IN-band: an out-of-band buffer costs a
# PickleBuffer + iovec slot + receiver-side view/rebuild per row (~µs each),
# which only pays for itself once the saved per-byte copies outweigh it.
# Measured crossover on the dataplane bench is low-single-digit KB; tabular
# ~1 KB rows must never regress (they were the fast case already).
_MIN_OOB_ROW_BYTES = 4096


def _pack_column(values: list):
    """Pack one homogeneous column, or None when it does not qualify."""
    import numpy as np

    first = values[0]
    if type(first) is bytes or type(first) is memoryview:
        # memoryview rows are the ingest zero-copy record views; mixing
        # with bytes rows is fine (every row is its own buffer either way)
        if len(first) >= _MIN_OOB_ROW_BYTES and all(
                type(v) in (bytes, memoryview) for v in values):
            return _BytesColumn(values)
        return None
    if isinstance(first, np.ndarray) and not first.dtype.hasobject:
        if first.dtype.kind == "V":
            # structured/void dtypes don't survive the dtype.str round-trip
            # (field names collapse to raw '|V8'); numpy's own reduce
            # serializes them correctly, so leave such rows unpacked
            return None
        if first.nbytes >= _MIN_OOB_ROW_BYTES and all(
                isinstance(v, np.ndarray) and v.dtype == first.dtype
                and v.shape == first.shape for v in values):
            return _ArrayColumn(values)
        return None
    return None


class PackedChunk:
    """A feed chunk restructured into columns for protocol-5 framing.

    ``layout`` is ``"flat"`` (rows ARE the single column's values),
    ``"tuple"`` (row i = tuple of column i-th values), or ``"dict"``
    (``meta`` holds the shared key order).  Columns are ``_BytesColumn`` /
    ``_ArrayColumn`` (out-of-band) or plain lists (in-band, e.g. labels).
    """

    __slots__ = ("layout", "columns", "meta")

    def __init__(self, layout: str, columns: tuple, meta: Any = None):
        self.layout = layout
        self.columns = columns
        self.meta = meta

    def __reduce__(self):
        return (PackedChunk, (self.layout, self.columns, self.meta))

    def __len__(self) -> int:
        if self.layout == "columns":
            return len(self.columns[0])  # the ColumnChunk itself
        col = self.columns[0]
        return len(col.rows if hasattr(col, "rows") else col)

    def rows(self) -> list:
        if self.layout == "columns":
            # a dfutil.ColumnChunk travelled whole (one contiguous buffer
            # per numeric column); it owns the columns->rows expansion
            return self.columns[0].rows()
        cols = [c.rows if hasattr(c, "rows") else c for c in self.columns]
        if self.layout == "flat":
            return cols[0]
        if self.layout == "tuple":
            return [tuple(vals) for vals in zip(*cols)]
        if self.layout == "dict":
            from tensorflowonspark_tpu import dfutil

            return dfutil.columns_to_rows(self.meta, cols)
        raise ValueError(f"corrupt PackedChunk layout {self.layout!r}")


class DecodedChunk:
    """One pre-decoded ingest chunk in flight from a data-service worker to
    a trainer (the ``chunk_fwd`` wire op).

    ``payload`` is exactly what a trainer-local reader pipeline would have
    pushed: a list of record payloads (owned ``bytes`` — never zero-copy
    views, which cannot travel a wire), or a ``dfutil.ColumnChunk`` whose
    contiguous column buffers ride the v2/v3 wire out-of-band.  The
    trainer-side ``IngestFeed`` recognizes the wrapper on its input queue
    and injects the payload straight into its pipeline's decoded-chunk
    queue — the feed becomes a pure consumer, with the partition watermark
    accounting unchanged (each forwarded chunk is one "shard" of its
    ledger partition).  ``source`` is an opaque provenance tag (the
    worker's work-item key) for telemetry and debugging only.
    """

    __slots__ = ("payload", "nrows", "source", "_nbytes")

    def __init__(self, payload, source=None):
        self.payload = payload
        self.nrows = len(payload)
        self.source = source
        self._nbytes: int | None = None

    @property
    def nbytes(self) -> int:
        """Payload bytes, computed once per wrapper (the forwarder's
        byte counters must not re-walk every record per delivery)."""
        if self._nbytes is None:
            self._nbytes = chunk_nbytes(self.payload)
        return self._nbytes

    def __reduce__(self):
        return (DecodedChunk, (self.payload, self.source))

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<DecodedChunk rows={self.nrows} source={self.source!r}>"


def chunk_nbytes(payload) -> int:
    """Approximate payload bytes of one decoded chunk (record list or
    ``dfutil.ColumnChunk``) — the accounting unit of the ingest tier's
    cross-epoch chunk cache (``TOS_INGEST_CACHE_BYTES``) and its forwarded-
    bytes counters.  Cheap and slightly conservative: python object
    overhead is not charged, only payload bytes."""
    import numpy as np

    if hasattr(payload, "columns") and hasattr(payload, "counts"):
        total = 0
        for col in payload.columns.values():
            if isinstance(col, np.ndarray):
                total += col.nbytes
            else:  # bytes/str column: a plain list of per-record values
                total += sum(len(v) for v in col)
        for counts in payload.counts.values():
            total += (counts.nbytes if isinstance(counts, np.ndarray)
                      else 8 * len(counts))
        return total
    total = 0
    for r in payload:
        if isinstance(r, (bytes, bytearray, memoryview)):
            total += len(r)
        elif isinstance(r, np.ndarray):
            total += r.nbytes
        elif isinstance(r, tuple):
            total += sum(len(v) if isinstance(v, (bytes, memoryview))
                         else getattr(v, "nbytes", 8) for v in r)
        else:
            total += getattr(r, "nbytes", 64)
    return total


def pack_chunk(items: list) -> PackedChunk | None:
    """Columnar-pack a homogeneous chunk, or None when it does not qualify
    (the caller then sends the plain list — semantics are identical either
    way; packing only changes how the bytes travel).

    A ``dfutil.ColumnChunk`` (the ingest pipeline's columnar decode
    product) packs directly: its K contiguous column buffers ARE the
    out-of-band frame (protocol 5 ships each ndarray column as one
    buffer), and the receiver's ``unpack_items`` expands rows — no per-row
    repack on either side."""
    packed = _pack_chunk_inner(items)
    # pack-vs-fallback counts: a feed that silently stopped qualifying for
    # the zero-copy path (heterogeneous rows, sub-threshold sizes) shows up
    # here instead of as an unexplained throughput regression
    from tensorflowonspark_tpu import telemetry

    telemetry.counter("dataplane.chunks_packed" if packed is not None
                      else "dataplane.chunks_unpacked").inc()
    return packed


def _pack_chunk_inner(items: list) -> PackedChunk | None:
    from tensorflowonspark_tpu import dfutil

    if isinstance(items, dfutil.ColumnChunk):
        return PackedChunk("columns", (items,)) if len(items) else None
    if not items:
        return None
    first = items[0]
    if type(first) in (bytes, memoryview) or _is_ndarray(first):
        col = _pack_column(items)
        return PackedChunk("flat", (col,)) if col is not None else None
    if type(first) is tuple:
        n = len(first)
        if n == 0 or not all(type(r) is tuple and len(r) == n for r in items):
            return None
        packed_any = False
        columns = []
        for pos in range(n):
            values = [r[pos] for r in items]
            col = _pack_column(values)
            packed_any = packed_any or col is not None
            columns.append(col if col is not None else values)
        return PackedChunk("tuple", tuple(columns)) if packed_any else None
    if type(first) is dict:
        # row-dict chunks (the dfutil row model) pack per key; dfutil owns
        # the rows<->columns reshaping so schema'd readers share one path
        from tensorflowonspark_tpu import dfutil

        reshaped = dfutil.rows_to_columns(items)
        if reshaped is None:
            return None
        keys, value_lists = reshaped
        packed_any = False
        columns = []
        for values in value_lists:
            col = _pack_column(values)
            packed_any = packed_any or col is not None
            columns.append(col if col is not None else values)
        if not packed_any:
            return None
        return PackedChunk("dict", tuple(columns), meta=keys)
    return None


def _is_ndarray(x: Any) -> bool:
    import numpy as np

    return isinstance(x, np.ndarray)


def materialize_views(items: list) -> list:
    """bytes-ify memoryview rows (and views inside tuple/dict rows) that
    did NOT qualify for out-of-band packing — plain pickle cannot
    serialize memoryview at all, so a sub-threshold zero-copy record
    reaching the wire unpacked must materialize here rather than crash
    deep in the transport.  Returns ``items`` unchanged when nothing
    needs fixing (the overwhelmingly common case)."""

    def _dirty(v) -> bool:
        if type(v) is memoryview:
            return True
        if type(v) in (tuple, list):
            return any(type(x) is memoryview for x in v)
        if type(v) is dict:
            return any(type(x) is memoryview for x in v.values())
        return False

    def _fix(v):
        if type(v) is memoryview:
            return bytes(v)
        if type(v) in (tuple, list) and _dirty(v):
            fixed = [bytes(x) if type(x) is memoryview else x for x in v]
            return tuple(fixed) if type(v) is tuple else fixed
        if type(v) is dict and _dirty(v):
            return {k: bytes(x) if type(x) is memoryview else x
                    for k, x in v.items()}
        return v

    if not isinstance(items, list):
        return items
    if any(_dirty(v) for v in items):
        return [_fix(x) for x in items]
    return items


def unpack_items(items: Any) -> list:
    """Server-side inverse of ``pack_chunk``: a PackedChunk (or a bare
    ``dfutil.ColumnChunk`` fed as one pre-packed item) becomes its row
    list; anything else passes through unchanged (old peers send lists)."""
    if isinstance(items, PackedChunk):
        return items.rows()
    if hasattr(items, "rows") and hasattr(items, "counts"):  # ColumnChunk
        return items.rows()
    return items


def as_partitioned(data: Any, default_partitions: int = 1) -> PartitionedDataset:
    """Coerce user input into a PartitionedDataset.

    Accepts a PartitionedDataset, a sequence of *lists* (interpreted as
    partitions), or a flat iterable of samples (split into
    ``default_partitions``).  Samples that are themselves sequences should be
    tuples, not lists, to avoid ambiguity with the partition form.
    """
    if isinstance(data, PartitionedDataset):
        return data
    data = list(data)
    if data and all(isinstance(p, list) for p in data):
        return PartitionedDataset.from_partitions(data)
    return PartitionedDataset.from_iterable(data, default_partitions)
