"""Partitioned dataset abstraction — the RDD stand-in.

The reference's data plane is a Spark RDD/DataFrame (partitions delivered by
Spark tasks, SURVEY.md §3.2).  This environment ships no Spark, and the
framework is standalone by design (SURVEY.md §7): ``PartitionedDataset`` is
the minimal partitioned collection the cluster API streams from.  Anything
that can yield partitions (list of lists, list of generators, glob of files)
adapts into it.
"""

from __future__ import annotations

import glob as _glob
from typing import Any, Callable, Iterable, Iterator, Sequence


class PartitionedDataset:
    """An ordered list of lazily-evaluated partitions."""

    def __init__(self, partition_fns: Sequence[Callable[[], Iterator[Any]]]):
        self._partition_fns = list(partition_fns)

    # -- constructors --------------------------------------------------------

    @classmethod
    def from_partitions(cls, partitions: Sequence[Iterable[Any]]) -> "PartitionedDataset":
        """From concrete per-partition iterables (each re-iterable)."""
        return cls([(lambda p=p: iter(p)) for p in partitions])

    @classmethod
    def from_iterable(cls, items: Iterable[Any], num_partitions: int) -> "PartitionedDataset":
        """Split a flat sequence into ``num_partitions`` contiguous partitions."""
        items = list(items)
        n = len(items)
        base, extra = divmod(n, num_partitions)
        parts, start = [], 0
        for i in range(num_partitions):
            size = base + (1 if i < extra else 0)
            parts.append(items[start : start + size])
            start += size
        return cls.from_partitions(parts)

    @classmethod
    def from_files(cls, pattern: str, reader: Callable[[str], Iterator[Any]]) -> "PartitionedDataset":
        """One partition per file matching ``pattern`` (sorted), read lazily."""
        files = sorted(_glob.glob(pattern))
        if not files:
            raise FileNotFoundError(f"no files match {pattern!r}")
        return cls([(lambda f=f: reader(f)) for f in files])

    # -- accessors -----------------------------------------------------------

    @property
    def num_partitions(self) -> int:
        return len(self._partition_fns)

    def iter_partition(self, index: int) -> Iterator[Any]:
        return self._partition_fns[index]()

    def __iter__(self) -> Iterator[Any]:
        for i in range(self.num_partitions):
            yield from self.iter_partition(i)

    # -- transforms ----------------------------------------------------------

    def map(self, fn: Callable[[Any], Any]) -> "PartitionedDataset":
        return PartitionedDataset(
            [(lambda pf=pf: (fn(x) for x in pf())) for pf in self._partition_fns]
        )

    def repartition(self, num_partitions: int) -> "PartitionedDataset":
        return PartitionedDataset.from_iterable(list(self), num_partitions)

    def shuffle_partitions(self, seed: int) -> "PartitionedDataset":
        """Deterministically reorder partitions (lazy; contents untouched).

        The between-epochs shuffle the reference got from Spark/tf.data file
        shuffling: pass a per-epoch seed so every epoch streams partitions
        in a different order without materializing anything.
        """
        import random

        order = list(range(self.num_partitions))
        random.Random(seed).shuffle(order)
        return PartitionedDataset([self._partition_fns[i] for i in order])


def shuffle_buffer(items: Iterable[Any], buffer_size: int,
                   seed: int) -> Iterator[Any]:
    """Streaming buffered shuffle — the ``tf.data.Dataset.shuffle`` analogue.

    Fills a ``buffer_size`` reservoir, then yields a uniformly random buffer
    slot per incoming item (replacing it), draining the rest at the end.
    O(buffer_size) memory, deterministic under ``seed``, emits every input
    exactly once.  Perfect shuffling needs ``buffer_size >= len(items)``;
    smaller buffers trade randomness for memory exactly like tf.data.
    """
    import random

    if buffer_size < 1:
        raise ValueError("buffer_size must be >= 1")
    rng = random.Random(seed)
    buf: list[Any] = []
    for item in items:
        if len(buf) < buffer_size:
            buf.append(item)
            continue
        idx = rng.randrange(buffer_size)
        yield buf[idx]
        buf[idx] = item
    rng.shuffle(buf)
    yield from buf


def as_partitioned(data: Any, default_partitions: int = 1) -> PartitionedDataset:
    """Coerce user input into a PartitionedDataset.

    Accepts a PartitionedDataset, a sequence of *lists* (interpreted as
    partitions), or a flat iterable of samples (split into
    ``default_partitions``).  Samples that are themselves sequences should be
    tuples, not lists, to avoid ambiguity with the partition form.
    """
    if isinstance(data, PartitionedDataset):
        return data
    data = list(data)
    if data and all(isinstance(p, list) for p in data):
        return PartitionedDataset.from_partitions(data)
    return PartitionedDataset.from_iterable(data, default_partitions)
