"""Per-node TCP data-plane server + driver-side client.

Replaces the Spark RDD partition-delivery path of the reference
(SURVEY.md §3.2/§3.3): where TFoS ran ``TFSparkNode.train``/``inference``
closures inside pyspark workers that pushed items into ``TFManager`` remote
queues (``TFSparkNode.py:~430-580``), here the driver streams partitions over
a socket directly into the node's in-process ``FeedQueues``.  One hop, no
manager proxy.

Wire format: length-framed pickle, **after** an HMAC-SHA256
challenge-response handshake on the shared cluster ``authkey`` (mirroring the
``multiprocessing`` authkey handshake the reference's manager queues used,
``TFSparkNode.py:~80-130``).  No pickle bytes are deserialized before the
peer has proven knowledge of the authkey — pickle is an arbitrary-code
format, so authentication must precede deserialization.

Two frame formats share the stream, distinguished by the top bit of the
8-byte length word (auto-negotiated via a ``hello`` op so old peers keep
working):

- **v1** (legacy): ``[len:8][pickle bytes]``.
- **v2** (vectorized, zero-copy): ``[VEC|nsections:8][section lens:8*n]``
  followed by a pickle **protocol-5** body and its out-of-band buffers.
  numpy rows / bytes rows (via ``data.pack_chunk``) travel as contiguous
  buffers scatter-gathered straight from their own memory
  (``utils.net.sendmsg_all`` — no intermediate ``bytes`` join) and are
  received into preallocated buffers (``recv_into``), so the only per-byte
  cost on the hot path is the kernel copy.

Invariants preserved:
- feed backpressure: bounded queue put with ``feed_timeout`` raises upstream
  (reference ``TFSparkNode.py:~460-490``);
- 'terminating' state fast-drains remaining items so upstream feeders
  unblock (reference ``TFNode.py:~400-430``);
- inference returns **exactly count, ordered** results per partition
  (reference invariant, SURVEY.md §3.3).
"""

from __future__ import annotations

import contextlib
import logging
import pickle
import queue
import socket
import struct
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_lock
from time import monotonic as _monotonic
from typing import Any, Iterable

from tensorflowonspark_tpu import faultinject, telemetry
from tensorflowonspark_tpu.telemetry import trace as ttrace
from tensorflowonspark_tpu.data import _MIN_OOB_ROW_BYTES as _MIN_OOB_BYTES
from tensorflowonspark_tpu.data import materialize_views as _materialize_views
from tensorflowonspark_tpu.data import pack_chunk as _pack_chunk
from tensorflowonspark_tpu.data import unpack_items as _unpack_items
from tensorflowonspark_tpu.feeding import FeedQueues
from tensorflowonspark_tpu.marker import EndOfFeed, EndPartition, ResultChunk

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">Q")
# v2 frame marker: top bit of the length word (v1 lengths can never reach it)
_VEC_BIT = 1 << 63
# sanity cap on section counts so a corrupt/hostile frame cannot trigger a
# giant header allocation before the pickle layer ever sees it
_MAX_SECTIONS = 1 << 20
#: Highest wire version this build speaks; negotiated down via the ``hello``
#: op (old servers answer it with an unknown-op error -> v1).  v3 frames are
#: byte-identical to v2 (protocol-5 vectorized); the bump only gates the op
#: schema extension that appends a trace context to ``infer_round``/
#: ``end_partition`` — a v2 peer never sees the extra element.
WIRE_VERSION = 3
# shm-ring v2 records carry an explicit magic (ring records are pickled blobs
# otherwise, which always start with b"\x80")
_RING_VEC_MAGIC = b"TOSVEC2\x00"

from tensorflowonspark_tpu.utils.net import (  # noqa: E402
    hmac_handshake_client as _hmac_handshake_client,
    hmac_handshake_server as _hmac_handshake_server,
    recv_exact as _recv_raw,
    recv_exact_into as _recv_into,
    sendmsg_all as _sendmsg_all,
    set_nodelay as _set_nodelay,
)


def _extend_results(out: list, item: Any) -> None:
    """Flatten a popped output-queue item into per-item results (a
    ``ResultChunk`` carries a whole batch as one entry)."""
    if isinstance(item, ResultChunk):
        out.extend(item.items)
    else:
        out.append(item)


def _force_put(q: queue.Queue, item: Any) -> None:
    """Put a control marker even into a full queue whose consumer has stopped,
    discarding queued-but-unconsumed data items to make room (the consumer is
    shutting down; this mirrors the terminate fast-drain semantics)."""
    while True:
        try:
            q.put_nowait(item)
            return
        except queue.Full:
            try:
                q.get_nowait()
            except queue.Empty:  # toslint: allow-silent(consumer raced the drain and made room; the outer loop retries the put)
                pass


def _vec_parts(obj: Any) -> tuple[bytes, list]:
    """(pickle-5 body, contiguous out-of-band buffer views) for ``obj``.

    The buffer callback applies the same size threshold as
    ``data.pack_chunk``: a tiny buffer (e.g. a <4 KB label array riding a
    tuple column) stays IN-band — its per-buffer section-len/iovec/rebuild
    overhead outweighs the saved copy — and non-contiguous buffers stay
    in-band too (pickle copies them flat), so this never fails."""
    raws: list = []

    def _cb(pb: pickle.PickleBuffer):
        try:
            raw = pb.raw()
        except BufferError:
            return True  # non-contiguous: serialize in-band
        if raw.nbytes < _MIN_OOB_BYTES:
            return True  # tiny: in-band beats per-buffer overhead
        raws.append(raw)
        return False  # out-of-band

    body = pickle.dumps(obj, protocol=5, buffer_callback=_cb)
    return body, raws


def frame_parts(obj: Any, wire: int = 1) -> list:
    """Buffer list for ONE wire frame of ``obj`` (header, body[, raw
    buffers]); sending the list in order IS the frame.  Shared by the
    blocking ``_send`` below and the serving reactor, whose non-blocking
    writes park leftover views on a per-connection queue instead of
    looping — the zero-copy property (out-of-band buffers scatter-gather
    straight from their own memory) is identical on both paths."""
    if wire >= 2:
        body, raws = _vec_parts(obj)
        header = bytearray(_LEN.pack(_VEC_BIT | (len(raws) + 1)))
        header += _LEN.pack(len(body))
        for r in raws:
            header += _LEN.pack(r.nbytes)
        return [header, body, *raws]
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return [_LEN.pack(len(data)), data]


def _send(sock: socket.socket, obj: Any, wire: int = 1) -> None:
    parts = frame_parts(obj, wire)
    _sendmsg_all(sock, parts)
    telemetry.counter("dataplane.tx_bytes").inc(
        sum(memoryview(p).nbytes for p in parts))
    telemetry.counter("dataplane.tx_frames").inc()


# Frames up to this size are received into one preallocated buffer (the
# zero-copy fast path); anything larger grows incrementally as bytes
# actually arrive, so a corrupt/desynced length word (bit flip, partial
# frame from a prior error) can only cost what the peer really sends —
# never an up-front multi-TB zero-fill.
_PREALLOC_LIMIT = 256 << 20


def _recv_sized(sock: socket.socket, n: int) -> bytearray:
    if n <= _PREALLOC_LIMIT:
        buf = bytearray(n)
        _recv_into(sock, buf)
        return buf
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("data socket closed mid-frame")
        buf.extend(chunk)
    return buf


def _recv_frame(sock: socket.socket) -> tuple[Any, bool]:
    """Receive one frame -> (object, was_vectorized).  Both formats are
    self-describing on the wire, so a v2 speaker can always read a v1 peer;
    the ``hello`` negotiation only gates what gets SENT."""
    (word,) = _LEN.unpack(_recv_raw(sock, 8))
    if word & _VEC_BIT:
        nsec = word & (_VEC_BIT - 1)
        if not 1 <= nsec <= _MAX_SECTIONS:
            raise ConnectionError(f"corrupt vectorized frame ({nsec} sections)")
        lens = struct.unpack(f">{nsec}Q", _recv_raw(sock, 8 * nsec))
        body = _recv_sized(sock, lens[0])
        blob = _recv_sized(sock, sum(lens[1:]))
        view = memoryview(blob)
        bufs, off = [], 0
        for ln in lens[1:]:
            bufs.append(view[off:off + ln])
            off += ln
        telemetry.counter("dataplane.rx_bytes").inc(8 + 8 * nsec + sum(lens))
        telemetry.counter("dataplane.rx_frames").inc()
        return pickle.loads(body, buffers=bufs), True
    # v1: one length-framed pickle, received into a single preallocated
    # buffer and unpickled in place (no full-frame bytes() copy)
    telemetry.counter("dataplane.rx_bytes").inc(8 + word)
    telemetry.counter("dataplane.rx_frames").inc()
    return pickle.loads(_recv_sized(sock, word)), False


def _recv(sock: socket.socket) -> Any:
    return _recv_frame(sock)[0]


# -- shm-ring record framing (same two formats over ring records) -------------


def _ring_vec_record(obj: Any) -> list:
    """Buffer list for ONE segmented ring record carrying a v2 frame
    (pushed join-free via ``ShmRing.put_buffers``)."""
    body, raws = _vec_parts(obj)
    header = bytearray(_RING_VEC_MAGIC)
    header += _LEN.pack(len(raws) + 1)
    header += _LEN.pack(len(body))
    for r in raws:
        header += _LEN.pack(r.nbytes)
    return [header, body, *raws]


def _ring_loads(blob: bytes) -> tuple[Any, bool]:
    """Decode one ring record -> (object, was_vectorized); buffer sections
    resolve to zero-copy views of the record blob."""
    if blob[:8] == _RING_VEC_MAGIC:
        view = memoryview(blob)
        (nsec,) = _LEN.unpack(view[8:16])
        if not 1 <= nsec <= _MAX_SECTIONS:
            raise ValueError(f"corrupt vectorized ring record ({nsec} sections)")
        lens = struct.unpack(f">{nsec}Q", view[16:16 + 8 * nsec])
        off = 16 + 8 * nsec
        body = view[off:off + lens[0]]
        off += lens[0]
        bufs = []
        for ln in lens[1:]:
            bufs.append(view[off:off + ln])
            off += ln
        return pickle.loads(body, buffers=bufs), True
    return pickle.loads(blob), False


def _ring_send(ring, obj: Any, wire: int, timeout: float | None) -> None:
    if wire >= 2:
        bufs = _ring_vec_record(obj)
        ring.put_buffers(bufs, timeout=timeout)
        telemetry.counter("dataplane.tx_bytes").inc(
            sum(b.nbytes if isinstance(b, memoryview) else len(b)
                for b in bufs))
        telemetry.counter("dataplane.tx_frames").inc()
        return
    ring.put(obj, timeout=timeout)
    telemetry.counter("dataplane.tx_frames").inc()


def _ring_recv(ring, timeout: float | None) -> tuple[Any, bool]:
    blob = ring.get_bytes(timeout=timeout)
    telemetry.counter("dataplane.rx_bytes").inc(len(blob))
    telemetry.counter("dataplane.rx_frames").inc()
    return _ring_loads(blob)


class DataServer:
    """Accepts driver feed/inference connections for one node process."""

    def __init__(self, queues: FeedQueues, authkey: bytes, feed_timeout: float = 600.0):
        self.queues = queues
        self.authkey = authkey
        self.feed_timeout = feed_timeout
        from tensorflowonspark_tpu.utils.net import bound_socket

        self._sock = bound_socket("")  # all interfaces: the driver may be remote
        self.port: int = self._sock.getsockname()[1]
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True, name="dataserver")
        self._ring_threads: list[threading.Thread] = []

    def start(self) -> int:
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:  # toslint: allow-silent(closing the listener is what unblocks the accept loop; a second close racing it is fine)
            pass
        # Wait briefly for ring threads to run their cleanup (close_write):
        # they are daemons, and if the node process exits before a ring's
        # close_write, a driver blocked in ring.get() waits out its FULL call
        # timeout (~minutes) instead of seeing RingClosed immediately — the
        # teardown race behind sporadic 600s shutdown stalls.  The threads
        # wake from their bounded waits within a few seconds.
        for t in self._ring_threads:
            t.join(timeout=8.0)

    # -- server internals ----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            _set_nodelay(conn)  # request/reply stream: Nagle only adds 40ms
            threading.Thread(target=self._serve_conn, args=(conn,), daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            if not _hmac_handshake_server(conn, self.authkey):
                logger.warning("rejected data-plane connection: bad authkey")
                return
            while True:
                msg, was_vec = _recv_frame(conn)
                if isinstance(msg, tuple) and msg \
                        and msg[0] == "collective_attach":
                    # Collective wire op: hand this (already-authenticated)
                    # connection to the collective layer — after the ok
                    # reply it becomes a one-way stream of ``cchunk``
                    # frames a peer node's ring neighbor pumps gradient
                    # chunks down (collective/transport.py).  The receive
                    # loop runs on THIS connection thread, which is what
                    # makes peer sends deadlock-free: every node's inbound
                    # wire drains independently of its compute thread.
                    from tensorflowonspark_tpu.collective import (
                        transport as _ctransport,
                    )

                    # frame: (op, group, src_rank, generation[, src_eid]) —
                    # the eid rider keys the connection for membership
                    # severing (gray-failure hard fencing); older 4-tuple
                    # senders key as -1 (never severed by membership)
                    src_eid = int(msg[4]) if len(msg) > 4 else -1
                    err = _ctransport.attach_error(str(msg[1]), src_eid,
                                                   int(msg[3]))
                    _send(conn, ("ok",) if err is None else ("err", err),
                          wire=2 if was_vec else 1)
                    if err is None:
                        _ctransport.serve_attached(conn, str(msg[1]),
                                                   int(msg[2]), int(msg[3]),
                                                   src_eid)
                    return
                try:
                    reply = self._handle(msg)
                except faultinject.FaultInjected:
                    # Chaos hook `sever`: drop the connection with NO reply —
                    # exactly what a mid-partition socket loss looks like to
                    # the driver (the node itself stays healthy).
                    logger.warning("fault injection: severing data connection")
                    return
                except Exception as e:  # surface handler errors to the driver
                    logger.exception("dataserver op failed")
                    reply = ("err", f"{type(e).__name__}: {e}")
                # answer in the format the request used: a v2 speaker already
                # proved it reads vectorized frames, a v1 peer never will
                _send(conn, reply, wire=2 if was_vec else 1)
                if msg[0] == "close":
                    return
        except (ConnectionError, OSError, EOFError):
            return
        finally:
            conn.close()

    def _put_responsive(self, q: queue.Queue, item: Any) -> tuple | None:
        """Blocking put that stays responsive to terminate/stop.

        A put against a full queue whose consumer has wedged in user code
        (not the feed, not a barrier) must not pin the driver's feed worker
        for the whole ``feed_timeout``: poll the terminating state in short
        slices so a stop signal drains the feed within ~0.5s.  Returns None
        when the item was queued, or the reply tuple to send instead."""
        deadline = _monotonic() + self.feed_timeout
        while True:
            if self.queues.get("state") == "terminating":
                return ("ok", "terminating")
            remaining = deadline - _monotonic()
            if remaining <= 0:
                return ("err", f"feed timeout after {self.feed_timeout}s (consumer stalled?)")
            try:
                q.put(item, block=True, timeout=min(0.5, remaining))
                return None
            except queue.Full:
                continue

    def _handle(self, msg: tuple) -> tuple:
        op = msg[0]
        if op == "hello":
            # wire-format negotiation: a client that gets an unknown-op error
            # back (old server) stays on v1; see WIRE_VERSION
            return ("ok", min(WIRE_VERSION, int(msg[1])))
        if op in ("feed", "infer_send", "infer_round", "chunk_fwd"):
            # chaos seams: `delay_net:ms=M` injects wire latency on every
            # data-carrying op; `sever`/`flap` may raise FaultInjected so
            # the connection closes with no reply (chunk_fwd is the
            # trainer<->ingest-worker stream — severable like the rest)
            faultinject.net_delay()
            faultinject.data_op()
        if op == "chunk_fwd":
            # Disaggregated ingest tier: a data-service worker forwards
            # PRE-DECODED chunks (data.DecodedChunk wrappers) into this
            # trainer's input queue; the trainer's IngestFeed injects the
            # payloads into its pipeline as a pure consumer.  Same
            # backpressure/terminating contract as `feed`.
            _, qname, chunks = msg
            telemetry.counter("dataplane.chunks_in").inc(len(chunks))
            telemetry.counter("dataplane.rows_in").inc(
                sum(c.nrows for c in chunks))
            if self.queues.get("state") == "terminating":
                return ("ok", "terminating")
            q = self.queues.get_queue(qname)
            for c in chunks:
                state = self._put_responsive(q, c)
                if state is not None:
                    return state
            return ("ok", "running")
        if op == "feed":
            _, qname, items = msg
            items = _unpack_items(items)
            telemetry.counter("dataplane.chunks_in").inc()
            telemetry.counter("dataplane.rows_in").inc(len(items))
            if self.queues.get("state") == "terminating":
                return ("ok", "terminating")  # fast-drain: drop silently
            q = self.queues.get_queue(qname)
            for item in items:
                state = self._put_responsive(q, item)
                if state is not None:
                    return state
            return ("ok", "running")
        if op == "end_partition":
            # data-integrity marker mid-stream: bounded wait, surface stalls
            # Snapshot the watermark BEFORE the marker is queued: once the
            # EndPartition is poppable, a fast map_fun can consume this very
            # partition before the reply is built, and a report that already
            # includes it would make the ledger's first-ack anchor strand a
            # ghost entry in its delivered window (the tail drain would then
            # stall on work that was consumed all along).  Reading early only
            # lags the watermark — over-requeue on death, never loss.
            consumed = self.queues.partitions_consumed(msg[1])
            state = self._put_responsive(
                self.queues.get_queue(msg[1]),
                EndPartition(msg[2] if len(msg) > 2 else None,
                             trace=ttrace.coerce_context(
                                 msg[3] if len(msg) > 3 else None)))
            if state is not None and state[0] == "err":
                return ("err", f"feed timeout placing EndPartition after {self.feed_timeout}s")
            # reply carries the consumption watermark: how many partitions the
            # map_fun has fully drained so far — the driver's ledger uses it
            # to bound what a sudden death can take down with the queue
            return ("ok", consumed)
        if op == "consumed":
            # standalone watermark read: after the last feed ack there are no
            # more end_partition replies to carry it, and the driver's tail
            # drain (elastic train) polls this until the buffered window is
            # known-consumed
            return ("ok", self.queues.partitions_consumed(msg[1]))
        if op == "eof":
            # Shutdown marker.  A full queue usually just means backpressure
            # (consumer alive but behind) — wait briefly for space so no
            # queued sample is lost; force-discard if the consumer looks
            # stalled.  Deliberately NOT feed_timeout: shutdown sends EOFs
            # serially per node/queue and must never stack near-10-minute
            # waits behind a hung consumer.
            q = self.queues.get_queue(msg[1])
            try:
                q.put(EndOfFeed(), block=True, timeout=min(5.0, self.feed_timeout))
            except queue.Full:
                logger.warning("consumer stalled with full queue %r; forcing EndOfFeed "
                               "(discarding a queued item)", msg[1])
                _force_put(q, EndOfFeed())
            return ("ok",)
        if op == "infer_send":
            # Bounded-hold inference feed: accept what fits within a SHORT
            # wait and report progress; the client retries the remainder.
            # Keeps every data-plane round-trip brief, so one slow partition
            # can never pin the connection (and the client lock) for the
            # whole feed_timeout (VERDICT r2 weak #7).
            _, qname, items, want_end = msg
            items = _unpack_items(items)
            telemetry.counter("dataplane.chunks_in").inc()
            telemetry.counter("dataplane.rows_in").inc(len(items))
            if self.queues.get("state") == "terminating":
                return ("ok", len(items), True, "terminating")
            q = self.queues.get_queue(qname)
            budget = min(2.0, self.feed_timeout)
            accepted = 0
            for item in items:
                try:
                    q.put(item, block=True, timeout=budget)
                except queue.Full:
                    return ("ok", accepted, False, "running")
                accepted += 1
            end_placed = False
            if want_end:
                try:
                    q.put(EndPartition(), block=True, timeout=budget)
                    end_placed = True
                except queue.Full:  # toslint: allow-silent(bounded-hold protocol: end_placed=False in the reply makes the client retry the marker)
                    pass
            return ("ok", accepted, end_placed, "running")
        if op == "infer_round":
            # Serving hot path: ONE round-trip scores one whole micro-batch —
            # feed the items + EndPartition, then hold the connection until
            # the map_fun's results (usually one ResultChunk) are collected.
            # The send/collect split (infer_send + collect polling) exists so
            # BIG partitions never pin a connection; a serving batch is tiny
            # and latency-bound, so here the round-trip count wins instead.
            # A v3 peer may append the sampled batch's trace context: this
            # round records the node-side serve.node_round span under it
            # (queue put -> results popped), and the EndPartition carries it
            # to the consumer for the compute span.
            _, qname_in, qname_out, items, wait = msg[:5]
            round_trace = ttrace.coerce_context(msg[5] if len(msg) > 5
                                                else None)
            round_t0 = _monotonic()
            items = _unpack_items(items)
            telemetry.counter("dataplane.chunks_in").inc()
            telemetry.counter("dataplane.rows_in").inc(len(items))
            if self.queues.get("state") == "terminating":
                return ("ok", None, "terminating")
            q = self.queues.get_queue(qname_in)
            for item in (*items, EndPartition(trace=round_trace)):
                state = self._put_responsive(q, item)
                if state is not None:
                    return (state if state[0] == "err"
                            else ("ok", None, "terminating"))
            qo = self.queues.get_queue(qname_out)
            results: list = []
            deadline = _monotonic() + min(float(wait), self.feed_timeout)
            while len(results) < len(items):
                if self.queues.get("state") == "terminating":
                    return ("ok", None, "terminating")
                remaining = deadline - _monotonic()
                if remaining <= 0:
                    return ("err", f"infer_round produced {len(results)}/"
                                   f"{len(items)} results within {wait}s")
                try:
                    _extend_results(results,
                                    qo.get(block=True,
                                           timeout=min(0.5, remaining)))
                except queue.Empty:  # toslint: allow-silent(bounded poll slice; the while loop re-checks state and deadline)
                    pass
            ttrace.record_child("serve.node_round", round_trace, round_t0,
                                _monotonic() - round_t0,
                                {"rows": len(items)})
            return ("ok", results, "running")
        if op == "collect":
            # Pop up to max_n inference results: block briefly for the first,
            # then drain whatever is already there.  Short by construction.
            # A ResultChunk flattens to its per-item results (the serving
            # loop ships each batch as one chunk; chunks never split across
            # collects — each belongs wholly to the in-flight partition).
            _, qname, max_n, wait = msg
            qo = self.queues.get_queue(qname)
            results: list = []
            try:
                _extend_results(results,
                                qo.get(block=True,
                                       timeout=min(float(wait), self.feed_timeout)))
                while len(results) < int(max_n):
                    _extend_results(results, qo.get_nowait())
            except queue.Empty:  # toslint: allow-silent(collect drains what is already there; empty just ends this round-trip)
                pass
            return ("ok", results)
        if op == "ring_setup":
            # Same-host fast path: move the request/reply stream onto a pair
            # of native shared-memory rings (shm_ring.py).  Only offered
            # after the TCP HMAC handshake has already authenticated the
            # peer; the rings themselves are 0600 same-user segments.
            try:
                from tensorflowonspark_tpu import shm_ring

                capacity = int(msg[1]) if len(msg) > 1 else 64 * 1024 * 1024
                c2s = shm_ring.ShmRing.create(capacity=capacity)
                s2c = shm_ring.ShmRing.create(capacity=capacity)
            except Exception as e:  # noqa: BLE001 - no compiler/shm: stay on TCP
                return ("err", f"ring unavailable: {e}")
            t = threading.Thread(target=self._serve_ring, args=(c2s, s2c),
                                 daemon=True, name="dataserver-ring")
            # prune finished threads so repeated ring setups (driver
            # reconnects/downgrades) don't accumulate dead Thread objects
            self._ring_threads = [r for r in self._ring_threads if r.is_alive()]
            self._ring_threads.append(t)
            t.start()
            return ("ok", c2s.name, s2c.name)
        if op == "close":
            return ("ok",)
        return ("err", f"unknown op {op!r}")

    def _serve_ring(self, c2s, s2c) -> None:
        from tensorflowonspark_tpu.shm_ring import RingClosed, RingTimeout

        unlinked = False
        try:
            while not self._stopped.is_set():
                try:
                    msg, was_vec = _ring_recv(c2s, timeout=1.0)
                except RingTimeout:
                    continue
                except RingClosed:
                    return
                if not unlinked:
                    # First message proves the client has mmap'd both rings:
                    # unlink the names eagerly so the segments can never
                    # outlive the processes (POSIX shm persists past process
                    # death until unlinked — 2x capacity leaked per abandoned
                    # pair otherwise).
                    c2s.unlink()
                    s2c.unlink()
                    unlinked = True
                try:
                    reply = self._handle(msg)
                except faultinject.FaultInjected:
                    # `sever` on the ring path: abandon the ring with no
                    # reply (finally runs close_write, so the driver sees a
                    # dead data plane, mirroring the TCP sever).
                    logger.warning("fault injection: severing ring data plane")
                    return
                except Exception as e:  # noqa: BLE001 - mirror TCP behaviour
                    logger.exception("dataserver ring op failed")
                    reply = ("err", f"{type(e).__name__}: {e}")
                # Bounded reply put: a client that detached without draining
                # would otherwise pin this thread (and the finally-cleanup)
                # forever.  Retry-with-short-timeout is only safe for a
                # single WHOLE record (a timed-out push commits nothing);
                # a segmented put that times out mid-stream leaves partial
                # segments in flight (shm_ring contract) — one bounded
                # attempt, then abandon the ring.
                vec_bufs = _ring_vec_record(reply) if was_vec else None
                if vec_bufs is not None and len(vec_bufs) > 2:
                    # buffer-carrying v2 reply: join-free segmented push,
                    # single bounded attempt (mid-stream timeout is fatal)
                    try:
                        s2c.put_buffers(vec_bufs, timeout=self.feed_timeout)
                    except RingTimeout:
                        logger.warning("ring client not draining a vectorized "
                                       "reply; abandoning ring")
                        return
                    if msg[0] == "close":
                        return
                    continue
                if vec_bufs is not None:
                    data = b"".join(vec_bufs)  # header+body only: tiny
                else:
                    data = pickle.dumps(reply, protocol=pickle.HIGHEST_PROTOCOL)
                if len(data) + 1 <= s2c.capacity // 2:
                    sent = False
                    deadline = _monotonic() + self.feed_timeout
                    while not sent and not self._stopped.is_set():
                        try:
                            s2c.put_bytes(data, timeout=5.0)
                            sent = True
                        except RingTimeout:
                            if _monotonic() > deadline:
                                logger.warning(
                                    "ring client not draining replies for "
                                    "%.0fs; abandoning ring", self.feed_timeout)
                                return
                    if not sent:
                        return
                else:
                    try:
                        s2c.put_bytes(data, timeout=self.feed_timeout)
                    except RingTimeout:
                        logger.warning("ring client not draining a segmented "
                                       "reply; abandoning ring")
                        return
                if msg[0] == "close":
                    return
        except (RingClosed, OSError):
            return
        finally:
            s2c.close_write()
            for ring in (c2s, s2c):
                ring.detach()
                if not unlinked:
                    ring.unlink()


class DataClient:
    """Driver-side connection to one node's DataServer."""

    def __init__(self, host: str, port: int, authkey: bytes, chunk_size: int = 512,
                 prefer_ring: bool = True, ring_capacity: int = 64 * 1024 * 1024,
                 call_timeout: float = 660.0, stall_timeout: float = 600.0,
                 connect_timeout: float = 60.0, connect_attempts: int | None = None,
                 send_window: int | None = None):
        self.chunk_size = chunk_size
        self.ring_capacity = ring_capacity
        # Inference stall budget: infer_partition raises when no item was
        # accepted AND no result arrived for this long (the reference's
        # feed_timeout semantics, applied driver-side now that individual
        # round-trips are short).
        self.stall_timeout = stall_timeout
        # Ring-path request/reply timeout.  Must exceed the server's
        # feed_timeout (its puts can legitimately block that long under
        # backpressure) but must be finite: if the node process is SIGKILLed
        # the ring's closed flag is never set, and an infinite wait would
        # wedge the whole driver data plane inside self._lock.
        self.call_timeout = call_timeout
        from tensorflowonspark_tpu.utils.envtune import env_bool, env_int
        from tensorflowonspark_tpu.utils.net import connect_with_backoff

        # Backoff on the dial (TOS_CONNECT_ATTEMPTS): a node mid-restart has
        # its data port dark for the backoff + re-register window; a
        # single-shot connect would turn every recovery into a hard failure.
        # Recovery loops that poll dial with short connect_timeout /
        # connect_attempts=1 instead, so one blackholed host cannot pin them
        # past their own deadline.
        self._sock = connect_with_backoff(
            (host, port), timeout=connect_timeout,
            attempts=(connect_attempts if connect_attempts is not None
                      else env_int("TOS_CONNECT_ATTEMPTS", 3)))
        self._sock.settimeout(None)
        self._lock = tos_named_lock("dataserver.client._lock")
        self._consumed_reported: dict[str, int] = {}
        if not _hmac_handshake_client(self._sock, authkey):
            self._sock.close()
            raise RuntimeError("data plane error: auth handshake failed")
        # Pipelined feed: max unacked chunk frames in flight per connection
        # (TOS_SEND_WINDOW).  1 restores strict request/reply ping-pong.
        self.send_window = (send_window if send_window is not None
                            else env_int("TOS_SEND_WINDOW", 4))
        # Optional send-burst permit factory (the driver's TOS_SENDER_POOL
        # feed pump): acquired around individual chunk sends — never across
        # a whole partition round-trip, where one stalled node's
        # backpressure (or inference compute) would pin a permit and starve
        # every other connection.
        self.sender_gate = contextlib.nullcontext
        self._wire = self._negotiate_wire()
        self._c2s = self._s2c = None
        if prefer_ring:
            # TOS_SHM_RING: unset -> one-shot measured probe decides
            # (utils.net.ring_beats_loopback); "1"/"0" force either way.
            # A junk value must degrade to the documented default (probe),
            # never silently force a transport: env_bool falls back to its
            # default on junk, so two reads with opposite defaults agreeing
            # is the "parsed cleanly" signal.
            from tensorflowonspark_tpu.utils.envtune import env_str

            forced: bool | None = None
            if env_str("TOS_SHM_RING", ""):
                as_true = env_bool("TOS_SHM_RING", True)
                forced = as_true if as_true == env_bool("TOS_SHM_RING", False) \
                    else None
            if forced is not False:
                self._try_ring_setup(host, probe=forced is None)
        # transport selection, one count per client connection (the ring
        # probe decision is otherwise invisible outside debug logs)
        telemetry.counter("dataplane.clients_ring" if self.using_ring
                          else "dataplane.clients_tcp").inc()

    def _negotiate_wire(self) -> int:
        """Probe the server's wire version with a v1 ``hello``: a current
        server answers ("ok", version); an old one answers unknown-op —
        either way the stream stays consistent and we know what to SEND."""
        # Runs inside __init__, before this client is visible to any other
        # thread — the exchange needs no lock (taking one here would also be
        # the blocking-I/O-under-lock pattern lock-discipline flags).
        try:
            self._sock.settimeout(min(30.0, self.call_timeout))
            try:
                _send(self._sock, ("hello", WIRE_VERSION))
                reply = _recv(self._sock)
            finally:
                with contextlib.suppress(OSError):
                    self._sock.settimeout(None)
            if isinstance(reply, tuple) and len(reply) >= 2 and reply[0] == "ok":
                return max(1, min(WIRE_VERSION, int(reply[1])))
        except (ValueError, TypeError):
            logger.debug("malformed hello reply; staying on wire v1",
                         exc_info=True)
        return 1

    def _try_ring_setup(self, host: str, probe: bool = False) -> None:
        """Upgrade to shared-memory rings when the node is on this host."""
        from tensorflowonspark_tpu.utils.net import local_ip, ring_beats_loopback

        if host not in ("127.0.0.1", "localhost", local_ip()):
            return
        try:
            from tensorflowonspark_tpu import shm_ring

            if not shm_ring.available():
                return
            if probe and not ring_beats_loopback():
                # measured slower than loopback TCP on this host: never
                # silently pick the slower transport (VERDICT r5 weak #5)
                return
            with self._lock:
                _send(self._sock, ("ring_setup", self.ring_capacity), self._wire)
                reply = _recv(self._sock)
            if not (isinstance(reply, tuple) and reply[0] == "ok"):
                return
            self._c2s = shm_ring.ShmRing.attach(reply[1])
            self._s2c = shm_ring.ShmRing.attach(reply[2])
            logger.info("data plane upgraded to shm ring (%s)", reply[1])
        except Exception:  # noqa: BLE001 - any failure: stay on TCP
            logger.debug("shm ring setup failed; using TCP", exc_info=True)
            self._c2s = self._s2c = None

    @property
    def using_ring(self) -> bool:
        return self._c2s is not None

    def _check(self, reply: tuple) -> tuple:
        if not (isinstance(reply, tuple) and reply and reply[0] == "ok"):
            raise RuntimeError(f"data plane error: {reply[1] if len(reply) > 1 else reply!r}")
        return reply

    def _call(self, msg: tuple, timeout: float | None = None) -> tuple:
        timeout = self.call_timeout if timeout is None else timeout
        with self._lock:
            if self._c2s is not None:
                try:
                    _ring_send(self._c2s, msg, self._wire, timeout)
                except (EOFError, TimeoutError, OSError, ValueError):
                    # Send failed ⇒ the server never saw the request: safe to
                    # downgrade to the healthy TCP socket and retry there.
                    logger.warning("shm ring send failed; downgrading to TCP",
                                   exc_info=True)
                    self._teardown_ring()
                else:
                    try:
                        return self._check(_ring_recv(self._s2c, timeout)[0])
                    except (EOFError, TimeoutError, OSError, ValueError) as e:
                        # Reply path failed AFTER the server may have acted:
                        # retrying could double-feed, so surface the error.
                        # Future calls use TCP.
                        self._teardown_ring()
                        raise RuntimeError(
                            f"data plane error: ring reply lost ({e})") from e
            # TCP path honors the same bound: the socket is otherwise
            # blocking, and e.g. a short-timeout EOF must not wait forever
            # on a wedged (but alive) node.
            self._sock.settimeout(timeout)
            try:
                _send(self._sock, msg, self._wire)
                return self._check(_recv(self._sock))
            except (TimeoutError, OSError):
                # the stream may now hold a partial frame or a late reply;
                # reusing it would hand a future call the WRONG response —
                # poison the socket (mirror of _teardown_ring)
                with contextlib.suppress(OSError):
                    self._sock.close()
                raise
            finally:
                with contextlib.suppress(OSError):
                    self._sock.settimeout(None)

    def _teardown_ring(self) -> None:
        if self._c2s is not None:
            telemetry.counter("dataplane.ring_downgrades").inc()
            for ring in (self._c2s, self._s2c):
                try:
                    ring.detach()
                except Exception:  # noqa: BLE001  # toslint: allow-silent(downgrade path: the ring is already failed, TCP takes over either way)
                    pass
            self._c2s = self._s2c = None

    def _pack_items(self, chunk: list) -> Any:
        """Columnar-pack a chunk for the v2 wire (``data.pack_chunk``); v1
        peers (and unpackable chunks) get the plain row list — with any
        stray zero-copy views materialized to bytes first (sub-threshold
        memoryview records fall out of packing, and plain pickle cannot
        serialize memoryview at all)."""
        if self._wire >= 2:
            packed = _pack_chunk(chunk)
            if packed is not None:
                return packed
            return _materialize_views(chunk)
        telemetry.counter("dataplane.chunks_legacy_wire").inc()
        return _materialize_views(chunk)

    def feed_partition(self, items: Iterable[Any], qname: str = "input",
                       task_key: Any = None, trace: Any = None) -> str:
        """Stream one partition; returns final node state
        ('running'/'terminating').  ``task_key`` identifies the logical
        partition (the driver ledger's (epoch, partition)) so the node's
        consumption watermark counts an at-least-once re-feed of the same
        partition exactly once (see ``marker.EndPartition``).  ``trace``
        (a sampled partition's trace context) rides the EndPartition on a
        v3 wire so the node's partition-consume span joins the trace.

        Chunks are PIPELINED: up to ``send_window`` chunk frames ride the
        transport before their acks are read, so the sender never idles a
        round-trip per chunk (the driver's feed pump runs one such sender
        per node connection).  Any mid-burst failure poisons the transport
        and raises — the partition ledger's at-least-once re-feed owns
        recovery, exactly as it does for the unpipelined path.
        """
        state = self._stream_chunks(items, qname)
        msg = (("end_partition", qname, task_key, tuple(trace))
               if trace is not None and self._wire >= 3
               else ("end_partition", qname, task_key))
        reply = self._call(msg)
        if len(reply) > 1:
            # node's consumption watermark as of this partition's EndPartition
            # placement (see DataServer end_partition)
            self._consumed_reported[qname] = int(reply[1])
        return state

    def _stream_chunks(self, items: Iterable[Any], qname: str) -> str:
        with self._lock:
            if self._c2s is not None:
                try:
                    return self._pump_chunks(
                        lambda m: _ring_send(self._c2s, m, self._wire,
                                             self.call_timeout),
                        lambda: _ring_recv(self._s2c, self.call_timeout)[0],
                        items, qname)
                except (EOFError, TimeoutError, OSError, ValueError,
                        RuntimeError) as e:
                    # A pipelined burst cannot tell a lost send from a lost
                    # reply, and an err reply leaves unread acks behind: the
                    # ring state is unknown either way — drop to TCP for
                    # future calls and let the ledger re-feed the partition.
                    self._teardown_ring()
                    if isinstance(e, RuntimeError):
                        raise
                    raise RuntimeError(
                        f"data plane error: ring feed failed ({e})") from e
            self._sock.settimeout(self.call_timeout)
            try:
                return self._pump_chunks(
                    lambda m: _send(self._sock, m, self._wire),
                    lambda: _recv(self._sock), items, qname)
            except (TimeoutError, OSError, RuntimeError):
                # mid-burst failure (or an err reply with acks still unread):
                # the stream holds frames a future call would misread —
                # poison the socket (mirror of _call's error path)
                with contextlib.suppress(OSError):
                    self._sock.close()
                raise
            finally:
                with contextlib.suppress(OSError):
                    self._sock.settimeout(None)

    def _pump_chunks(self, send, recv, items: Iterable[Any], qname: str) -> str:
        window = max(1, int(self.send_window))
        outstanding = 0
        state = "running"
        chunks_sent = rows_sent = 0
        occupancy = telemetry.gauge("dataplane.send_window_occupancy")

        def drain_one() -> None:
            nonlocal outstanding, state
            reply = self._check(recv())
            outstanding -= 1
            occupancy.set(outstanding)
            if len(reply) > 1 and reply[1] == "terminating":
                state = "terminating"

        chunk: list = []
        for item in items:
            chunk.append(item)
            if len(chunk) >= self.chunk_size:
                with self.sender_gate():
                    send(("feed", qname, self._pack_items(chunk)))
                chunks_sent += 1
                rows_sent += len(chunk)
                chunk = []
                outstanding += 1
                occupancy.set(outstanding)
                while outstanding >= window:
                    drain_one()
                if state == "terminating":
                    break  # consumer is done; drop the rest fast
        if chunk and state != "terminating":
            with self.sender_gate():
                send(("feed", qname, self._pack_items(chunk)))
            chunks_sent += 1
            rows_sent += len(chunk)
            outstanding += 1
            occupancy.set(outstanding)
        while outstanding:
            drain_one()
        telemetry.counter("dataplane.chunks_sent").inc(chunks_sent)
        telemetry.counter("dataplane.rows_sent").inc(rows_sent)
        return state

    def forward_chunks(self, chunks: list, qname: str = "input") -> str:
        """Push pre-decoded ``data.DecodedChunk`` items into the node's
        input queue (the ingest-worker -> trainer hot path); returns the
        node state ('running'/'terminating').  One bounded round-trip per
        call — the reply IS the delivery ack the worker's consumption
        watermark advances on, so a chunk is never reported consumed
        before a trainer has actually buffered it."""
        reply = self._call(("chunk_fwd", qname, list(chunks)))
        return reply[1] if len(reply) > 1 else "running"

    def partitions_consumed(self, qname: str = "input") -> int | None:
        """The node's cumulative fully-consumed-partition count as of the
        last ``feed_partition`` ack on ``qname`` (None before the first)."""
        return self._consumed_reported.get(qname)

    def poll_consumed(self, qname: str = "input", timeout: float = 10.0) -> int:
        """Round-trip the node's CURRENT consumption watermark (tail-drain
        path: no feed acks are left to piggyback it on)."""
        return int(self._call(("consumed", qname), timeout=timeout)[1])

    def infer_partition(self, items: Iterable[Any], qname_in: str = "input", qname_out: str = "output") -> list:
        """Round-trip one partition; returns exactly-count ordered results.

        Sending and collecting interleave in bounded sub-second calls, so
        results stream back while later items are still being fed (and the
        output queue can never deadlock the input feed).  Raises if no
        progress happens for ``stall_timeout`` seconds.
        """
        items = list(items)
        results: list = []
        pos, end_placed = 0, False
        last_progress = _monotonic()
        while pos < len(items) or not end_placed or len(results) < len(items):
            progressed = False
            if pos < len(items) or not end_placed:
                chunk = items[pos : pos + self.chunk_size]
                want_end = pos + len(chunk) >= len(items)
                with self.sender_gate():
                    # permit covers ONE bounded-hold send round-trip (~2s
                    # server budget), never the collect/compute side
                    _, accepted, placed, state = self._call(
                        ("infer_send", qname_in, self._pack_items(chunk),
                         want_end))
                if state == "terminating":
                    raise RuntimeError(
                        "data plane error: node terminated mid-inference "
                        f"({len(results)}/{len(items)} results)")
                pos += accepted
                end_placed = end_placed or placed
                progressed = accepted > 0 or placed
            if len(results) < len(items):
                got = self._call(("collect", qname_out,
                                  min(self.chunk_size, len(items) - len(results)),
                                  2.0))[1]
                results.extend(got)
                progressed = progressed or bool(got)
            if progressed:
                last_progress = _monotonic()
            elif _monotonic() - last_progress > self.stall_timeout:
                raise RuntimeError(
                    f"data plane error: inference produced {len(results)}/"
                    f"{len(items)} results before {self.stall_timeout}s stall timeout")
        return results

    def infer_round(self, items: Iterable[Any], qname_in: str = "input",
                    qname_out: str = "output",
                    wait: float | None = None, trace: Any = None) -> list:
        """Score one micro-batch in a SINGLE round-trip (serving hot path):
        the server feeds the items, waits for the map_fun's results, and
        the reply carries them — no separate collect polling.  Returns
        exactly-count ordered results; raises when the node is terminating
        or the round times out.  ``trace`` (the sampled batch's context)
        is appended on a v3 wire so the node records its side of the round.
        Requires a server with the ``infer_round`` op (this build); the
        chunked send/collect pair remains the right tool for big batch
        partitions."""
        items = list(items)
        wait = self.stall_timeout if wait is None else wait
        # no sender_gate permit: the round spans node COMPUTE, and the gate
        # contract forbids holding a send permit across anything but a send
        msg = (("infer_round", qname_in, qname_out,
                self._pack_items(items), wait, tuple(trace))
               if trace is not None and self._wire >= 3
               else ("infer_round", qname_in, qname_out,
                     self._pack_items(items), wait))
        reply = self._call(msg)
        if len(reply) > 2 and reply[2] == "terminating":
            raise RuntimeError(
                "data plane error: node terminated mid-inference round")
        return reply[1]

    def collect_results(self, qname_out: str = "output", max_n: int = 64,
                        wait: float = 2.0) -> list:
        """Pop up to ``max_n`` already-available inference results (bounded
        wait for the first; ResultChunks flattened).  The serving router's
        re-admission resync drains abandoned-round leftovers with this."""
        return list(self._call(("collect", qname_out, int(max_n),
                                float(wait)))[1])

    def send_eof(self, qname: str = "input", timeout: float | None = None) -> None:
        """EOF is a teardown-path control message: the node replies within
        milliseconds or is gone — never wait the full feed timeout on it
        (a node may exit between the driver's liveness check and this call).
        Default budget 20s, env-overridable via ``TOS_EOF_TIMEOUT``."""
        if timeout is None:
            from tensorflowonspark_tpu.utils.envtune import env_float

            timeout = env_float("TOS_EOF_TIMEOUT", 20.0)
        self._call(("eof", qname), timeout=timeout)

    def abort(self) -> None:
        """Lockless immediate teardown (the monitor's death path): wake any
        thread wedged inside ``_call`` by shutting the socket down under it.
        ``close()`` would first wait on the per-client lock that thread holds
        for its full call timeout (~11 min against a dead ring peer) —
        exactly the stall a death declaration exists to cut short."""
        c2s, s2c = self._c2s, self._s2c
        self._c2s = self._s2c = None
        if c2s is not None:
            with contextlib.suppress(Exception):
                c2s.close_write()
                c2s.detach()
                s2c.detach()
        with contextlib.suppress(OSError):
            self._sock.shutdown(socket.SHUT_RDWR)
        with contextlib.suppress(OSError):
            self._sock.close()

    def close(self) -> None:
        if self._c2s is not None:
            try:
                self._c2s.close_write()  # ring server drains, then cleans up
                self._c2s.detach()
                self._s2c.detach()
            except Exception:  # noqa: BLE001
                logger.debug("ring teardown failed during close", exc_info=True)
            self._c2s = self._s2c = None
        try:
            with self._lock:
                # Bounded, unlike the old bare blocking recv: the lockgraph
                # shows cluster.resize and gateway.reload reach this lock
                # while holding their own (cluster._resize_lock /
                # gateway._reload_lock -> dataserver.client._lock), so a
                # wedged-but-alive node must not pin close() — and those
                # callers — forever.
                self._sock.settimeout(min(10.0, self.call_timeout))
                _send(self._sock, ("close",))
                try:
                    _recv(self._sock)
                except (ConnectionError, OSError, EOFError):  # toslint: allow-silent(best-effort close ack; the node may already be gone)
                    pass
        except OSError:  # toslint: allow-silent(best-effort teardown; socket close below is what matters)
            pass
        finally:
            self._sock.close()
