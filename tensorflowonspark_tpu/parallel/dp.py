"""Sync SPMD data-parallel training — the ParameterServer/MWMS replacement.

Reference (SURVEY.md §2.3): data parallelism via async parameter servers
(``tf.train.replica_device_setter``) or ``MultiWorkerMirroredStrategy``
(NCCL all-reduce), both configured through the ``TF_CONFIG`` env var TFoS
wrote.  TPU-native replacement (BASELINE.json:5): one jitted SPMD program
over a named mesh; the gradient all-reduce is emitted by XLA over ICI from
sharding annotations — there are no server objects, no strategy classes, and
no NCCL.

Usage::

    mesh = make_mesh(dp=-1)
    state = replicate(TrainState.create(params, optax.adam(1e-3)), mesh)
    step = make_train_step(loss_fn, optimizer)
    for batch in feed:
        state, metrics = step(state, shard_batch(mesh, batch))

``loss_fn(params, batch) -> (loss, aux_metrics)`` is the user contract.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from tensorflowonspark_tpu.parallel.mesh import batch_sharding, replicated


class TrainState(NamedTuple):
    """Minimal functional train state (params + optimizer state + step)."""

    params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params: Any, optimizer: optax.GradientTransformation) -> "TrainState":
        return cls(params=params, opt_state=optimizer.init(params), step=jnp.zeros((), jnp.int32))


def replicate(tree: Any, mesh) -> Any:
    """Place a pytree fully-replicated on the mesh (pure data parallelism).

    Copies through host memory on purpose: ``jax.device_put`` may alias the
    source buffer as one replica, and the train step *donates* its state —
    donation through an alias would silently delete the caller's original
    arrays.  Host-staging guarantees fresh device buffers and also accepts
    sources committed to any device subset (e.g. an orbax restore on device
    0).  This runs once at job start; the copy cost is irrelevant.

    Works on multi-process meshes too (every host holds the same full value;
    assembly is delegated to ``mesh.shard_tree``).
    """
    from tensorflowonspark_tpu.parallel.mesh import shard_tree

    sharding = replicated(mesh)
    return shard_tree(mesh, tree, jax.tree.map(lambda _: sharding, tree))


def make_train_step(
    loss_fn: Callable[[Any, Any], tuple[jax.Array, dict]],
    optimizer: optax.GradientTransformation,
    donate: bool = True,
    accum_steps: int = 1,
    cross_host_grad_fn: Callable[[Any], Any] | None = None,
) -> Callable[[TrainState, Any], tuple[TrainState, dict]]:
    """Build the jitted SPMD train step.

    The batch arrives sharded over the ``(dp, fsdp)`` axes and params arrive
    replicated (or fsdp-sharded); XLA partitions the forward/backward and
    inserts the gradient all-reduce over ICI automatically.  Metrics come
    back replicated scalars (already globally reduced, since the loss is a
    mean over the global batch).

    ``accum_steps > 1`` enables gradient accumulation: the global batch is
    split into that many microbatches along axis 0 and run through a
    ``lax.scan`` (one compiled microstep body, not an unrolled loop);
    gradients/metrics are averaged and the optimizer applies ONE update.
    The per-call batch size must be divisible by ``accum_steps``.

    Equivalence caveat: the accumulated step averages each microbatch's
    ALREADY-NORMALIZED loss gradient.  For losses that are plain means over
    examples this equals the full-batch step exactly; for losses with
    data-dependent normalization (e.g. ``loss_mask`` token averaging, where
    each microbatch divides by its own mask count) the weighting differs —
    microbatches with few unmasked tokens count more per token.  For masked
    LM training either keep mask density uniform across microbatches or use
    ``accum_steps=1``.

    ``cross_host_grad_fn`` composes the step with CROSS-HOST data
    parallelism over the cluster wire (``cluster.train(mode="sync")``): a
    host callable (e.g. ``CollectiveGroup.grad_fn()``) applied to the
    gradient pytree between backward and update — typically a bucketed
    ring all-reduce averaging gradients across nodes.  The step then
    compiles as TWO jitted halves (grads+metrics, then update) sharing the
    same optimizer code, with the exchange on host in between; each half
    compiles once, and the hook's bucket pipeline overlaps communication
    with the device->host tail of backprop.  ``None`` keeps the
    single-program step byte-for-byte as before.
    """

    def grads_and_metrics(params: Any, batch: Any) -> tuple[Any, dict]:
        if accum_steps == 1:
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch)
            return grads, {"loss": loss, **aux}
        micro = jax.tree.map(
            lambda x: x.reshape(accum_steps, x.shape[0] // accum_steps,
                                *x.shape[1:]), batch)

        def body(carry, mb):
            grads_acc, metrics_acc = carry
            (l, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(
                params, mb)
            m = {"loss": l, **aux}
            return (jax.tree.map(jnp.add, grads_acc, g),
                    jax.tree.map(jnp.add, metrics_acc, m)), None

        # Carry structure from an abstract eval — loss_fn is traced once
        # (inside the scan body), not twice.
        loss_sd, aux_sd = jax.eval_shape(
            loss_fn, params, jax.tree.map(lambda x: x[0], micro))
        zeros = lambda sd: jnp.zeros(sd.shape, sd.dtype)  # noqa: E731
        init = (jax.tree.map(jnp.zeros_like, params),
                jax.tree.map(zeros, {"loss": loss_sd, **aux_sd}))
        (grads, msum), _ = jax.lax.scan(body, init, micro)
        grads = jax.tree.map(lambda g: g / accum_steps, grads)
        metrics = jax.tree.map(lambda m: m / accum_steps, msum)
        return grads, metrics

    def apply_update(state: TrainState, grads: Any) -> TrainState:
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1)

    if cross_host_grad_fn is None:
        def step(state: TrainState, batch: Any) -> tuple[TrainState, dict]:
            grads, metrics = grads_and_metrics(state.params, batch)
            return apply_update(state, grads), metrics

        # Shardings are inferred from operand placement (replicated params +
        # dp-sharded batch ⇒ XLA partitions the step and all-reduces grads).
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    grad_step = jax.jit(grads_and_metrics)
    apply_step = jax.jit(apply_update, donate_argnums=(0,) if donate else ())

    def hooked_step(state: TrainState, batch: Any) -> tuple[TrainState, dict]:
        grads, metrics = grad_step(state.params, batch)
        grads = cross_host_grad_fn(grads)
        return apply_step(state, grads), metrics

    return hooked_step


class BNTrainState(NamedTuple):
    """Train state for models with mutable normalization stats (ResNet/BN)."""

    params: Any
    batch_stats: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params: Any, batch_stats: Any,
               optimizer: optax.GradientTransformation) -> "BNTrainState":
        return cls(params=params, batch_stats=batch_stats,
                   opt_state=optimizer.init(params), step=jnp.zeros((), jnp.int32))


def make_bn_train_step(
    loss_fn: Callable[[Any, Any, Any], tuple[jax.Array, tuple[Any, dict]]],
    optimizer: optax.GradientTransformation,
    donate: bool = True,
) -> Callable[[BNTrainState, Any], tuple[BNTrainState, dict]]:
    """Jitted SPMD train step for BN models.

    ``loss_fn(params, batch_stats, batch) -> (loss, (new_batch_stats, aux))``.
    Under GSPMD the BN batch reductions over the dp-sharded axis compile to
    global cross-replica reductions — sync BatchNorm for free.
    """

    def step(state: BNTrainState, batch: Any) -> tuple[BNTrainState, dict]:
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (loss, (batch_stats, aux)), grads = grad_fn(state.params, state.batch_stats, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, **aux}
        return BNTrainState(params, batch_stats, opt_state, state.step + 1), metrics

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_eval_step(
    apply_fn: Callable[[Any, Any], jax.Array],
) -> Callable[[Any, Any], jax.Array]:
    """Jitted inference step: params + sharded inputs -> outputs."""
    return jax.jit(apply_fn)


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy over the (global) batch."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))


def make_batch_iterator(
    feed,
    batch_size: int,
    to_arrays: Callable[[list], Any],
    mesh=None,
    ctx=None,
    pad_to_batch: bool = True,
    prefetch: int = 2,
    max_steps: int | None = -1,
    lockstep: bool | None = None,
):
    """Drain a DataFeed into device-ready, mesh-sharded batches.

    Handles the sync-SPMD end-of-data problem (SURVEY.md §7.3-1): partial
    final batches are padded (repeating the last sample) and, when ``ctx`` is
    given, a control-plane ``all_done`` consensus decides when *all* hosts
    stop — no host may exit the step loop early.

    ``prefetch`` > 0 double-buffers the host side (SURVEY.md §7.3-6): a
    background thread drains the feed, converts (``to_arrays``) and starts
    the host→device transfer (``shard_batch``) for batch N+1 while the
    caller's jitted step N is still executing — the conversion/transfer cost
    disappears behind the device step instead of serializing with it.  Set
    ``prefetch=0`` for strictly synchronous delivery.

    Weighting caveat (applies to the final batches of any uneven run): PAD
    rows (partial final batch) and FILLER rows (a dry host's lockstep
    batches, ``n=0``) participate in the global loss mean like real rows —
    duplicated last-sample data carries gradient mass for those few steps.
    This mirrors the reference's padded-batch semantics; for strictly
    unbiased tails either shard data evenly across hosts, or use the
    returned ``n`` to weight/skip the update (``n`` is per-HOST; a filler
    round has ``n=0``).

    ``max_steps`` >= 0 caps the number of yielded batches (the pipeline
    layer's ``steps`` Param; reference ``args.steps`` semantics —
    ``None`` and ``-1`` both mean uncapped, so ``args.get("steps")`` can be
    passed straight through).  On
    reaching the cap the host behaves exactly as if its feed ran dry: the
    feed is ``terminate()``d (upstream streaming stops fast), the host keeps
    voting in the ``all_done`` consensus, and on a multi-process mesh it
    keeps joining the remaining global steps with filler batches — so a
    capped host never deadlocks uncapped peers.

    ``lockstep`` forces the multi-process yield discipline (identical batch
    counts on every host, filler batches after a host's feed runs dry)
    WITHOUT a multi-process mesh — the shape cross-host collective training
    (``cluster.train(mode="sync")`` + ``cross_host_grad_fn``) needs: every
    global step carries a cluster-wide gradient all-reduce, so a host that
    stopped yielding early would wedge its peers mid-collective exactly
    like a missing ``jax.distributed`` participant would.  Default
    ``None`` keeps the old rule (lockstep iff the mesh spans processes).
    """
    inner = _batch_iterator(feed, batch_size, to_arrays, mesh, ctx,
                            pad_to_batch,
                            -1 if max_steps is None else int(max_steps),
                            lockstep)
    if prefetch <= 0:
        yield from inner
        return
    yield from _prefetch_iterator(inner, prefetch)


def _prefetch_iterator(inner, depth: int):
    """Run ``inner`` on a background thread through a bounded queue.

    An abandoned consumer (early ``break`` → ``GeneratorExit``) must not
    leave the producer blocked on a full queue holding the feed: ``close()``
    sets a stop flag and drains, and the producer re-checks it around every
    put.  Producer exceptions re-raise at the consumer's next pull — the same
    point they would have surfaced unprefetched.
    """
    import queue as _queue
    import threading

    q: _queue.Queue = _queue.Queue(maxsize=depth)
    stop = threading.Event()
    END = object()
    failure: list[BaseException] = []

    def _produce() -> None:
        try:
            for item in inner:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except _queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 - re-raised consumer-side
            failure.append(e)
        finally:
            inner.close()
            while not stop.is_set():
                try:
                    q.put(END, timeout=0.1)
                    break
                except _queue.Full:
                    continue

    thread = threading.Thread(target=_produce, name="batch-prefetch", daemon=True)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is END:
                break
            yield item
        if failure:
            raise failure[0]
        thread.join()
    finally:
        stop.set()
        thread.join(timeout=30.0)


def _batch_iterator(
    feed,
    batch_size: int,
    to_arrays: Callable[[list], Any],
    mesh=None,
    ctx=None,
    pad_to_batch: bool = True,
    max_steps: int = -1,
    lockstep: bool | None = None,
):
    from tensorflowonspark_tpu.parallel.mesh import is_multiprocess, shard_batch

    if getattr(feed, "input_mapping", None):
        raise ValueError(
            "make_batch_iterator needs row-shaped batches; construct the "
            "DataFeed without input_mapping and map columns in to_arrays"
        )
    # Multi-host SPMD (jax.distributed + a mesh spanning processes): every
    # process runs ONE jitted global step per consensus round, so the number
    # of yielded batches must be identical on every host.  A host whose feed
    # runs dry before the others keeps yielding FILLER batches (its last real
    # sample repeated, reported as n=0) until the all_done consensus turns
    # true — if it just skipped rounds, the still-active hosts would enter
    # the next collective without it and the job would hang (SURVEY.md
    # §5.8-3; the reference's MWMS had the same no-early-exit constraint).
    multiproc = (bool(lockstep) if lockstep is not None
                 else mesh is not None and is_multiprocess(mesh))
    if multiproc and ctx is None:
        raise ValueError(
            "lockstep (multi-process mesh / cross-host sync) streaming "
            "requires ctx: the all_done consensus is what keeps per-host "
            "global-step counts in lockstep"
        )
    if multiproc and not pad_to_batch:
        raise ValueError(
            "lockstep streaming requires pad_to_batch=True: every "
            "host must contribute the same local batch shape or the global "
            "step (batch assembly / gradient collective) diverges"
        )
    last_item = None   # filler source for multi-process end-of-data rounds
    exhausted = False  # feed hit end-of-feed: NEVER call next_batch again
    dry = False        # exhausted and nothing left to yield
    yielded = 0
    pending = None     # pipelined consensus vote from the previous round
    try:
        while True:
            if max_steps >= 0 and yielded >= max_steps and not dry:
                # steps cap: behave exactly like end-of-data from here on —
                # terminate the feed (upstream streaming stops fast, reference
                # args.steps semantics) and vote dry in the consensus.
                terminate = getattr(feed, "terminate", None)
                if terminate is not None and not exhausted:
                    terminate()
                exhausted = dry = True
            items: list = []
            if not dry:
                if not exhausted:
                    items = feed.next_batch(batch_size)
                    # EndOfFeed can arrive mid-batch: a non-empty partial batch
                    # with should_stop() set must still be trained on, but one
                    # more next_batch() call would block forever.
                    exhausted = feed.should_stop()
                dry = exhausted and not items
            if ctx is not None:
                # One consensus round per step: active hosts vote False once
                # per batch; dry hosts keep voting True (without touching the
                # feed) until everyone is dry, so no host exits the SPMD loop
                # early.  The vote is PIPELINED for active hosts (VERDICT r4
                # weak #2): they send their vote, run the training step while
                # the rendezvous resolves, and read the result here at the
                # top of the next round — the control-plane RTT hides behind
                # step compute instead of adding to it.  A dry host resolves
                # synchronously (blocking is free when there is nothing to
                # train), so exit timing and yield counts are IDENTICAL to
                # the fully-synchronous protocol: an all-dry consensus is
                # only ever observed by dry hosts, which return before
                # yielding any extra filler.
                if pending is not None:
                    prev, pending = pending(), None
                    if prev:
                        # impossible by construction: this host voted
                        # "active" in that generation and the reduce is
                        # kind="all"
                        raise RuntimeError(
                            "end-of-data consensus turned true in a round "
                            "this host voted active (protocol bug)")
                if dry:
                    if ctx.all_done(dry):
                        return
                else:
                    pending = ctx.all_done_begin(False)
            elif dry:
                return
            if not items and not multiproc:
                continue
            n = len(items)
            if not items:
                # multiproc: this host is dry (or drew an empty batch) but
                # other hosts still have data — join their global step with a
                # filler.
                if last_item is None:
                    raise RuntimeError(
                        "multi-process streaming: this host reached end-of-feed "
                        "before receiving any data; every data node needs at "
                        "least one sample to participate in the global SPMD step"
                    )
                items = [last_item] * batch_size
            else:
                last_item = items[-1]
            if pad_to_batch and len(items) < batch_size:
                items = list(items) + [items[-1]] * (batch_size - len(items))
            batch = to_arrays(items)
            if mesh is not None:
                batch = shard_batch(mesh, batch)
            yield batch, n
            yielded += 1
    finally:
        if pending is not None and ctx is not None:
            # The caller abandoned the iterator (break / exception in its
            # train step) with a vote in flight; the unread reply would
            # desync any future consensus on this connection — drop it.
            ctx._reset_consensus_client()
