"""Expert parallelism: mixture-of-experts FFN sharded over the ``ep`` axis.

Absent in the reference (SURVEY.md §2.3).  TPU-idiomatic MoE is the
GShard/Switch einsum formulation: top-k routing with a *static* per-expert
capacity, dispatch/combine as one-hot einsums (MXU-friendly, no dynamic
shapes), expert-stacked weights with the expert dimension sharded over
``ep`` — GSPMD turns the dispatch einsums into all-to-alls over ICI.
Overflow tokens beyond capacity are dropped (their combine weight is zero),
the standard capacity-factor trade-off.

``MoEMLP`` is a flax module usable standalone or inside
``models/transformer.py``; the load-balancing auxiliary loss is sown into
the ``"aux_loss"`` collection (fetch with ``mutable=["aux_loss"]``).
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.parallel.tp import constrain


def _one_hot(x, n):
    return jax.nn.one_hot(x, n, dtype=jnp.float32)


class MoEMLP(nn.Module):
    """Top-k routed SwiGLU MoE FFN, ``[B, S, D] -> [B, S, D]``.

    Param layout (matched by ``tp.TRANSFORMER_TP_RULES``): ``router/kernel``
    replicated; ``experts_gate``/``experts_up`` ``[E, D, F]`` and
    ``experts_down`` ``[E, F, D]`` sharded ``P('ep', …)`` (+ tp on F).
    """

    d_model: int
    d_ff: int
    n_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        n = b * s
        e = self.n_experts
        xf = x.reshape(n, d)

        router = nn.Dense(e, use_bias=False, name="router",
                          dtype=jnp.float32)  # routing always f32
        probs = jax.nn.softmax(router(xf.astype(jnp.float32)), axis=-1)
        top_p, top_idx = jax.lax.top_k(probs, self.top_k)         # [n, k]
        top_p = top_p / jnp.maximum(jnp.sum(top_p, -1, keepdims=True), 1e-9)

        capacity = max(1, int(math.ceil(n * self.capacity_factor
                                        * self.top_k / e)))

        # GShard dispatch: slots are filled in top-k priority order; a
        # token's j-th choice only lands if the expert still has room after
        # all higher-priority assignments.
        counts = jnp.zeros((e,), jnp.float32)
        dispatch = jnp.zeros((n, e, capacity), jnp.float32)
        combine = jnp.zeros((n, e, capacity), jnp.float32)
        for j in range(self.top_k):
            oh = _one_hot(top_idx[:, j], e)                       # [n, e]
            pos = jnp.cumsum(oh, axis=0) - oh + counts[None, :]   # [n, e]
            keep = (pos < capacity).astype(jnp.float32) * oh
            counts = counts + jnp.sum(keep, axis=0)
            slot = _one_hot(jnp.sum(pos * oh, axis=-1).astype(jnp.int32),
                            capacity)                             # [n, c]
            d_j = keep[:, :, None] * slot[:, None, :]
            dispatch = dispatch + d_j
            combine = combine + d_j * top_p[:, j][:, None, None]

        # Load-balancing aux loss (Switch eq. 4): e · Σ_e f_e · P_e .
        frac_tokens = jnp.mean(_one_hot(top_idx[:, 0], e), axis=0)
        frac_probs = jnp.mean(probs, axis=0)
        self.sow("aux_loss", "load_balance",
                 e * jnp.sum(frac_tokens * frac_probs))

        w_gate = self.param("experts_gate", nn.initializers.lecun_normal(),
                            (e, d, self.d_ff))
        w_up = self.param("experts_up", nn.initializers.lecun_normal(),
                          (e, d, self.d_ff))
        w_down = self.param("experts_down", nn.initializers.lecun_normal(),
                            (e, self.d_ff, d))

        cdt = self.compute_dtype
        # The ep constraints make GSPMD materialise the token shuffle as
        # all-to-alls over the ep axis (tokens in, expert outputs back).
        expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(cdt),
                               xf.astype(cdt))
        expert_in = constrain(expert_in, P("ep", None, None))
        h = (jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in,
                                    w_gate.astype(cdt)))
             * jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(cdt)))
        h = constrain(h, P("ep", None, "tp"))
        out = jnp.einsum("ecf,efd->ecd", h, w_down.astype(cdt))
        out = constrain(out, P("ep", None, None))
        y = jnp.einsum("nec,ecd->nd", combine.astype(cdt), out)
        return y.reshape(b, s, d).astype(x.dtype)
