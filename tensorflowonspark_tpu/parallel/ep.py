"""Expert parallelism: mixture-of-experts FFN sharded over the ``ep`` axis.

Absent in the reference (SURVEY.md §2.3).  TPU-idiomatic MoE keeps the
GShard/Switch *static-capacity* contract (top-k routing, per-expert capacity
``c``, overflow dropped — no dynamic shapes anywhere) but dispatches with
**sorted indices** instead of the classic one-hot einsums: the einsum
formulation materializes ``[n, e, c]`` dispatch/combine tensors, which at
serious shapes (16k tokens × 64 experts × c=512) is ~2 GB *per tensor per
layer*; the sort formulation carries only ``[n·k]`` index/gate vectors and
scatters straight into the ``[e, c, d]`` expert buffers — the MegaBlocks /
modern-maxtext-style dropping dispatch, here with slot assignment matched
bit-for-bit to the GShard priority rule (see ``_sorted_dispatch``).

Expert-stacked weights keep the expert dimension sharded over ``ep``; the
``P('ep', …)`` constraints on the expert buffers make GSPMD materialize the
token shuffle as all-to-alls over ICI exactly as before.

``MoEMLP`` is a flax module usable standalone or inside
``models/transformer.py``.  Two auxiliary losses are sown into the
``"aux_loss"`` collection (fetch with ``mutable=["aux_loss"]``):
``load_balance`` (Switch eq. 4) and ``router_z`` (ST-MoE z-loss,
``mean(logsumexp(router_logits)^2)`` — keeps router logits from drifting
into f32-overflow territory); ``models.transformer.make_loss_fn`` weights
them independently.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.parallel.tp import constrain


def _one_hot(x, n):
    return jax.nn.one_hot(x, n, dtype=jnp.float32)


def _sorted_dispatch(top_idx, top_p, capacity: int, n_experts: int):
    """GShard slot assignment without one-hot tensors.

    Returns ``(slots, token_ids, gates, keep)``, each ``[n · k]`` flat over
    (choice-round j, sorted-token) pairs: ``slots`` is the flat
    expert-buffer slot (``expert · capacity + position``, or ``e ·
    capacity`` for dropped pairs), ``token_ids`` the source token of each
    pair, ``gates`` its normalized routing weight.

    Slot semantics are IDENTICAL to the classic priority-loop formulation
    (mesh-tf Switch / GShard): within round j, positions are assigned in
    token order (stable sort by expert id = rank within expert); rounds are
    processed in priority order, and only KEPT assignments from earlier
    rounds advance an expert's fill counter.  All shapes static; the sorts
    are ``[n]``-sized and jit-friendly.
    """
    n, k = top_idx.shape
    e = n_experts
    counts = jnp.zeros((e,), jnp.int32)       # kept fills per expert so far
    slots, toks, gates, keeps = [], [], [], []
    for j in range(k):
        eid = top_idx[:, j]
        order = jnp.argsort(eid, stable=True)
        sorted_eid = eid[order]
        starts = jnp.searchsorted(sorted_eid, jnp.arange(e), side="left")
        # rank of this pair within its expert (token order) + prior fills
        pos = jnp.arange(n) - starts[sorted_eid] + counts[sorted_eid]
        keep = pos < capacity
        slots.append(jnp.where(keep, sorted_eid * capacity + pos, e * capacity))
        toks.append(order)
        gates.append(top_p[order, j])
        keeps.append(keep)
        counts = counts.at[sorted_eid].add(keep.astype(jnp.int32))
    return (jnp.concatenate(slots), jnp.concatenate(toks),
            jnp.concatenate(gates), jnp.concatenate(keeps))


class MoEMLP(nn.Module):
    """Top-k routed SwiGLU MoE FFN, ``[B, S, D] -> [B, S, D]``.

    Param layout (matched by ``tp.TRANSFORMER_TP_RULES``): ``router/kernel``
    replicated; ``experts_gate``/``experts_up`` ``[E, D, F]`` and
    ``experts_down`` ``[E, F, D]`` sharded ``P('ep', …)`` (+ tp on F).

    ``dispatch='sort'`` (default) uses the index-based dispatch
    (O(n·k) bookkeeping); ``'einsum'`` keeps the classic one-hot
    formulation (O(n·e·c) memory — fine for tests/small shapes, and the
    parity reference for the sort path).
    """

    d_model: int
    d_ff: int
    n_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    compute_dtype: jnp.dtype = jnp.float32
    dispatch: str = "sort"        # sort | einsum

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        n = b * s
        e = self.n_experts
        xf = x.reshape(n, d)

        router = nn.Dense(e, use_bias=False, name="router",
                          dtype=jnp.float32)  # routing always f32
        router_logits = router(xf.astype(jnp.float32))
        probs = jax.nn.softmax(router_logits, axis=-1)
        top_p, top_idx = jax.lax.top_k(probs, self.top_k)         # [n, k]
        top_p = top_p / jnp.maximum(jnp.sum(top_p, -1, keepdims=True), 1e-9)

        capacity = max(1, int(math.ceil(n * self.capacity_factor
                                        * self.top_k / e)))

        # Load-balancing aux loss (Switch eq. 4): e · Σ_e f_e · P_e .
        frac_tokens = jnp.mean(_one_hot(top_idx[:, 0], e), axis=0)
        frac_probs = jnp.mean(probs, axis=0)
        self.sow("aux_loss", "load_balance",
                 e * jnp.sum(frac_tokens * frac_probs))
        # Router z-loss (ST-MoE): keeps router logits bounded.
        z = jax.scipy.special.logsumexp(router_logits, axis=-1)
        self.sow("aux_loss", "router_z", jnp.mean(z * z))

        w_gate = self.param("experts_gate", nn.initializers.lecun_normal(),
                            (e, d, self.d_ff))
        w_up = self.param("experts_up", nn.initializers.lecun_normal(),
                          (e, d, self.d_ff))
        w_down = self.param("experts_down", nn.initializers.lecun_normal(),
                            (e, self.d_ff, d))
        cdt = self.compute_dtype

        if self.dispatch == "einsum":
            expert_in, combine = self._einsum_dispatch(xf, top_idx, top_p,
                                                       capacity, cdt)
        else:
            slots, toks, gates, keeps = _sorted_dispatch(top_idx, top_p,
                                                         capacity, e)
            x_pairs = xf[toks].astype(cdt) * keeps[..., None].astype(cdt)
            expert_in = (jnp.zeros((e * capacity, d), cdt)
                         .at[slots].add(x_pairs, mode="drop")
                         .reshape(e, capacity, d))

        # The ep constraints make GSPMD materialise the token shuffle as
        # all-to-alls over the ep axis (tokens in, expert outputs back).
        expert_in = constrain(expert_in, P("ep", None, None))
        h = (jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in,
                                    w_gate.astype(cdt)))
             * jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(cdt)))
        h = constrain(h, P("ep", None, "tp"))
        out = jnp.einsum("ecf,efd->ecd", h, w_down.astype(cdt))
        out = constrain(out, P("ep", None, None))

        if self.dispatch == "einsum":
            y = jnp.einsum("nec,ecd->nd", combine.astype(cdt), out)
        else:
            # gather each kept pair's expert output, weight by its gate,
            # scatter-add back to its source token
            out_flat = out.reshape(e * capacity, d)
            safe = jnp.minimum(slots, e * capacity - 1)
            contrib = (out_flat[safe]
                       * gates[..., None].astype(cdt)
                       * keeps[..., None].astype(cdt))
            y = jnp.zeros((n, d), cdt).at[toks].add(contrib)
        return y.reshape(b, s, d).astype(x.dtype)

    def _einsum_dispatch(self, xf, top_idx, top_p, capacity, cdt):
        """Classic GShard one-hot dispatch/combine (parity reference)."""
        e = self.n_experts
        n = xf.shape[0]
        counts = jnp.zeros((e,), jnp.float32)
        dispatch = jnp.zeros((n, e, capacity), jnp.float32)
        combine = jnp.zeros((n, e, capacity), jnp.float32)
        for j in range(self.top_k):
            oh = _one_hot(top_idx[:, j], e)                       # [n, e]
            pos = jnp.cumsum(oh, axis=0) - oh + counts[None, :]   # [n, e]
            keep = (pos < capacity).astype(jnp.float32) * oh
            counts = counts + jnp.sum(keep, axis=0)
            slot = _one_hot(jnp.sum(pos * oh, axis=-1).astype(jnp.int32),
                            capacity)                             # [n, c]
            d_j = keep[:, :, None] * slot[:, None, :]
            dispatch = dispatch + d_j
            combine = combine + d_j * top_p[:, j][:, None, None]
        expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(cdt),
                               xf.astype(cdt))
        return expert_in, combine
