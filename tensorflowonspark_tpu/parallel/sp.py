"""Sequence/context parallelism: ring attention and Ulysses over the ``sp`` axis.

The reference has nothing here (SURVEY.md §5.7 — it predates long-context
work), but long sequences are first-class in this build.  Two TPU-idiomatic
schemes, both built on the chunk/merge online-softmax primitives from
``ops/attention.py``:

- **Ring attention** (``ring_attention`` / ``ring_self_attention``): Q stays
  put, KV shards rotate around the ``sp`` ring via ``jax.lax.ppermute`` over
  ICI neighbours; each hop's partial result merges via the online-softmax
  identity.  Memory per chip is O(S_local²-ish blockwise); the sequence can
  exceed any single chip's HBM.
- **Ulysses** (``ulysses_self_attention``): two ``all_to_all``s swap the
  sharded axis seq→heads and back, so each chip computes *full-sequence*
  attention for a head subset — cheaper collectives when heads ≥ sp and the
  whole sequence fits per chip.

Both are meant to run *inside* ``jax.shard_map`` (the raw functions) or via
the ``*_self_attention`` wrappers that shard_map over a standard mesh.
"""

from __future__ import annotations

import functools
from typing import Literal

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.ops.attention import (
    blockwise_attention,
    chunk_attention,
    match_vma,
    merge_attention,
)


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = True,
                   sm_scale: float | None = None):
    """Ring attention over a named axis; call inside ``shard_map``.

    ``q``/``k``/``v`` are local sequence shards ``[B, S_local, H, D]`` with
    the global sequence laid out contiguously across the axis (shard i holds
    positions ``[i*S_local, (i+1)*S_local)``).  Each step attends the local Q
    against the currently-held KV chunk (with its *global* offset, so causal
    masks stay exact), merges online-softmax style, then rotates KV to the
    next ring neighbour with ``ppermute`` — XLA overlaps the permute with the
    next chunk's compute over ICI.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def attend_held(o, lse, k_cur, v_cur, hop):
        # KV currently held originated on ring neighbour (idx - hop) mod n.
        src = jax.lax.rem(idx - hop + n, n)
        kv_off = (src - idx) * s_local  # kv global start relative to q's
        o_c, lse_c = chunk_attention(q, k_cur, v_cur, causal=causal,
                                     sm_scale=sm_scale, kv_offset=kv_off)
        return merge_attention(o, lse, o_c, lse_c)

    def step(carry, hop):
        o, lse, k_cur, v_cur = carry
        k_nxt, v_nxt = jax.lax.ppermute((k_cur, v_cur), axis_name, perm)
        o, lse = attend_held(o, lse, k_cur, v_cur, hop)
        return (o, lse, k_nxt, v_nxt), None

    b, s, h, d = q.shape
    # The accumulator stays float32 through every merge (merge_attention
    # preserves o1's dtype): a bf16 carry would round after each hop and
    # precision would degrade with ring size relative to the f32
    # accumulation used everywhere else in ops/attention.py.
    o0 = match_vma(jnp.zeros((b, s, h, d), jnp.float32), q)
    lse0 = match_vma(jnp.full((b, s, h), -jnp.inf, jnp.float32), q)
    # n-1 hops rotate KV while attending; the final held chunk is attended
    # outside the scan so its rotation (whose result nobody reads) is never
    # issued on the ICI.
    (o, lse, k_last, v_last), _ = jax.lax.scan(
        step, (o0, lse0, k, v), jnp.arange(n - 1, dtype=jnp.int32))
    o, lse = attend_held(o, lse, k_last, v_last, jnp.int32(n - 1))
    return o.astype(q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str = "sp", causal: bool = True,
                      sm_scale: float | None = None, block_k: int = 512):
    """Ulysses (all-to-all) attention over a named axis; call inside shard_map.

    Local shards ``[B, S_local, H, D]`` → all_to_all to ``[B, S, H/n, D]`` →
    full-sequence blockwise attention per head subset → all_to_all back.
    Requires ``H % axis_size == 0``.
    """
    n = jax.lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n:
        raise ValueError(f"ulysses needs heads ({h}) divisible by sp axis ({n})")
    swap = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                             split_axis=2, concat_axis=1, tiled=True)
    unswap = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                               split_axis=1, concat_axis=2, tiled=True)
    out = blockwise_attention(swap(q), swap(k), swap(v), causal=causal,
                              sm_scale=sm_scale, block_k=block_k)
    return unswap(out)


SpImpl = Literal["ring", "ulysses"]


def sequence_parallel_attention(mesh, q, k, v, *, causal: bool = True,
                                sm_scale: float | None = None,
                                impl: SpImpl = "ring"):
    """Shard_map wrapper: self-attention with sequence sharded over ``sp``.

    Global arrays ``[B, S, H, D]``: batch over ``(dp, fsdp)``, sequence over
    ``sp``, heads over ``tp``.  Returns the same layout.
    """
    pspec = P(("dp", "fsdp"), "sp", "tp", None)
    fn = ring_attention if impl == "ring" else ulysses_attention
    inner = functools.partial(fn, axis_name="sp", causal=causal,
                              sm_scale=sm_scale)

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(pspec, pspec, pspec),
                       out_specs=pspec)
    def mapped(q, k, v):
        return inner(q, k, v)

    return mapped(q, k, v)
