"""Device-mesh construction and sharding helpers.

The reference handed out ``CUDA_VISIBLE_DEVICES`` strings (``gpu_info.py``)
and wired nodes via ``TF_CONFIG`` (``TFSparkNode.py:~260-300``).  The TPU
equivalent of "cluster wiring" is a named ``jax.sharding.Mesh``: SPMD
programs annotate shardings over its axes and XLA inserts the collectives
(all-reduce over ICI for data-parallel gradients, etc.).

Axis convention (SURVEY.md §2.3 disposition column):
- ``dp``   — data parallelism (the reference's only strategy, now sync SPMD);
- ``fsdp`` — parameter-sharded data parallelism (zero-style);
- ``tp``   — tensor/model parallelism (reference: absent; first-class here);
- ``sp``   — sequence/context parallelism for long-context (ring attention);
- ``ep``   — expert parallelism;
- ``pp``   — pipeline parallelism.
Unused axes default to size 1 so one mesh shape serves every model family.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "tp", "sp", "ep", "pp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A named mesh layout; axes omitted at construction default to 1."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def size(self) -> int:
        return math.prod(self.axis_sizes())

    def axis_sizes(self) -> tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXES)


def make_mesh(devices: Sequence[jax.Device] | None = None, **axis_sizes: int) -> Mesh:
    """Build a Mesh with the standard axis names.

    Any axis given as ``-1`` absorbs the remaining devices (like a reshape
    wildcard).  With no axes at all, everything lands on ``dp``.

    On real hardware, ``jax.devices()`` order already reflects ICI topology
    (jax returns devices in a topology-aware order); axis order places the
    innermost axes (``pp`` last) on the nearest neighbours, so put the
    bandwidth-hungry axis (``tp``/``sp``) after ``dp``/``fsdp`` as this
    layout does.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = {a: int(axis_sizes.get(a, 1)) for a in AXES}
    unknown = set(axis_sizes) - set(AXES)
    if unknown:
        raise ValueError(f"unknown mesh axes {sorted(unknown)}; valid: {AXES}")
    wilds = [a for a, s in sizes.items() if s == -1]
    if len(wilds) > 1:
        raise ValueError("at most one axis may be -1")
    fixed = math.prod(s for s in sizes.values() if s != -1)
    if wilds:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes product {fixed}")
        sizes[wilds[0]] = n // fixed
    elif not axis_sizes:
        sizes["dp"] = n
    elif fixed != n:
        raise ValueError(f"mesh axes product {fixed} != device count {n}")
    arr = np.array(devices).reshape([sizes[a] for a in AXES])
    return Mesh(arr, AXES)


def spec(mesh: Mesh) -> MeshSpec:
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    return MeshSpec(**{a: shape.get(a, 1) for a in AXES})


def batch_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    """Sharding for a batch: leading dim split over (dp, fsdp), rest replicated."""
    return NamedSharding(mesh, P(("dp", "fsdp"), *([None] * extra_dims)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def fsdp_shardings(mesh: Mesh, tree):
    """Per-leaf NamedShardings sharding params over the ``fsdp`` axis (ZeRO-3
    style: each leaf is split on its largest fsdp-divisible dimension; XLA
    inserts the all-gather before use and the reduce-scatter on gradients).

    Leaves too small to split (or with no divisible dim) stay replicated —
    that is the correct GSPMD idiom, not a fallback: tiny biases/BN scales
    cost nothing to replicate and sharding them would only add latency.
    """
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get("fsdp", 1)

    def leaf_sharding(x) -> NamedSharding:
        if axis_size == 1 or not hasattr(x, "shape") or x.ndim == 0:
            return replicated(mesh)
        d = pick_shard_dim(x.shape, axis_size)
        if d is None:
            return replicated(mesh)
        pspec = [None] * x.ndim
        pspec[d] = "fsdp"
        return NamedSharding(mesh, P(*pspec))

    return jax.tree.map(leaf_sharding, tree)


def pick_shard_dim(shape, axis_size: int, taken=()) -> int | None:
    """Largest dim divisible by ``axis_size`` (skipping ``taken`` dims), or
    None if nothing splits evenly — the shared heuristic behind fsdp
    sharding here and ``tp.compose_fsdp``."""
    dims = sorted(range(len(shape)), key=lambda d: shape[d], reverse=True)
    for d in dims:
        if d in taken:
            continue
        if shape[d] % axis_size == 0 and shape[d] >= axis_size:
            return d
    return None


def is_multiprocess(mesh: Mesh) -> bool:
    """True when the mesh spans devices owned by other processes.

    This is the multi-host SPMD case (``jax.distributed`` initialized, one
    controller per host): ``jax.device_put`` cannot target non-addressable
    devices, so array placement must go through the process-local assembly
    APIs instead (see ``shard_batch``/``shard_tree``).
    """
    if jax.process_count() == 1:
        return False
    pid = jax.process_index()
    return any(d.process_index != pid for d in mesh.devices.flat)


def shard_tree(mesh: Mesh, tree, shardings=None):
    """Place a pytree on the mesh under the given (or fsdp-derived) shardings.

    Stages through host memory for the same donation-safety reason as
    ``dp.replicate`` (fresh buffers; sources may live on any device subset).

    Multi-process meshes: every process must hold the same full host value
    (the usual case — params from a shared init seed or a restored
    checkpoint); each process materializes only its addressable shards via
    ``jax.make_array_from_callback``.
    """
    shardings = shardings if shardings is not None else fsdp_shardings(mesh, tree)
    if is_multiprocess(mesh):
        def put_global(x, s):
            x = np.asarray(x)
            return jax.make_array_from_callback(x.shape, s, lambda idx: x[idx])
        return jax.tree.map(put_global, tree, shardings)
    return jax.tree.map(
        lambda x, s: jax.device_put(np.asarray(x), s), tree, shardings
    )


def shard_batch(mesh: Mesh, batch):
    """Place a host batch onto the mesh, sharded along the leading axis.

    Single process: a plain ``device_put`` split over ``(dp, fsdp)``.

    Multi-process (``jax.distributed``): each host holds a DISJOINT local
    batch (its own streamed partitions — reference ``InputMode.SPARK`` feed
    closures, ``TFSparkNode.py:~430-510``); the global batch is their
    concatenation in process order, assembled without any cross-host copy by
    ``jax.make_array_from_process_local_data``.  The global leading dim is
    ``local_batch × (processes spanning the batch axes)``, so the jitted SPMD
    step sees one global batch while each host only ever touches its own
    rows.
    """
    if is_multiprocess(mesh):
        def put_local(x):
            x = np.asarray(x)
            return jax.make_array_from_process_local_data(
                batch_sharding(mesh, extra_dims=x.ndim - 1), x)
        return jax.tree.map(put_local, batch)
    return jax.tree.map(
        lambda x: jax.device_put(x, batch_sharding(mesh, extra_dims=x.ndim - 1)),
        batch,
    )
