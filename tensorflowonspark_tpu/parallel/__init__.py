"""Parallelism: device meshes, SPMD data parallelism, sharding helpers.

The tensor plane of the framework (SURVEY.md §5.8-2): XLA collectives over
ICI emitted by jit-compiled SPMD programs — no server objects, no NCCL.
"""

from tensorflowonspark_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    make_mesh,
    batch_sharding,
    replicated,
    shard_batch,
)
