"""Parallelism: device meshes, SPMD data parallelism, sharding helpers.

The tensor plane of the framework (SURVEY.md §5.8-2): XLA collectives over
ICI emitted by jit-compiled SPMD programs — no server objects, no NCCL.

Axes (mesh.AXES): ``dp`` (sync data parallel), ``fsdp`` (ZeRO-style param
sharding), ``tp`` (Megatron tensor parallel, tp.py), ``sp`` (ring/Ulysses
sequence parallel, sp.py), ``ep`` (expert parallel MoE, ep.py), ``pp``
(GPipe pipeline, pp.py).
"""

from tensorflowonspark_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    make_mesh,
    batch_sharding,
    replicated,
    shard_batch,
    shard_tree,
)
from tensorflowonspark_tpu.parallel.sp import (  # noqa: F401
    ring_attention,
    sequence_parallel_attention,
    ulysses_attention,
)
from tensorflowonspark_tpu.parallel.tp import (  # noqa: F401
    TRANSFORMER_TP_RULES,
    compose_fsdp,
    constrain,
    rule_shardings,
)
from tensorflowonspark_tpu.parallel.pp import (  # noqa: F401
    gpipe,
    pipeline_1f1b,
    stack_stages,
    stage_shardings,
)
from tensorflowonspark_tpu.parallel.ep import MoEMLP  # noqa: F401
