"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` axis.

Absent in the reference (SURVEY.md §2.3).  TPU-idiomatic form: every device
holds one stage's params (stage-stacked pytree sharded ``P('pp', …)``), the
schedule is a ``lax.scan`` over ``n_micro + n_stages - 1`` ticks inside
``shard_map``, and activations hop stage→stage with ``jax.lax.ppermute``
over ICI neighbours.  All stages run the same ``stage_fn`` SPMD program each
tick (on their own microbatch-in-flight), so utilisation follows the classic
GPipe bubble 1 - m/(m+s-1).

Differentiable end-to-end: grads flow back through the scan + ppermute, so
``jax.grad`` over a pipelined loss just works (the backward pipeline is the
reverse-time scan XLA derives).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.ops.attention import match_vma


def _dp_batch_spec(mesh, data_axis: str, batch: int,
                   n_microbatches: int) -> tuple[int, P]:
    """Shared gpipe/1F1B data-parallel plumbing: the ``data_axis`` size, the
    batch divisibility check, and the batch PartitionSpec."""
    dp_size = dict(mesh.shape).get(data_axis, 1)
    if batch % (dp_size * n_microbatches):
        raise ValueError(
            f"batch {batch} not divisible by {data_axis}-size x "
            f"n_microbatches = {dp_size} x {n_microbatches}")
    return dp_size, (P(data_axis) if dp_size > 1 else P())


def _validate_stage_params(stage_params: Any, n_stages: int) -> None:
    """Shared gpipe/1F1B precondition: a stage-stacked params layout
    (every leaf leading dim == n_stages)."""
    for path, leaf in jax.tree_util.tree_leaves_with_path(stage_params):
        shape = getattr(leaf, "shape", None)
        if not shape or shape[0] != n_stages:
            raise ValueError(
                f"stage_params leaf {jax.tree_util.keystr(path)} has shape "
                f"{shape}, expected leading dim n_stages={n_stages} "
                f"(use stack_stages)")


def gpipe(stage_fn: Callable[[Any, jax.Array], jax.Array], stage_params: Any,
          x: jax.Array, *, mesh, n_microbatches: int, axis_name: str = "pp",
          data_axis: str = "dp"):
    """Run ``x`` through a pipeline of stages; returns the final activations.

    - ``stage_params``: pytree whose leaves have a leading ``n_stages`` dim
      (stage-stacked); sharded over ``pp`` by the wrapper.
    - ``stage_fn(params_i, mb) -> mb``: one stage's computation; activation
      shapes must be identical between stages (the inter-stage wire format).
    - ``x``: global batch ``[B, …]`` with ``B`` divisible by
      ``data_axis``-size × ``n_microbatches``.  When the mesh's
      ``data_axis`` (default ``dp``) has size > 1 the batch shards over it
      and each dp row pipelines only its shard — without this, every row
      would redundantly compute the full batch.

    **Bubble accounting.**  With ``m`` microbatches over ``s`` stages the
    schedule runs ``m + s - 1`` ticks of which each stage computes on ``m``,
    so utilisation is ``m / (m + s - 1)`` (bubble fraction
    ``(s-1)/(m+s-1)``); the backward scan XLA derives doubles both numbers,
    leaving the fraction unchanged.  Memory: ``jax.grad`` through the scan
    saves every tick's activations — O(m) microbatch residuals per stage.
    When that dominates, use :func:`pipeline_1f1b`, which caps in-flight
    residuals at ``s - stage_index`` and recomputes stage forwards in the
    backward (GPipe-remat style), at the same bubble fraction.
    """
    n_stages = mesh.shape[axis_name]
    _validate_stage_params(stage_params, n_stages)
    _, batch_spec = _dp_batch_spec(mesh, data_axis, x.shape[0],
                                   n_microbatches)

    def body(params, xb):
        params = jax.tree.map(lambda a: a[0], params)   # local stage's slice
        idx = jax.lax.axis_index(axis_name)
        n = jax.lax.axis_size(axis_name)
        mb = xb.shape[0] // n_microbatches
        xs = xb.reshape((n_microbatches, mb) + xb.shape[1:])
        ticks = n_microbatches + n - 1
        pad = jnp.zeros((n - 1, mb) + xb.shape[1:], xb.dtype)
        feed = jnp.concatenate([xs, pad], axis=0)        # [ticks, mb, ...]

        fwd = [(i, i + 1) for i in range(n - 1)]         # non-cyclic shift

        def tick(carry, inp):
            recv, outputs, t = carry
            cur = jnp.where(idx == 0, inp, recv)
            out = stage_fn(params, cur)
            nxt = jax.lax.ppermute(out, axis_name, fwd)
            # Last stage finishes microbatch t-(n-1) at tick t.
            slot = jnp.clip(t - (n - 1), 0, n_microbatches - 1)
            contrib = jnp.where((idx == n - 1) & (t >= n - 1), out, 0.0)
            outputs = jax.lax.dynamic_update_slice(
                outputs, (jax.lax.dynamic_slice_in_dim(outputs, slot, 1)
                          + contrib[None]),
                (slot,) + (0,) * out.ndim)
            return (nxt, outputs, t + 1), None

        out0 = match_vma(jnp.zeros((n_microbatches, mb) + xb.shape[1:],
                                   jnp.result_type(xb.dtype, jnp.float32)), xb)
        recv0 = match_vma(jnp.zeros((mb,) + xb.shape[1:], xb.dtype), xb)
        (_, outputs, _), _ = jax.lax.scan(
            tick, (recv0, out0, jnp.int32(0)), feed)
        # Only the last stage holds real outputs; psum broadcasts them (all
        # other stages contribute zeros).
        outputs = jax.lax.psum(outputs, axis_name)
        return outputs.reshape((xb.shape[0],) + xb.shape[1:]).astype(xb.dtype)

    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis_name), batch_spec),
        out_specs=batch_spec,
        check_vma=False,
    )
    return mapped(stage_params, x)


def pipeline_1f1b(stage_fn: Callable[[Any, jax.Array], jax.Array],
                  stage_params: Any, x: jax.Array, loss_fn: Callable,
                  *, mesh, n_microbatches: int, targets: Any = None,
                  head_params: Any = None, with_input_grad: bool = False,
                  axis_name: str = "pp", data_axis: str = "dp"):
    """One-forward-one-backward (PipeDream-flush) pipelined loss + grads.

    Returns ``(loss, grads[, head_grads][, dx])``: ``loss`` is the mean of
    the per-microbatch losses, ``grads`` is ``d loss / d stage_params`` in
    the same stage-stacked layout.  With ``head_params`` the loss head that
    lives OUTSIDE the pipe (final norm + lm_head, the classic GPipe
    placement) trains too: ``loss_fn(head_params, y_mb[, tgt_mb])`` and the
    result gains ``head_grads``.  With ``with_input_grad=True`` the result
    gains ``dx = d loss / d x`` ``[B, …]`` so an outside-the-pipe embedding
    can backprop through the pipeline (``dx`` is the size of ``x`` itself —
    it does not reintroduce the O(m) per-stage residuals this schedule
    avoids).

    Versus differentiating :func:`gpipe` (which scans forward then lets XLA
    reverse it), the backward here is *scheduled*: each stage alternates one
    forward and one backward microbatch in steady state, so at most
    ``s - stage_index`` forward residuals are ever in flight per stage
    (O(s) memory, independent of ``m``) instead of O(m).  Only stage
    *inputs* are saved; the backward recomputes the stage forward under
    ``jax.vjp`` (activation recompute, the standard 1F1B-with-remat
    trade: ~1.33x forward FLOPs).  The bubble fraction is GPipe's
    ``(s-1)/(m+s-1)``; 1F1B moves the backward earlier, it does not shrink
    the bubble (an interleaved/virtual-stage schedule — v chunks per device,
    bubble / v — is the known extension and is not implemented).
    Beyond-reference capability — the reference has no pipeline parallelism
    at all (SURVEY.md §2.3).

    Schedule (tick ``t``, stage ``i``, ``s`` stages, ``m`` microbatches):
    forward ``k`` runs at ``t = i + 2k``, backward ``k`` at
    ``t = 2s - 1 - i + 2k`` — disjoint by parity, producer always one tick
    ahead of its consumer on both the forward and backward wires; last
    backward lands at ``t = 2(m + s) - 3``.

    ``stage_fn(params_i, mb) -> mb_out`` as in :func:`gpipe`;
    ``loss_fn([head_params, ]y_mb[, tgt_mb])`` (``tgt_mb`` present when
    ``targets`` — a pytree of ``[B, …]`` arrays — is given) must return a
    scalar.

    Composes with data parallelism: when the mesh's ``data_axis`` (default
    ``dp``) has size > 1, the batch (and ``targets``) shard over it, each dp
    row runs its own pipeline on its shard, and stage/head grads and the
    loss are averaged across rows — the global result equals a single
    pipeline over the whole batch.
    """
    n_stages = mesh.shape[axis_name]
    m = n_microbatches
    _validate_stage_params(stage_params, n_stages)
    dp_size, batch_spec = _dp_batch_spec(mesh, data_axis, x.shape[0], m)
    has_tgts = targets is not None
    tgts_in = targets if has_tgts else ()
    has_head = head_params is not None
    head_in = head_params if has_head else ()

    def _dp_mean(tree):
        if dp_size == 1:
            return tree
        return jax.tree.map(lambda a: jax.lax.pmean(a, data_axis), tree)

    def body(params, hp, xb, tgts):
        params = jax.tree.map(lambda a: a[0], params)
        idx = jax.lax.axis_index(axis_name)
        s = n_stages
        mb = xb.shape[0] // m
        xs = xb.reshape((m, mb) + xb.shape[1:])
        tgts_mb = jax.tree.map(
            lambda a: a.reshape((m, mb) + a.shape[1:]), tgts)
        fwd_perm = [(i, i + 1) for i in range(s - 1)]
        bwd_perm = [(i + 1, i) for i in range(s - 1)]

        # Forward wire + residual buffer ride in the activation dtype (bf16
        # stays bf16 — the O(s) residual cap is the schedule's selling
        # point); only the gradient wire is f32.
        zero_act = match_vma(jnp.zeros((mb,) + xb.shape[1:], xb.dtype), xb)
        zero_grad = match_vma(jnp.zeros((mb,) + xb.shape[1:], jnp.float32), xb)

        def tick(carry, t):
            fwd_recv, bwd_recv, resid, grad_acc, loss_acc, hg_acc, dx_buf = carry
            tf = t - idx
            is_f = (tf >= 0) & (tf % 2 == 0) & (tf < 2 * m)
            kf = jnp.clip(tf // 2, 0, m - 1)
            tb = t - (2 * s - 1 - idx)
            is_b = (tb >= 0) & (tb % 2 == 0) & (tb < 2 * m)
            kb = jnp.clip(tb // 2, 0, m - 1)
            x_in = jnp.where(idx == 0,
                             jax.lax.dynamic_index_in_dim(xs, kf, keepdims=False),
                             fwd_recv)

            def fwd_branch(resid, grad_acc, loss_acc, hg_acc, dx_buf):
                out = stage_fn(params, x_in)
                resid = jax.lax.dynamic_update_index_in_dim(
                    resid, x_in, kf % s, 0)
                return (match_vma(out.astype(xb.dtype), xb), zero_grad,
                        resid, grad_acc, loss_acc, hg_acc, dx_buf)

            def bwd_branch(resid, grad_acc, loss_acc, hg_acc, dx_buf):
                inp = jax.lax.dynamic_index_in_dim(
                    resid, kb % s, keepdims=False)
                out, vjp = jax.vjp(stage_fn, params, inp)
                if has_tgts:
                    tgt_k = jax.tree.map(
                        lambda a: jax.lax.dynamic_index_in_dim(
                            a, kb, keepdims=False), tgts_mb)
                else:
                    tgt_k = None
                last = idx == s - 1
                # The loss head runs ONLY on the last stage (lax.cond, no
                # collectives inside) — an lm_head-sized loss would
                # otherwise cost s x per backward tick, discarded on s-1
                # stages.
                if has_head:
                    lfn = (lambda h, y: loss_fn(h, y, tgt_k)) if has_tgts \
                        else (lambda h, y: loss_fn(h, y))

                    def _head(hp, out):
                        lk, (g_hp, g_l) = jax.value_and_grad(
                            lfn, argnums=(0, 1))(hp, out)
                        return (jnp.float32(lk),
                                jax.tree.map(
                                    lambda a: a.astype(jnp.float32), g_hp),
                                g_l.astype(jnp.float32))

                    def _skip(hp, out):
                        return (jnp.float32(0.0),
                                jax.tree.map(
                                    lambda a: jnp.zeros(a.shape, jnp.float32),
                                    hp),
                                jnp.zeros(out.shape, jnp.float32))

                    lk, g_hp, g_loss = jax.lax.cond(last, _head, _skip,
                                                    hp, out)
                    hg_acc = jax.tree.map(jnp.add, hg_acc, g_hp)
                else:
                    lfn = (lambda y: loss_fn(y, tgt_k)) if has_tgts \
                        else loss_fn

                    def _head(out):
                        lk, g_l = jax.value_and_grad(lfn)(out)
                        return jnp.float32(lk), g_l.astype(jnp.float32)

                    def _skip(out):
                        return (jnp.float32(0.0),
                                jnp.zeros(out.shape, jnp.float32))

                    lk, g_loss = jax.lax.cond(last, _head, _skip, out)
                g_out = jnp.where(last, g_loss, bwd_recv).astype(out.dtype)
                g_par, g_in = vjp(g_out)
                grad_acc = jax.tree.map(
                    lambda acc, g: acc + g.astype(jnp.float32),
                    grad_acc, g_par)
                loss_acc = loss_acc + lk  # lk is zero off the last stage
                if with_input_grad:
                    dx_buf = jax.lax.dynamic_update_index_in_dim(
                        dx_buf,
                        jnp.where(idx == 0, g_in.astype(jnp.float32), 0.0),
                        kb, 0)
                return (zero_act, match_vma(g_in.astype(jnp.float32), xb),
                        resid, grad_acc, loss_acc, hg_acc, dx_buf)

            def idle_branch(resid, grad_acc, loss_acc, hg_acc, dx_buf):
                return (zero_act, zero_grad, resid, grad_acc, loss_acc,
                        hg_acc, dx_buf)

            branch = jnp.where(is_f, 1, 0) + jnp.where(is_b, 2, 0)
            (send_f, send_b, resid, grad_acc, loss_acc, hg_acc,
             dx_buf) = jax.lax.switch(
                branch, [idle_branch, fwd_branch, bwd_branch],
                resid, grad_acc, loss_acc, hg_acc, dx_buf)
            fwd_recv = jax.lax.ppermute(send_f, axis_name, fwd_perm)
            bwd_recv = jax.lax.ppermute(send_b, axis_name, bwd_perm)
            return (fwd_recv, bwd_recv, resid, grad_acc, loss_acc, hg_acc,
                    dx_buf), None

        resid0 = match_vma(
            jnp.zeros((s, mb) + xb.shape[1:], xb.dtype), xb)
        grad0 = jax.tree.map(
            lambda a: match_vma(jnp.zeros(a.shape, jnp.float32), xb), params)
        loss0 = match_vma(jnp.float32(0.0), xb)
        hg0 = jax.tree.map(
            lambda a: match_vma(jnp.zeros(a.shape, jnp.float32), xb), hp)
        dx0 = match_vma(
            jnp.zeros(((m, mb) + xb.shape[1:]) if with_input_grad else (0,),
                      jnp.float32), xb)
        carry = (zero_act, zero_grad, resid0, grad0, loss0, hg0, dx0)
        carry, _ = jax.lax.scan(tick, carry, jnp.arange(2 * (m + s) - 2))
        _, _, _, grad_acc, loss_acc, hg_acc, dx_buf = carry
        loss = _dp_mean(jax.lax.psum(loss_acc, axis_name) / m)
        grads = _dp_mean(jax.tree.map(lambda a: (a / m)[None], grad_acc))
        outs = [loss, grads]
        if has_head:
            outs.append(_dp_mean(jax.tree.map(
                lambda a: jax.lax.psum(a, axis_name) / m, hg_acc)))
        if with_input_grad:
            # 1/dp matches the dp-averaged loss the other grads differentiate
            dx = jax.lax.psum(dx_buf, axis_name) / (m * dp_size)
            outs.append(dx.reshape((xb.shape[0],) + xb.shape[1:]))
        return tuple(outs)

    out_specs = (P(), P(axis_name))
    if has_head:
        out_specs = out_specs + (P(),)
    if with_input_grad:
        out_specs = out_specs + (batch_spec,)
    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis_name), P(), batch_spec, batch_spec),
        out_specs=out_specs,
        check_vma=False,
    )
    return mapped(stage_params, head_in, x, tgts_in)


def stack_stages(param_trees: list) -> Any:
    """Stack per-stage param pytrees into the stage-stacked layout."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_trees)


def stage_shardings(mesh, stacked_params, axis_name: str = "pp"):
    """NamedShardings placing the leading stage dim over ``pp``."""
    from jax.sharding import NamedSharding

    def leaf(x):
        return NamedSharding(mesh, P(*((axis_name,) + (None,) * (x.ndim - 1))))

    return jax.tree.map(leaf, stacked_params)
