"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` axis.

Absent in the reference (SURVEY.md §2.3).  TPU-idiomatic form: every device
holds one stage's params (stage-stacked pytree sharded ``P('pp', …)``), the
schedule is a ``lax.scan`` over ``n_micro + n_stages - 1`` ticks inside
``shard_map``, and activations hop stage→stage with ``jax.lax.ppermute``
over ICI neighbours.  All stages run the same ``stage_fn`` SPMD program each
tick (on their own microbatch-in-flight), so utilisation follows the classic
GPipe bubble 1 - m/(m+s-1).

Differentiable end-to-end: grads flow back through the scan + ppermute, so
``jax.grad`` over a pipelined loss just works (the backward pipeline is the
reverse-time scan XLA derives).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.ops.attention import match_vma


def gpipe(stage_fn: Callable[[Any, jax.Array], jax.Array], stage_params: Any,
          x: jax.Array, *, mesh, n_microbatches: int, axis_name: str = "pp"):
    """Run ``x`` through a pipeline of stages; returns the final activations.

    - ``stage_params``: pytree whose leaves have a leading ``n_stages`` dim
      (stage-stacked); sharded over ``pp`` by the wrapper.
    - ``stage_fn(params_i, mb) -> mb``: one stage's computation; activation
      shapes must be identical between stages (the inter-stage wire format).
    - ``x``: global batch ``[B, …]`` with ``B % n_microbatches == 0``.
    """
    n_stages = mesh.shape[axis_name]
    if x.shape[0] % n_microbatches:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"n_microbatches {n_microbatches}")
    for path, leaf in jax.tree_util.tree_leaves_with_path(stage_params):
        if getattr(leaf, "ndim", 0) == 0 or leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leaf {jax.tree_util.keystr(path)} has leading "
                f"dim {leaf.shape[0]}, expected n_stages={n_stages} "
                f"(use stack_stages)")

    def body(params, xb):
        params = jax.tree.map(lambda a: a[0], params)   # local stage's slice
        idx = jax.lax.axis_index(axis_name)
        n = jax.lax.axis_size(axis_name)
        mb = xb.shape[0] // n_microbatches
        xs = xb.reshape((n_microbatches, mb) + xb.shape[1:])
        ticks = n_microbatches + n - 1
        pad = jnp.zeros((n - 1, mb) + xb.shape[1:], xb.dtype)
        feed = jnp.concatenate([xs, pad], axis=0)        # [ticks, mb, ...]

        fwd = [(i, i + 1) for i in range(n - 1)]         # non-cyclic shift

        def tick(carry, inp):
            recv, outputs, t = carry
            cur = jnp.where(idx == 0, inp, recv)
            out = stage_fn(params, cur)
            nxt = jax.lax.ppermute(out, axis_name, fwd)
            # Last stage finishes microbatch t-(n-1) at tick t.
            slot = jnp.clip(t - (n - 1), 0, n_microbatches - 1)
            contrib = jnp.where((idx == n - 1) & (t >= n - 1), out, 0.0)
            outputs = jax.lax.dynamic_update_slice(
                outputs, (jax.lax.dynamic_slice_in_dim(outputs, slot, 1)
                          + contrib[None]),
                (slot,) + (0,) * out.ndim)
            return (nxt, outputs, t + 1), None

        out0 = match_vma(jnp.zeros((n_microbatches, mb) + xb.shape[1:],
                                   jnp.result_type(xb.dtype, jnp.float32)), xb)
        recv0 = match_vma(jnp.zeros((mb,) + xb.shape[1:], xb.dtype), xb)
        (_, outputs, _), _ = jax.lax.scan(
            tick, (recv0, out0, jnp.int32(0)), feed)
        # Only the last stage holds real outputs; psum broadcasts them (all
        # other stages contribute zeros).
        outputs = jax.lax.psum(outputs, axis_name)
        return outputs.reshape((xb.shape[0],) + xb.shape[1:]).astype(xb.dtype)

    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )
    return mapped(stage_params, x)


def stack_stages(param_trees: list) -> Any:
    """Stack per-stage param pytrees into the stage-stacked layout."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_trees)


def stage_shardings(mesh, stacked_params, axis_name: str = "pp"):
    """NamedShardings placing the leading stage dim over ``pp``."""
    from jax.sharding import NamedSharding

    def leaf(x):
        return NamedSharding(mesh, P(*((axis_name,) + (None,) * (x.ndim - 1))))

    return jax.tree.map(leaf, stacked_params)
