"""Tensor (model) parallelism: GSPMD sharding rules over the ``tp`` axis.

The reference has no model parallelism at all (SURVEY.md §2.3 — "leave a
model axis as an extension point"); here it is first-class.  TPU-idiomatic
TP is *not* explicit collectives: params get Megatron-style layouts
(column-parallel up-projections, row-parallel down-projections) as
``PartitionSpec`` annotations, activations get ``with_sharding_constraint``
hints, and XLA/GSPMD inserts the all-reduces over ICI.

Rules are ``(path_regex, PartitionSpec)`` pairs matched against the
``/``-joined param path; first match wins, no match ⇒ replicated-over-tp
(then fsdp sharding may still apply via ``compose_fsdp``).
"""

from __future__ import annotations

import re
from typing import Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Sequence[tuple[str, P]]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def rule_shardings(mesh: Mesh, tree, rules: Rules, *, default: P = P()):
    """Per-leaf NamedShardings from path-regex rules (first match wins)."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def leaf(path, x):
        s = _path_str(path)
        for pat, spec in compiled:
            if pat.search(s):
                return NamedSharding(mesh, spec)
        return NamedSharding(mesh, default)

    return jax.tree_util.tree_map_with_path(leaf, tree)


def compose_fsdp(mesh: Mesh, tree, shardings):
    """Layer fsdp sharding on top of tp rules: any leaf dim not already
    tp-sharded is split over ``fsdp`` (largest divisible dim), so TP and
    ZeRO-3 compose the way Megatron-LM + FSDP do."""
    from tensorflowonspark_tpu.parallel.mesh import pick_shard_dim

    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get("fsdp", 1)

    def leaf(x, sharding):
        if axis_size == 1 or not hasattr(x, "shape") or x.ndim == 0:
            return sharding
        spec = list(sharding.spec) + [None] * (x.ndim - len(sharding.spec))
        used = {a for s in spec if s is not None
                for a in (s if isinstance(s, tuple) else (s,))}
        if "fsdp" in used:
            return sharding
        taken = tuple(d for d, s in enumerate(spec) if s is not None)
        d = pick_shard_dim(x.shape, axis_size, taken)
        if d is None:
            return sharding
        spec[d] = "fsdp"
        return NamedSharding(mesh, P(*spec))

    return jax.tree.map(leaf, tree, shardings)


def constrain(x, spec: P):
    """Activation sharding hint; no-op when no mesh context is active (so
    models run unchanged on a bare single device / in unit tests).

    Axes that are in MANUAL mode — i.e. we are inside a ``shard_map`` body,
    e.g. a transformer Block running as a GPipe pipeline stage — are dropped
    from the spec: per-device code already sees local shards, and
    ``with_sharding_constraint`` rejects Manual axes outright.
    """
    mesh = jax.sharding.get_abstract_mesh()
    if mesh.empty:
        return x
    known = set(mesh.axis_names) - set(mesh.manual_axes)
    if not known:
        return x
    clean = P(*(
        (tuple(a for a in s if a in known) or None)
        if isinstance(s, tuple) else (s if s in known else None)
        for s in spec
    ))
    return jax.lax.with_sharding_constraint(x, clean)


# Megatron-style rule set for the transformer family (models/transformer.py
# param tree): attention q/k/v shard the heads dim (column-parallel), o_proj
# the heads-input dim (row-parallel); MLP up/gate column-, down row-parallel;
# embeddings/lm_head shard the vocab; norms replicate.
# q/k/v kernels are DenseGeneral 3-D [d_model, heads, d_head]; o_proj is
# [heads, d_head, d_model].
TRANSFORMER_TP_RULES: Rules = (
    (r"(q_proj|k_proj|v_proj)/kernel$", P(None, "tp", None)),
    (r"o_proj/kernel$", P("tp", None, None)),
    (r"(up_proj|gate_proj)/kernel$", P(None, "tp")),
    (r"down_proj/kernel$", P("tp", None)),
    (r"embed/embedding$", P("tp", None)),
    (r"lm_head/kernel$", P(None, "tp")),
    # MoE expert-stacked weights: leading dim is the expert axis (ep), the
    # per-expert matrices keep Megatron layouts over tp.
    (r"experts_(up|gate)$", P("ep", None, "tp")),
    (r"experts_down$", P("ep", "tp", None)),
    (r"router/kernel$", P()),
)
