"""Collective algorithms on numpy arrays over a :class:`PeerTransport`.

The cross-host tensor-plane primitives of the sync-training path: chunked
ring all-reduce / reduce-scatter / all-gather (the bandwidth-optimal
algorithms of the MPI collective papers — each node moves ``2(W-1)/W x N``
bytes regardless of world size), a pipelined ring broadcast, and the naive
gather-broadcast all-reduce kept as the bench control (root moves
``2(W-1) x N`` serially — the shape ``bench_collective.py`` measures the
ring against).

Transfers are CHUNKED at ``bucket_bytes``: a ring segment larger than one
bucket goes out as a pipeline of sub-chunks, so a node's accumulate of
chunk *k* overlaps the wire time of chunk *k+1* (and no single frame ever
buffers a whole gradient).  Every message is stamped with the group's
``(generation, seq, tag)`` — see ``transport.py`` for the fencing contract.

Determinism: the reduction order of each result segment is fixed by the
ring schedule (same every run), and for ``world == 2`` both algorithms
compute the same two-operand sums — the property the sync-training
equivalence test pins against a single-process run.

Chaos seam: ``faultinject.collective_round()`` is called once per
all-reduce, *mid-algorithm* (after the first data exchange), so a ``kill``
armed on it dies with partial chunks genuinely in flight on the wire —
the worst case the generation-barrier rejoin must survive.
"""

from __future__ import annotations

import time

import numpy as np

from tensorflowonspark_tpu import faultinject
from tensorflowonspark_tpu.collective.transport import (
    CollectiveAborted,
    PeerTransport,
    pack_csr,
    unpack_csr,
)


def _op_deadline(tp: PeerTransport) -> float:
    """Per-OP receive deadline: every recv of one collective shares a
    single ``tp.timeout`` budget, so a round's total blocked time is
    bounded by one collective timeout — not one timeout per hop per chunk
    (the multiplicative wedge a gray peer used to be able to inflict)."""
    return time.monotonic() + tp.timeout


def _left(deadline: float) -> float:
    """Remaining recv budget (floored so a recv at the wire always gets a
    beat to drain an already-delivered frame)."""
    return max(0.05, deadline - time.monotonic())


def _segment_bounds(n: int, world: int) -> list[int]:
    """World+1 monotone bounds splitting ``n`` elements into ``world``
    near-equal contiguous segments (empty segments are fine: tiny arrays
    on big worlds still reduce correctly)."""
    return [(n * i) // world for i in range(world + 1)]


def _chunk_spans(lo: int, hi: int, chunk_elems: int) -> list[tuple[int, int]]:
    """Sub-chunk spans of ``[lo, hi)`` at most ``chunk_elems`` long; always
    at least one span so sender and receiver agree on the message count
    even for an empty segment."""
    if hi <= lo:
        return [(lo, lo)]
    spans = []
    while lo < hi:
        spans.append((lo, min(hi, lo + chunk_elems)))
        lo += chunk_elems
    return spans


def _chunk_elems(itemsize: int, bucket_bytes: int) -> int:
    return max(1, int(bucket_bytes) // max(1, itemsize))


def _as_flat_copy(arr: np.ndarray) -> np.ndarray:
    """Contiguous 1-D float-preserving accumulation copy of ``arr`` (the
    algorithms reduce in place; the caller's array is never mutated)."""
    return np.array(arr, copy=True).reshape(-1)


def ring_all_reduce(tp: PeerTransport, arr: np.ndarray, *, seq: int,
                    bucket_bytes: int, average: bool = False) -> np.ndarray:
    """Chunked ring all-reduce (reduce-scatter phase + all-gather phase).

    Returns a NEW array of ``arr``'s shape holding the element-wise sum
    (mean when ``average``) across all ranks.  Safe against send/recv
    deadlock by construction: each peer's inbound wire is drained by its
    dataserver connection thread independent of its compute thread, so a
    blocking send can always make progress.
    """
    world, rank = tp.world, tp.rank
    src = np.asarray(arr)
    out = _as_flat_copy(src)
    if world <= 1:
        faultinject.collective_round()
        return out.reshape(src.shape)
    bounds = _segment_bounds(out.size, world)
    chunk = _chunk_elems(out.itemsize, bucket_bytes)
    deadline = _op_deadline(tp)
    right, left = (rank + 1) % world, (rank - 1) % world
    # reduce-scatter: after step s, segment (rank - s - 1) holds the partial
    # sum of s+2 ranks; after world-1 steps rank owns segment (rank+1)%world
    for step in range(world - 1):
        si = (rank - step) % world
        ri = (rank - step - 1) % world
        send_spans = _chunk_spans(bounds[si], bounds[si + 1], chunk)
        recv_spans = _chunk_spans(bounds[ri], bounds[ri + 1], chunk)
        for k in range(max(len(send_spans), len(recv_spans))):
            if k < len(send_spans):
                lo, hi = send_spans[k]
                tp.send(right, seq, ("rs", step, k), out[lo:hi])
            if k < len(recv_spans):
                lo, hi = recv_spans[k]
                piece = tp.recv(left, seq, ("rs", step, k),
                                timeout=_left(deadline))
                if hi > lo:
                    out[lo:hi] += np.asarray(piece).reshape(-1)
    # mid-all-reduce chaos seam: partial sums are committed, the all-gather
    # exchange is still ahead — a SIGKILL (or gray stall) here leaves
    # chunks in flight
    faultinject.collective_round()
    # all-gather: circulate the finished segments
    for step in range(world - 1):
        si = (rank + 1 - step) % world
        ri = (rank - step) % world
        send_spans = _chunk_spans(bounds[si], bounds[si + 1], chunk)
        recv_spans = _chunk_spans(bounds[ri], bounds[ri + 1], chunk)
        for k in range(max(len(send_spans), len(recv_spans))):
            if k < len(send_spans):
                lo, hi = send_spans[k]
                tp.send(right, seq, ("ag", step, k), out[lo:hi])
            if k < len(recv_spans):
                lo, hi = recv_spans[k]
                piece = tp.recv(left, seq, ("ag", step, k),
                                timeout=_left(deadline))
                if hi > lo:
                    out[lo:hi] = np.asarray(piece).reshape(-1)
    if average:
        out = _averaged(out, world)
    return out.reshape(src.shape)


def _averaged(out: np.ndarray, world: int) -> np.ndarray:
    """Mean step of an averaging reduce: in place for float buffers,
    out-of-place (promoting to float) for integer ones — true division
    cannot land back in an int buffer."""
    if np.issubdtype(out.dtype, np.inexact):
        out /= world
        return out
    return out / world


def naive_all_reduce(tp: PeerTransport, arr: np.ndarray, *, seq: int,
                     average: bool = False) -> np.ndarray:
    """Gather-broadcast all-reduce through rank 0 — the control algorithm
    (``TOS_COLLECTIVE_ALGO=naive``): every rank ships its whole array to
    the root, the root reduces in rank order and ships the result back.
    Root wire traffic grows linearly with world size; kept for the bench
    comparison and as the graceful fallback for tiny payloads."""
    world, rank = tp.world, tp.rank
    src = np.asarray(arr)
    out = _as_flat_copy(src)
    if world <= 1:
        faultinject.collective_round()
        return out.reshape(src.shape)
    deadline = _op_deadline(tp)
    if rank == 0:
        for peer in range(1, world):
            piece = tp.recv(peer, seq, ("gb", "up"),
                            timeout=_left(deadline))
            out += np.asarray(piece).reshape(-1)
        faultinject.collective_round()
        if average:
            out = _averaged(out, world)
        for peer in range(1, world):
            tp.send(peer, seq, ("gb", "down"), out)
        return out.reshape(src.shape)
    tp.send(0, seq, ("gb", "up"), out)
    faultinject.collective_round()
    reduced = np.asarray(tp.recv(0, seq, ("gb", "down"),
                                 timeout=_left(deadline)))
    return np.array(reduced, copy=True).reshape(src.shape)


def reduce_scatter(tp: PeerTransport, arr: np.ndarray, *, seq: int,
                   bucket_bytes: int,
                   average: bool = False) -> tuple[int, np.ndarray]:
    """Ring reduce-scatter: returns ``(segment_index, reduced_segment)`` —
    this rank ends up owning the fully-reduced segment
    ``(rank + 1) % world`` of the flattened array."""
    world, rank = tp.world, tp.rank
    src = np.asarray(arr)
    out = _as_flat_copy(src)
    if world <= 1:
        return 0, out.reshape(src.shape)
    bounds = _segment_bounds(out.size, world)
    chunk = _chunk_elems(out.itemsize, bucket_bytes)
    deadline = _op_deadline(tp)
    right, left = (rank + 1) % world, (rank - 1) % world
    for step in range(world - 1):
        si = (rank - step) % world
        ri = (rank - step - 1) % world
        send_spans = _chunk_spans(bounds[si], bounds[si + 1], chunk)
        recv_spans = _chunk_spans(bounds[ri], bounds[ri + 1], chunk)
        for k in range(max(len(send_spans), len(recv_spans))):
            if k < len(send_spans):
                lo, hi = send_spans[k]
                tp.send(right, seq, ("rs", step, k), out[lo:hi])
            if k < len(recv_spans):
                lo, hi = recv_spans[k]
                piece = tp.recv(left, seq, ("rs", step, k),
                                timeout=_left(deadline))
                if hi > lo:
                    out[lo:hi] += np.asarray(piece).reshape(-1)
    own = (rank + 1) % world
    seg = out[bounds[own]:bounds[own + 1]]
    if average:
        seg = seg / world
    return own, np.array(seg, copy=True)


def all_gather(tp: PeerTransport, arr: np.ndarray, *,
               seq: int) -> list[np.ndarray]:
    """Ring all-gather of per-rank arrays (shapes may differ across ranks —
    frames are self-describing); returns the list indexed by rank."""
    world, rank = tp.world, tp.rank
    own = np.ascontiguousarray(np.asarray(arr))
    if world <= 1:
        return [np.array(own, copy=True)]
    out: list = [None] * world
    out[rank] = np.array(own, copy=True)
    deadline = _op_deadline(tp)
    right, left = (rank + 1) % world, (rank - 1) % world
    cur = own
    for step in range(world - 1):
        tp.send(right, seq, ("ag", step), cur)
        cur = np.asarray(tp.recv(left, seq, ("ag", step),
                                 timeout=_left(deadline)))
        out[(rank - step - 1) % world] = np.array(cur, copy=True)
    return out


def broadcast(tp: PeerTransport, arr: np.ndarray | None, *, seq: int,
              root: int, bucket_bytes: int) -> np.ndarray:
    """Pipelined ring broadcast from ``root``: the value flows
    root -> root+1 -> ... around the ring, chunked at ``bucket_bytes`` so a
    middle rank forwards chunk *k* while chunk *k+1* is still inbound.
    Non-root ranks pass ``arr=None`` and get the root's array back (shape
    and dtype ride a header frame)."""
    world, rank = tp.world, tp.rank
    if world <= 1:
        if arr is None:
            raise ValueError("broadcast root must supply the array")
        return np.array(np.asarray(arr), copy=True)
    right = (rank + 1) % world
    last = (root - 1) % world  # the ring's tail: never forwards
    if rank == root:
        if arr is None:
            raise ValueError("broadcast root must supply the array")
        flat = np.ascontiguousarray(np.asarray(arr)).reshape(-1)
        chunk = _chunk_elems(flat.itemsize, bucket_bytes)
        spans = _chunk_spans(0, flat.size, chunk)
        header = {"chunks": len(spans), "shape": tuple(np.asarray(arr).shape),
                  "dtype": str(flat.dtype)}
        tp.send(right, seq, ("bc", "hdr"), header)
        for k, (lo, hi) in enumerate(spans):
            tp.send(right, seq, ("bc", k), flat[lo:hi])
        return np.array(np.asarray(arr), copy=True)
    deadline = _op_deadline(tp)
    left = (rank - 1) % world
    header = tp.recv(left, seq, ("bc", "hdr"), timeout=_left(deadline))
    if rank != last:
        tp.send(right, seq, ("bc", "hdr"), header)
    pieces = []
    for k in range(int(header["chunks"])):
        piece = np.asarray(tp.recv(left, seq, ("bc", k),
                                   timeout=_left(deadline)))
        if rank != last:
            tp.send(right, seq, ("bc", k), piece)
        pieces.append(piece.reshape(-1))
    flat = (np.concatenate(pieces) if len(pieces) != 1
            else np.array(pieces[0], copy=True))
    return flat.astype(np.dtype(header["dtype"]), copy=False).reshape(
        header["shape"])


def all_reduce(tp: PeerTransport, arr: np.ndarray, *, seq: int,
               bucket_bytes: int, algo: str = "ring",
               average: bool = False) -> np.ndarray:
    """Algorithm dispatch (``TOS_COLLECTIVE_ALGO``)."""
    if algo == "ring":
        return ring_all_reduce(tp, arr, seq=seq, bucket_bytes=bucket_bytes,
                               average=average)
    if algo == "naive":
        return naive_all_reduce(tp, arr, seq=seq, average=average)
    raise CollectiveAborted(f"unknown collective algorithm {algo!r} "
                            "(expected 'ring' or 'naive')")


# -- sparse collectives (embedding tier) ---------------------------------------
#
# Model-parallel embedding tables exchange {row id -> row} SETS, not dense
# segments: each step touches a batch-sized sliver of a table far too large
# to all-reduce.  Both ops below are personalized exchanges over the same
# generation-fenced wire as the dense ring — the large-message MPI
# characterization regime (arxiv 1810.11112) where message COUNT is fixed
# (W-1 pairwise frames) and bytes scale with touched rows, not table size.


def sparse_all_to_all(tp: PeerTransport, parts: list, *,
                      seq: int) -> list:
    """Personalized all-to-all of per-destination (ids, values) CSR pairs.

    ``parts`` is a world-length list: ``parts[d]`` is the ``(ids, values)``
    pair bound for rank ``d`` (``values`` may be ``None`` for id-only lookup
    requests; ids may be empty — the empty-partition edge ships a zero-row
    frame so sender and receiver always agree on the message count).
    Returns a world-length list indexed by SOURCE rank of ``(ids, values)``
    received; the local part comes back as-is (no self-send).

    Schedule: round ``off`` pairs rank with ``rank+off`` (send) and
    ``rank-off`` (recv) — a fixed permutation schedule, so duplicate-free
    progress needs no global coordination and determinism is inherited by
    everything built on top.
    """
    world, rank = tp.world, tp.rank
    if len(parts) != world:
        raise CollectiveAborted(
            f"sparse_all_to_all needs one part per rank: got {len(parts)} "
            f"parts at world {world}")
    out: list = [None] * world
    ids0, vals0 = parts[rank] if isinstance(parts[rank], tuple) else (parts[rank], None)
    out[rank] = unpack_csr(pack_csr(ids0, vals0))
    if world <= 1:
        faultinject.collective_round()
        return out
    deadline = _op_deadline(tp)
    for off in range(1, world):
        dst = (rank + off) % world
        src = (rank - off) % world
        ids, vals = parts[dst] if isinstance(parts[dst], tuple) else (parts[dst], None)
        tp.send(dst, seq, ("sa", off), pack_csr(ids, vals))
        if off == 1:
            # mid-exchange chaos seam: the first pairwise frames are on the
            # wire, the rest of the permutation schedule is still ahead
            faultinject.collective_round()
        out[src] = unpack_csr(tp.recv(src, seq, ("sa", off),
                                      timeout=_left(deadline)))
    return out


def combine_csr(ids_list: list, rows_list: list,
                dim: int) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic exact-sum combine of CSR contributions: concatenate in
    LIST ORDER, then unbuffered scatter-add (``np.add.at``) into the sorted
    unique-id index space.

    This is the ONE summation kernel of the sparse path — the distributed
    reduce-scatter sums each owner's contributions through it in rank order,
    and the single-process reference replays the same per-node contribution
    lists through it, so the two trajectories are bit-for-bit equal (float
    addition is order-sensitive; sharing the kernel pins the order).
    """
    kept_i = [np.asarray(i, dtype=np.int64).reshape(-1) for i in ids_list]
    n = sum(i.size for i in kept_i)
    if n == 0:
        return (np.empty((0,), np.int64), np.empty((0, dim), np.float32))
    kept_r = [np.asarray(r, np.float32).reshape(-1, dim)
              for r in rows_list if r is not None and np.asarray(r).size]
    ids_all = np.concatenate(kept_i) if len(kept_i) != 1 else kept_i[0]
    rows_all = (np.concatenate(kept_r, axis=0) if len(kept_r) != 1
                else kept_r[0])
    if rows_all.shape[0] != ids_all.shape[0]:
        raise CollectiveAborted(
            f"CSR combine mismatch: {ids_all.shape[0]} ids vs "
            f"{rows_all.shape[0]} rows")
    uniq, inv = np.unique(ids_all, return_inverse=True)
    acc = np.zeros((uniq.size, dim), np.float32)
    np.add.at(acc, inv, rows_all)
    return uniq, acc


def sparse_reduce_scatter(tp: PeerTransport, ids: np.ndarray,
                          rows: np.ndarray, bounds, *,
                          seq: int) -> tuple[np.ndarray, np.ndarray]:
    """Sparse reduce-scatter: every rank contributes (ids, rows); each row
    gradient scatters back to the rank whose shard range (``bounds``, the
    embedding plan's world+1 monotone id bounds) owns its id, where
    duplicates — within one contributor and across contributors — are
    EXACT-summed in rank order via :func:`combine_csr`.

    Returns ``(uniq_ids, summed_rows)`` for this rank's own id range.
    A rank with zero ids for some owner still ships the empty CSR frame
    (message-count agreement, like the dense ring's empty segments).
    """
    world, rank = tp.world, tp.rank
    ids = np.asarray(ids, dtype=np.int64).reshape(-1)
    rows = np.asarray(rows, np.float32)
    if rows.ndim != 2:
        raise CollectiveAborted(
            f"sparse_reduce_scatter rows must be [n, dim], got shape "
            f"{rows.shape} (pass np.empty((0, dim)) for an empty "
            "contribution — dim must survive the empty edge)")
    if rows.shape[0] != ids.size:
        raise CollectiveAborted(
            f"sparse_reduce_scatter got {ids.size} ids for "
            f"{rows.shape[0]} rows")
    dim = int(rows.shape[1])
    bounds = np.asarray(bounds, dtype=np.int64)
    if bounds.size != world + 1:
        raise CollectiveAborted(
            f"sparse_reduce_scatter bounds must have world+1={world + 1} "
            f"entries, got {bounds.size}")
    if ids.size and (ids.min() < bounds[0] or ids.max() >= bounds[-1]):
        raise CollectiveAborted(
            f"sparse ids outside the shard plan [{bounds[0]}, {bounds[-1]})")
    # partition by owner: searchsorted over the interior bounds maps each id
    # to the rank whose [bounds[r], bounds[r+1]) range holds it
    owner = np.searchsorted(bounds[1:-1], ids, side="right")
    parts = []
    for dst in range(world):
        take = np.flatnonzero(owner == dst)
        parts.append((ids[take], rows[take]))
    got = sparse_all_to_all(tp, parts, seq=seq)
    # rank-order combine: got[] is already indexed by source rank
    return combine_csr([g[0] for g in got], [g[1] for g in got], dim)
