"""Peer-to-peer collective transport over the existing zero-copy data wire.

The tensor plane of the cross-host collectives (ISSUE 12 / the ROADMAP's
"collectives over the cluster" item) rides the SAME wire the partition feed
already uses: a peer dials its neighbor's :class:`~tensorflowonspark_tpu.
dataserver.DataServer` port, passes the cluster HMAC handshake, and sends a
``collective_attach`` op that turns the connection into a one-way stream of
v2 (protocol-5, out-of-band-buffer) chunk frames — numpy gradient chunks
scatter-gather straight from their own memory (``utils.net.sendmsg_all``)
and land in preallocated receive buffers (``recv_into`` via the dataserver
framing layer).  No second listener, no second auth scheme: a node's
collective endpoint IS its registered ``data_port``.

Confinement contract (enforced by the ``dial-discipline`` checker): every
raw peer socket of the collective layer — the outbound dials here, the
attach-side receive loops the dataserver hands over — lives in THIS module.
``group.py``/``ops.py`` speak in ranks and tags only.

Generation fencing: every frame is stamped with the group *generation*
assigned by the coordinator rendezvous.  After an elastic restart re-forms
the group (a new generation), frames from a poisoned round — a fenced
zombie, a late buffer flush from a dead peer's socket — carry a stale
generation and are dropped by the inbox instead of corrupting a live
reduce; frames racing slightly AHEAD of a member's own reconfigure are
buffered until it catches up (the coordinator reply reaches members at
slightly different times).

Failure semantics: a broken inbound connection poisons every pending and
future receive from that peer *up to the generation the connection served*
(:class:`CollectiveAborted`), so survivors abort a poisoned round within
milliseconds of the death instead of riding out the full collective
timeout.  Higher generations are untouched — the peer's replacement
attaches with a fresh connection and a fresh generation.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import socket
import threading
import time

from tensorflowonspark_tpu import telemetry

logger = logging.getLogger(__name__)


class CollectiveAborted(RuntimeError):
    """A collective round was poisoned (peer death, timeout, stale
    generation): the caller must abandon the round, re-form the group at a
    new generation barrier, and resync state before continuing."""


# -- inbox registry (the dataserver's attach handler looks groups up here) ----

_registry_lock = threading.Lock()
_inboxes: dict[str, "CollectiveInbox"] = {}


def register_inbox(name: str, inbox: "CollectiveInbox") -> None:
    with _registry_lock:
        if name in _inboxes:
            raise RuntimeError(f"collective group {name!r} already registered "
                               "in this process")
        _inboxes[name] = inbox


def unregister_inbox(name: str) -> None:
    with _registry_lock:
        _inboxes.pop(name, None)


def lookup_inbox(name: str) -> "CollectiveInbox | None":
    with _registry_lock:
        return _inboxes.get(name)


class CollectiveInbox:
    """Per-group landing zone for inbound chunk frames.

    Delivery threads are the dataserver's per-connection handlers (one per
    attached peer); consumers are the group's collective ops.  Frames are
    keyed ``(generation, src_rank, seq, tag)`` — ``seq`` is the group's
    SPMD-consistent op counter (reset at each formation), ``tag`` the op's
    internal message id — so out-of-order arrival across peers can never
    mis-match a chunk.  Ahead-of-generation frames are buffered (a peer may
    finish the formation rendezvous microseconds earlier); behind-generation
    frames are dropped (fencing)."""

    def __init__(self, name: str):
        self.name = name
        self._cond = threading.Condition()
        self._frames: dict[tuple, collections.deque] = {}
        # src rank -> highest generation a broken connection was serving:
        # receives at or below it abort fast, above it are a NEW connection
        self._failed: dict[int, int] = {}
        self._generation = 0
        self._closed = False

    def advance_generation(self, generation: int) -> None:
        """A new formation completed: drop every stale-generation frame and
        failure record (fencing — a poisoned round's leftovers must never
        feed a live one)."""
        with self._cond:
            self._generation = generation
            self._frames = {k: v for k, v in self._frames.items()
                            if k[0] >= generation}
            self._failed = {s: g for s, g in self._failed.items()
                            if g >= generation}
            self._cond.notify_all()

    def deliver(self, generation: int, src: int, seq: int, tag, payload) -> None:
        with self._cond:
            if self._closed or generation < self._generation:
                return  # fenced: a stale round's frame
            self._frames.setdefault((generation, src, seq, tag),
                                    collections.deque()).append(payload)
            self._cond.notify_all()

    def fail_peer(self, src: int, generation: int) -> None:
        """An inbound connection from ``src`` (serving up to ``generation``)
        broke: poison matching receives so waiters abort immediately."""
        with self._cond:
            if generation >= self._failed.get(src, -1):
                self._failed[src] = generation
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._frames.clear()
            self._cond.notify_all()

    def recv(self, generation: int, src: int, seq: int, tag,
             timeout: float):
        """Block for one frame; raises :class:`CollectiveAborted` on peer
        failure, group close, or timeout (a silent peer must poison the
        round, not wedge the trainer)."""
        key = (generation, src, seq, tag)
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                q = self._frames.get(key)
                if q:
                    payload = q.popleft()
                    if not q:
                        del self._frames[key]
                    return payload
                if self._closed:
                    raise CollectiveAborted(
                        f"collective group {self.name!r} closed mid-receive")
                if self._failed.get(src, -1) >= generation:
                    raise CollectiveAborted(
                        f"peer rank {src} lost its connection (generation "
                        f"{generation}); round poisoned")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise CollectiveAborted(
                        f"timed out after {timeout:.0f}s waiting for chunk "
                        f"{tag!r} from rank {src} (generation {generation})")
                self._cond.wait(min(0.5, remaining))


# -- attach-side receive loop (runs on a dataserver connection thread) --------


def attach_error(name: str) -> str | None:
    """Validation half of the dataserver's ``collective_attach`` op: None
    when the named group's inbox is live in this process."""
    if lookup_inbox(name) is None:
        return (f"no collective group {name!r} registered in this process "
                "(peer attached before/after the group's lifetime)")
    return None


def serve_attached(conn: socket.socket, name: str, src_rank: int,
                   generation: int) -> None:
    """Receive loop for one attached peer connection: route chunk frames
    into the group's inbox until the peer closes (or the group goes away).
    Runs on the dataserver's per-connection thread — the reason sends from
    a compute thread can never deadlock against a peer that is also mid-
    send: every node's inbound wire is drained unconditionally."""
    from tensorflowonspark_tpu.dataserver import _recv_frame

    inbox = lookup_inbox(name)
    if inbox is None:
        return
    rx_bytes = telemetry.counter("collective.rx_bytes")
    rx_frames = telemetry.counter("collective.rx_frames")
    last_gen = generation
    try:
        while True:
            msg, _ = _recv_frame(conn)
            if not (isinstance(msg, tuple) and msg and msg[0] == "cchunk"):
                logger.warning("collective stream from rank %d carried a "
                               "non-chunk frame %r; closing", src_rank,
                               msg[0] if isinstance(msg, tuple) else msg)
                return
            _, gen, src, seq, tag, payload = msg
            last_gen = max(last_gen, int(gen))
            nbytes = getattr(payload, "nbytes", 0)
            rx_bytes.inc(int(nbytes))
            rx_frames.inc()
            inbox.deliver(int(gen), int(src), int(seq), tag, payload)
    except (ConnectionError, OSError, EOFError):
        return
    finally:
        # the inbox this loop was feeding may have been replaced by a later
        # group with the same name (close() then a fresh CollectiveGroup);
        # poison only OURS, never the successor's
        current = lookup_inbox(name)
        if current is inbox:
            inbox.fail_peer(src_rank, last_gen)


# -- outbound peer channels ---------------------------------------------------


class PeerTransport:
    """One node's collective endpoint set: the registered inbox (inbound)
    plus lazily-dialed outbound channels to peers, re-pointed at every
    formation (``configure``).  Sends run on the group's single comm thread;
    ``configure``/``close`` run on the map_fun thread — the small lock only
    guards the shared maps, never any blocking I/O."""

    def __init__(self, name: str, authkey: bytes, timeout: float):
        self.name = name
        self.authkey = authkey
        self.timeout = timeout
        self._lock = threading.Lock()
        self._conns: dict[int, socket.socket] = {}
        self._members: list[dict] = []
        self._generation = 0
        self._rank = -1
        self.inbox = CollectiveInbox(name)
        register_inbox(name, self.inbox)

    @property
    def rank(self) -> int:
        with self._lock:
            return self._rank

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    @property
    def world(self) -> int:
        with self._lock:
            return len(self._members)

    def configure(self, generation: int, rank: int, members: list[dict]) -> None:
        """Adopt a completed formation: new generation, rank, and peer
        endpoints.  Every cached outbound channel is dropped — a surviving
        socket may point at a dead predecessor's port, and the new
        generation must start from fresh dials."""
        with self._lock:
            self._generation = int(generation)
            self._rank = int(rank)
            self._members = [dict(m) for m in members]
        self.drop_connections()
        self.inbox.advance_generation(int(generation))

    def drop_connections(self) -> None:
        """Close every outbound channel (abort path + reconfigure): closing
        our ends makes each peer's attach loop see EOF and poison its round
        — the cascade that turns one death into a whole-ring abort within
        milliseconds instead of a timeout per hop."""
        with self._lock:
            conns, self._conns = self._conns, {}
        for sock in conns.values():
            with contextlib.suppress(OSError):
                sock.close()

    def poison_generation(self) -> None:
        """Abort the CURRENT generation locally and outward: every pending
        (and future) receive of this generation fails immediately — so a
        straggler op still running on the comm thread unblocks NOW, before
        any reform can reconfigure ranks/seq under it — and the closed
        outbound channels cascade the abort to every peer."""
        with self._lock:
            gen, world = self._generation, len(self._members)
        for src in range(world):
            self.inbox.fail_peer(src, gen)
        self.drop_connections()

    def _endpoint(self, dst: int) -> tuple[str, int]:
        with self._lock:
            if not 0 <= dst < len(self._members):
                raise CollectiveAborted(
                    f"rank {dst} is not a member of generation "
                    f"{self._generation}")
            m = self._members[dst]
            return str(m["host"]), int(m["port"])

    def _dial(self, dst: int) -> socket.socket:
        from tensorflowonspark_tpu.dataserver import _recv, _send
        from tensorflowonspark_tpu.utils.net import (
            connect_with_backoff,
            hmac_handshake_client,
        )

        host, port = self._endpoint(dst)
        with self._lock:
            gen, rank = self._generation, self._rank
        sock = connect_with_backoff((host, port), timeout=self.timeout,
                                    attempts=3)
        try:
            # bounded everything: a dead peer mid-handshake (or one whose
            # kernel buffer backs up mid-reduce) must poison the round, not
            # pin the comm thread forever
            sock.settimeout(self.timeout)
            if not hmac_handshake_client(sock, self.authkey):
                raise CollectiveAborted(
                    f"peer rank {dst} rejected the cluster authkey")
            _send(sock, ("collective_attach", self.name, rank, gen), wire=2)
            reply = _recv(sock)
            if not (isinstance(reply, tuple) and reply and reply[0] == "ok"):
                raise CollectiveAborted(
                    f"peer rank {dst} refused collective attach: "
                    f"{reply[1] if len(reply) > 1 else reply!r}")
        except (OSError, ConnectionError, EOFError) as e:
            with contextlib.suppress(OSError):
                sock.close()
            raise CollectiveAborted(
                f"could not attach to peer rank {dst} at {host}:{port}: {e}"
            ) from e
        except CollectiveAborted:
            with contextlib.suppress(OSError):
                sock.close()
            raise
        telemetry.counter("collective.attaches_total").inc()
        return sock

    def send(self, dst: int, seq: int, tag, payload) -> None:
        """Ship one chunk frame to ``dst`` (dialing lazily).  ``payload`` is
        usually a numpy array — it travels as a protocol-5 out-of-band
        buffer, scatter-gathered straight from its own memory — but any
        picklable object works (broadcast headers)."""
        from tensorflowonspark_tpu.dataserver import frame_parts
        from tensorflowonspark_tpu.utils.net import sendmsg_all

        with self._lock:
            sock = self._conns.get(dst)
            gen, rank = self._generation, self._rank
        if sock is None:
            sock = self._dial(dst)
            with self._lock:
                self._conns[dst] = sock
        parts = frame_parts(("cchunk", gen, rank, seq, tag, payload), wire=2)
        try:
            sendmsg_all(sock, parts)
        except (OSError, ConnectionError) as e:
            with self._lock:
                if self._conns.get(dst) is sock:
                    del self._conns[dst]
            with contextlib.suppress(OSError):
                sock.close()
            raise CollectiveAborted(
                f"send to peer rank {dst} failed mid-round: {e}") from e
        telemetry.counter("collective.tx_bytes").inc(
            int(getattr(payload, "nbytes", 0)))
        telemetry.counter("collective.tx_frames").inc()

    def recv(self, src: int, seq: int, tag, timeout: float | None = None):
        with self._lock:
            gen = self._generation
        return self.inbox.recv(gen, src, seq, tag,
                               self.timeout if timeout is None else timeout)

    def close(self) -> None:
        # unregister FIRST so a racing attach can't hand a connection to a
        # closed inbox; late attach attempts get a clean refusal instead
        current = lookup_inbox(self.name)
        if current is self.inbox:
            unregister_inbox(self.name)
        self.inbox.close()
        self.drop_connections()
