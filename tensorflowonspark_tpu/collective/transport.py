"""Peer-to-peer collective transport over the existing zero-copy data wire.

The tensor plane of the cross-host collectives (ISSUE 12 / the ROADMAP's
"collectives over the cluster" item) rides the SAME wire the partition feed
already uses: a peer dials its neighbor's :class:`~tensorflowonspark_tpu.
dataserver.DataServer` port, passes the cluster HMAC handshake, and sends a
``collective_attach`` op that turns the connection into a one-way stream of
v2 (protocol-5, out-of-band-buffer) chunk frames — numpy gradient chunks
scatter-gather straight from their own memory (``utils.net.sendmsg_all``)
and land in preallocated receive buffers (``recv_into`` via the dataserver
framing layer).  No second listener, no second auth scheme: a node's
collective endpoint IS its registered ``data_port``.

Confinement contract (enforced by the ``dial-discipline`` checker): every
raw peer socket of the collective layer — the outbound dials here, the
attach-side receive loops the dataserver hands over — lives in THIS module.
``group.py``/``ops.py`` speak in ranks and tags only.

Generation fencing: every frame is stamped with the group *generation*
assigned by the coordinator rendezvous.  After an elastic restart re-forms
the group (a new generation), frames from a poisoned round — a fenced
zombie, a late buffer flush from a dead peer's socket — carry a stale
generation and are dropped by the inbox instead of corrupting a live
reduce; frames racing slightly AHEAD of a member's own reconfigure are
buffered until it catches up (the coordinator reply reaches members at
slightly different times).

Failure semantics: a broken inbound connection poisons every pending and
future receive from that peer *up to the generation the connection served*
(:class:`CollectiveAborted`), so survivors abort a poisoned round within
milliseconds of the death instead of riding out the full collective
timeout.  Higher generations are untouched — the peer's replacement
attaches with a fresh connection and a fresh generation.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import socket
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_condition, tos_named_lock
import time

from tensorflowonspark_tpu import telemetry

logger = logging.getLogger(__name__)


class CollectiveAborted(RuntimeError):
    """A collective round was poisoned (peer death, timeout, stale
    generation): the caller must abandon the round, re-form the group at a
    new generation barrier, and resync state before continuing."""


class CollectiveTimeout(CollectiveAborted):
    """The specific abort where a receive TIMED OUT waiting on one peer —
    distinguished so the straggler-detection recv loop can keep slicing
    (and reporting suspicion) without mistaking a peer-failure poison or a
    group close for mere slowness."""


# -- sparse (CSR) payloads -----------------------------------------------------
#
# The sparse collectives (embedding tier) ship {row id -> value row} sets
# instead of dense segments.  The wire layout is CSR-style: one int64 id
# vector plus one contiguous values matrix (ids[i] owns values[i]), framed as
# a single chunk payload whose two arrays BOTH ride as protocol-5 out-of-band
# buffers — same zero-copy path as the dense ring, same generation fencing.


def pack_csr(ids, values) -> tuple:
    """(ids, values) -> one sparse chunk payload.

    ``ids`` is any int array-like ([n] global row ids), ``values`` the
    matching ``[n, dim]`` rows (``None`` for id-only frames — the lookup
    REQUEST direction of the embedding exchange, which asks for rows it
    does not yet have)."""
    import numpy as np

    ids = np.ascontiguousarray(np.asarray(ids, dtype=np.int64).reshape(-1))
    if values is None:
        return ("csr", ids, None)
    values = np.ascontiguousarray(np.asarray(values))
    if values.ndim != 2 or values.shape[0] != ids.shape[0]:
        raise ValueError(
            f"CSR payload shape mismatch: {ids.shape[0]} ids vs values "
            f"{values.shape}")
    return ("csr", ids, values)


def unpack_csr(payload) -> tuple:
    """One sparse chunk payload -> (ids, values) (``values`` may be None)."""
    if not (isinstance(payload, tuple) and len(payload) == 3
            and payload[0] == "csr"):
        raise CollectiveAborted(
            f"expected a CSR sparse chunk, got {type(payload).__name__}")
    return payload[1], payload[2]


def payload_nbytes(payload) -> int:
    """Wire-metering size of a chunk payload: dense arrays meter their own
    ``nbytes``; CSR tuples meter ids + values (the bytes the sparse-vs-dense
    bench headline compares).  Headers and other picklable odds and ends
    meter 0 — metering exists for the tensor plane, not control chatter."""
    n = getattr(payload, "nbytes", None)
    if n is not None:
        return int(n)
    if isinstance(payload, tuple):
        return sum(int(getattr(p, "nbytes", 0) or 0) for p in payload)
    return 0


# -- inbox registry (the dataserver's attach handler looks groups up here) ----

_registry_lock = tos_named_lock("transport._registry_lock")
_inboxes: dict[str, "CollectiveInbox"] = {}


def register_inbox(name: str, inbox: "CollectiveInbox") -> None:
    with _registry_lock:
        if name in _inboxes:
            raise RuntimeError(f"collective group {name!r} already registered "
                               "in this process")
        _inboxes[name] = inbox


def unregister_inbox(name: str) -> None:
    with _registry_lock:
        _inboxes.pop(name, None)


def lookup_inbox(name: str) -> "CollectiveInbox | None":
    with _registry_lock:
        return _inboxes.get(name)


class CollectiveInbox:
    """Per-group landing zone for inbound chunk frames.

    Delivery threads are the dataserver's per-connection handlers (one per
    attached peer); consumers are the group's collective ops.  Frames are
    keyed ``(generation, src_rank, seq, tag)`` — ``seq`` is the group's
    SPMD-consistent op counter (reset at each formation), ``tag`` the op's
    internal message id — so out-of-order arrival across peers can never
    mis-match a chunk.  Ahead-of-generation frames are buffered (a peer may
    finish the formation rendezvous microseconds earlier); behind-generation
    frames are dropped (fencing)."""

    def __init__(self, name: str):
        self.name = name
        self._cond = tos_named_condition("transport.inbox._cond")
        self._frames: dict[tuple, collections.deque] = {}
        # src rank -> highest generation a broken connection was serving:
        # receives at or below it abort fast, above it are a NEW connection
        self._failed: dict[int, int] = {}
        self._generation = 0
        # Membership fence (gray-failure eviction): the eids of the CURRENT
        # formation and its world size.  A frame at the current generation
        # from a rank outside the live world is an evicted (or otherwise
        # fenced) peer still moving bytes — dropped, and its attach
        # connection severed.  None until the first formation.
        self._member_eids: set[int] | None = None
        self._world = 0
        # eid -> attach connections feeding this inbox (the dataserver hands
        # them over); tracked so eviction can HARD-SEVER a non-member's wire
        # instead of letting a zombie stream into the void forever.
        self._attach_conns: dict[int, list] = {}
        self._closed = False

    def advance_generation(self, generation: int,
                           member_eids: list[int] | None = None) -> None:
        """A new formation completed: drop every stale-generation frame and
        failure record (fencing — a poisoned round's leftovers must never
        feed a live one), adopt the live membership, and sever any attach
        connection from a peer that is no longer a member (the documented
        zombie window: a fenced-but-alive peer keeps its socket open and
        keeps moving bytes — close OUR end so it stops here)."""
        stale: list = []
        with self._cond:
            self._generation = generation
            self._frames = {k: v for k, v in self._frames.items()
                            if k[0] >= generation}
            self._failed = {s: g for s, g in self._failed.items()
                            if g >= generation}
            if member_eids is not None:
                self._member_eids = {int(e) for e in member_eids}
                self._world = len(self._member_eids)
                for eid in list(self._attach_conns):
                    if eid >= 0 and eid not in self._member_eids:
                        stale.extend(self._attach_conns.pop(eid))
            self._cond.notify_all()
        for conn in stale:
            with contextlib.suppress(OSError):
                conn.close()
        if stale:
            telemetry.counter("collective.severed_conns").inc(len(stale))

    def admits(self, src_eid: int, generation: int) -> bool:
        """Attach-time membership gate: a peer that is NOT in the current
        formation may only attach for a LATER generation (a readmitted
        member racing slightly ahead of our own reconfigure); at or below
        the current generation it is fenced out."""
        with self._cond:
            if self._member_eids is None or src_eid < 0:
                return True
            if src_eid in self._member_eids:
                return True
            return generation > self._generation

    def note_attach(self, src_eid: int, conn) -> None:
        with self._cond:
            self._attach_conns.setdefault(src_eid, []).append(conn)

    def forget_attach(self, src_eid: int, conn) -> None:
        with self._cond:
            conns = self._attach_conns.get(src_eid)
            if conns and conn in conns:
                conns.remove(conn)
                if not conns:
                    del self._attach_conns[src_eid]

    def deliver(self, generation: int, src: int, seq: int, tag, payload) -> None:
        with self._cond:
            if self._closed or generation < self._generation:
                return  # fenced: a stale round's frame
            if generation == self._generation and self._world \
                    and not 0 <= src < self._world:
                return  # fenced: a non-member rank's frame (evicted zombie)
            self._frames.setdefault((generation, src, seq, tag),
                                    collections.deque()).append(payload)
            self._cond.notify_all()

    def fail_peer(self, src: int, generation: int) -> None:
        """An inbound connection from ``src`` (serving up to ``generation``)
        broke: poison matching receives so waiters abort immediately."""
        with self._cond:
            if generation >= self._failed.get(src, -1):
                self._failed[src] = generation
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._frames.clear()
            self._cond.notify_all()

    def recv(self, generation: int, src: int, seq: int, tag,
             timeout: float):
        """Block for one frame; raises :class:`CollectiveAborted` on peer
        failure, group close, or timeout (a silent peer must poison the
        round, not wedge the trainer)."""
        key = (generation, src, seq, tag)
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                q = self._frames.get(key)
                if q:
                    payload = q.popleft()
                    if not q:
                        del self._frames[key]
                    return payload
                if self._closed:
                    raise CollectiveAborted(
                        f"collective group {self.name!r} closed mid-receive")
                if self._failed.get(src, -1) >= generation:
                    raise CollectiveAborted(
                        f"peer rank {src} lost its connection (generation "
                        f"{generation}); round poisoned")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise CollectiveTimeout(
                        f"timed out after {timeout:.0f}s waiting for chunk "
                        f"{tag!r} from rank {src} (generation {generation})")
                self._cond.wait(min(0.5, remaining))


# -- attach-side receive loop (runs on a dataserver connection thread) --------


def attach_error(name: str, src_eid: int = -1,
                 generation: int = 0) -> str | None:
    """Validation half of the dataserver's ``collective_attach`` op: None
    when the named group's inbox is live in this process AND the peer is
    admitted by the membership fence (an evicted member re-dialing at its
    stale generation gets a clean refusal, never a silent stream into a
    fence)."""
    inbox = lookup_inbox(name)
    if inbox is None:
        return (f"no collective group {name!r} registered in this process "
                "(peer attached before/after the group's lifetime)")
    if not inbox.admits(src_eid, generation):
        return (f"executor {src_eid} is not a member of collective group "
                f"{name!r} at generation {generation} (evicted or fenced); "
                "attach refused")
    return None


def serve_attached(conn: socket.socket, name: str, src_rank: int,
                   generation: int, src_eid: int = -1) -> None:
    """Receive loop for one attached peer connection: route chunk frames
    into the group's inbox until the peer closes (or the group goes away).
    Runs on the dataserver's per-connection thread — the reason sends from
    a compute thread can never deadlock against a peer that is also mid-
    send: every node's inbound wire is drained unconditionally.  The
    connection is registered against the sender's eid so a membership
    change (eviction) can hard-sever it from our side."""
    from tensorflowonspark_tpu.dataserver import _recv_frame

    inbox = lookup_inbox(name)
    if inbox is None:
        return
    inbox.note_attach(src_eid, conn)
    rx_bytes = telemetry.counter("collective.rx_bytes")
    rx_frames = telemetry.counter("collective.rx_frames")
    last_gen = generation
    try:
        while True:
            msg, _ = _recv_frame(conn)
            if not (isinstance(msg, tuple) and msg and msg[0] == "cchunk"):
                logger.warning("collective stream from rank %d carried a "
                               "non-chunk frame %r; closing", src_rank,
                               msg[0] if isinstance(msg, tuple) else msg)
                return
            _, gen, src, seq, tag, payload = msg
            last_gen = max(last_gen, int(gen))
            rx_bytes.inc(payload_nbytes(payload))
            rx_frames.inc()
            inbox.deliver(int(gen), int(src), int(seq), tag, payload)
    except (ConnectionError, OSError, EOFError):
        return
    finally:
        # the inbox this loop was feeding may have been replaced by a later
        # group with the same name (close() then a fresh CollectiveGroup);
        # poison only OURS, never the successor's
        current = lookup_inbox(name)
        if current is inbox:
            inbox.forget_attach(src_eid, conn)
            inbox.fail_peer(src_rank, last_gen)


# -- outbound peer channels ---------------------------------------------------


class PeerTransport:
    """One node's collective endpoint set: the registered inbox (inbound)
    plus lazily-dialed outbound channels to peers, re-pointed at every
    formation (``configure``).  Sends run on the group's single comm thread;
    ``configure``/``close`` run on the map_fun thread — the small lock only
    guards the shared maps, never any blocking I/O."""

    def __init__(self, name: str, authkey: bytes, timeout: float,
                 detect: bool = True):
        from tensorflowonspark_tpu.utils.envtune import env_float

        self.name = name
        self.authkey = authkey
        self.timeout = timeout
        self._lock = tos_named_lock("transport.peer._lock")
        self._conns: dict[int, socket.socket] = {}
        self._members: list[dict] = []
        self._generation = 0
        self._rank = -1
        self._eid = -1
        # Straggler detection (gray-failure tolerance): rolling EMA of
        # COMPLETED recv waits is the "typical contribution time" baseline;
        # a wait running TOS_COLLECTIVE_SUSPECT_FACTOR past it is a
        # persistent outlier worth reporting.  Relative by construction:
        # uniform slowness (a degraded network hitting everyone) raises the
        # baseline with the waits and never flags anyone.
        self.detect = bool(detect)
        self._suspect_factor = max(1.5, env_float(
            "TOS_COLLECTIVE_SUSPECT_FACTOR", 8.0))
        self._suspect_cb = None
        self._wait_ema: float | None = None
        self._reported: dict[tuple[int, int], float] = {}
        self.inbox = CollectiveInbox(name)
        register_inbox(name, self.inbox)

    @property
    def rank(self) -> int:
        with self._lock:
            return self._rank

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    @property
    def world(self) -> int:
        with self._lock:
            return len(self._members)

    def set_suspect_callback(self, cb) -> None:
        """Install the group's suspicion reporter: ``cb(src_rank,
        wait_secs) -> bool`` files a vote with the coordinator and returns
        True when a member of the CURRENT formation was evicted at quorum —
        the cue for a blocked recv to abort now instead of riding out the
        full collective timeout."""
        self._suspect_cb = cb

    def member_eids(self) -> list[int]:
        """Executor ids of the current formation, rank-ordered."""
        with self._lock:
            return [int(m["eid"]) for m in self._members]

    def configure(self, generation: int, rank: int, members: list[dict]) -> None:
        """Adopt a completed formation: new generation, rank, and peer
        endpoints.  Every cached outbound channel is dropped — a surviving
        socket may point at a dead predecessor's port, and the new
        generation must start from fresh dials.  The inbox adopts the live
        membership too, severing any attach connection from an evicted
        (non-member) peer — the hard half of the peer-plane fence."""
        with self._lock:
            self._generation = int(generation)
            self._rank = int(rank)
            self._members = [dict(m) for m in members]
            if 0 <= rank < len(members):
                self._eid = int(members[rank]["eid"])
            self._reported.clear()
        self.drop_connections()
        self.inbox.advance_generation(
            int(generation), [int(m["eid"]) for m in members])

    def drop_connections(self) -> None:
        """Close every outbound channel (abort path + reconfigure): closing
        our ends makes each peer's attach loop see EOF and poison its round
        — the cascade that turns one death into a whole-ring abort within
        milliseconds instead of a timeout per hop."""
        with self._lock:
            conns, self._conns = self._conns, {}
        for sock in conns.values():
            with contextlib.suppress(OSError):
                sock.close()

    def poison_generation(self) -> None:
        """Abort the CURRENT generation locally and outward: every pending
        (and future) receive of this generation fails immediately — so a
        straggler op still running on the comm thread unblocks NOW, before
        any reform can reconfigure ranks/seq under it — and the closed
        outbound channels cascade the abort to every peer."""
        with self._lock:
            gen, world = self._generation, len(self._members)
        for src in range(world):
            self.inbox.fail_peer(src, gen)
        self.drop_connections()

    def _endpoint(self, dst: int) -> tuple[str, int]:
        with self._lock:
            if not 0 <= dst < len(self._members):
                raise CollectiveAborted(
                    f"rank {dst} is not a member of generation "
                    f"{self._generation}")
            m = self._members[dst]
            return str(m["host"]), int(m["port"])

    def _dial(self, dst: int) -> socket.socket:
        from tensorflowonspark_tpu.dataserver import _recv, _send
        from tensorflowonspark_tpu.utils.net import (
            connect_with_backoff,
            hmac_handshake_client,
        )

        host, port = self._endpoint(dst)
        with self._lock:
            gen, rank, eid = self._generation, self._rank, self._eid
        sock = connect_with_backoff((host, port), timeout=self.timeout,
                                    attempts=3)
        try:
            # bounded everything: a dead peer mid-handshake (or one whose
            # kernel buffer backs up mid-reduce) must poison the round, not
            # pin the comm thread forever
            sock.settimeout(self.timeout)
            if not hmac_handshake_client(sock, self.authkey):
                raise CollectiveAborted(
                    f"peer rank {dst} rejected the cluster authkey")
            # the attach carries our eid so the receiver can key the
            # connection for membership severing (gray-failure fencing)
            _send(sock, ("collective_attach", self.name, rank, gen, eid),
                  wire=2)
            reply = _recv(sock)
            if not (isinstance(reply, tuple) and reply and reply[0] == "ok"):
                raise CollectiveAborted(
                    f"peer rank {dst} refused collective attach: "
                    f"{reply[1] if len(reply) > 1 else reply!r}")
        except (OSError, ConnectionError, EOFError) as e:
            with contextlib.suppress(OSError):
                sock.close()
            raise CollectiveAborted(
                f"could not attach to peer rank {dst} at {host}:{port}: {e}"
            ) from e
        except CollectiveAborted:
            with contextlib.suppress(OSError):
                sock.close()
            raise
        telemetry.counter("collective.attaches_total").inc()
        return sock

    def send(self, dst: int, seq: int, tag, payload) -> None:
        """Ship one chunk frame to ``dst`` (dialing lazily).  ``payload`` is
        usually a numpy array — it travels as a protocol-5 out-of-band
        buffer, scatter-gathered straight from its own memory — but any
        picklable object works (broadcast headers)."""
        from tensorflowonspark_tpu import faultinject
        from tensorflowonspark_tpu.dataserver import frame_parts
        from tensorflowonspark_tpu.utils.net import sendmsg_all

        # chaos seam: `slow_peer:ms=M` injects degraded-NIC latency on
        # every peer-plane send in the armed process
        faultinject.peer_send_delay()
        with self._lock:
            sock = self._conns.get(dst)
            gen, rank = self._generation, self._rank
        if sock is None:
            sock = self._dial(dst)
            with self._lock:
                self._conns[dst] = sock
        parts = frame_parts(("cchunk", gen, rank, seq, tag, payload), wire=2)
        try:
            sendmsg_all(sock, parts)
        except (OSError, ConnectionError) as e:
            with self._lock:
                if self._conns.get(dst) is sock:
                    del self._conns[dst]
            with contextlib.suppress(OSError):
                sock.close()
            raise CollectiveAborted(
                f"send to peer rank {dst} failed mid-round: {e}") from e
        telemetry.counter("collective.tx_bytes").inc(payload_nbytes(payload))
        telemetry.counter("collective.tx_frames").inc()

    def _note_wait(self, wait: float) -> None:
        """Fold one COMPLETED recv wait into the rolling baseline."""
        with self._lock:
            if self._wait_ema is None:
                self._wait_ema = wait
            else:
                self._wait_ema += 0.2 * (wait - self._wait_ema)

    def suspect_threshold(self, budget: float) -> float:
        """Wait (seconds) past which a peer is a persistent outlier worth
        reporting: SUSPECT_FACTOR x the rolling typical wait, floored at
        0.5s (below that is scheduler noise, not a gray failure) and capped
        at a quarter of the recv budget (detection must always beat the
        round timeout, or eviction never improves on thrashing).  With NO
        baseline yet (the group's first round: dials, attaches, cold TCP
        windows) the floor doubles — connection setup must not read as a
        stall."""
        with self._lock:
            ema = self._wait_ema
        floor = 0.5 if ema is not None else 1.0
        base = max(ema if ema is not None else 0.0, 1e-3)
        return min(max(self._suspect_factor * base, floor),
                   max(floor, budget / 4.0))

    def _maybe_report(self, generation: int, src: int, waited: float) -> bool:
        """Rate-limited suspicion report (at most one per second per
        (generation, src)); True when the callback says the current round
        is doomed (a member was evicted at quorum)."""
        cb = self._suspect_cb
        if cb is None:
            return False
        now = time.monotonic()
        with self._lock:
            if now - self._reported.get((generation, src), 0.0) < 1.0:
                return False
            self._reported[(generation, src)] = now
        try:
            return bool(cb(src, waited))
        except Exception:  # noqa: BLE001 - reporting must never poison a healthy round
            logger.debug("suspicion report for rank %d failed", src,
                         exc_info=True)
            return False

    def recv(self, src: int, seq: int, tag, timeout: float | None = None):
        """Blocking receive with straggler detection: the wait is sliced so
        that once it runs ``suspect_threshold`` past the rolling typical
        wait, a suspicion vote is filed with the coordinator (abort
        attribution: the vote names the peer we are waiting ON) — and if
        quorum evicts a member of this formation, the round aborts NOW
        instead of riding out the remaining collective timeout."""
        with self._lock:
            gen = self._generation
        budget = self.timeout if timeout is None else timeout
        if not self.detect or self._suspect_cb is None:
            wait_t0 = time.monotonic()
            payload = self.inbox.recv(gen, src, seq, tag, budget)
            if self.detect:
                self._note_wait(time.monotonic() - wait_t0)
            return payload
        deadline = time.monotonic() + budget
        threshold = self.suspect_threshold(budget)
        t0 = time.monotonic()
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # final abort attribution: the timeout itself is the
                # strongest suspicion signal — file it before poisoning
                self._maybe_report(gen, src, time.monotonic() - t0)
                raise CollectiveTimeout(
                    f"timed out after {budget:.0f}s waiting for chunk "
                    f"{tag!r} from rank {src} (generation {gen})")
            slice_ = min(remaining, max(0.05, threshold / 2.0))
            try:
                payload = self.inbox.recv(gen, src, seq, tag, slice_)
            except CollectiveTimeout:
                waited = time.monotonic() - t0
                if waited >= threshold and self._maybe_report(gen, src,
                                                              waited):
                    raise CollectiveAborted(
                        f"peer rank {src} evicted at quorum after waiting "
                        f"{waited:.1f}s (generation {gen}); round "
                        "poisoned") from None
                continue
            self._note_wait(time.monotonic() - t0)
            return payload

    def close(self) -> None:
        # unregister FIRST so a racing attach can't hand a connection to a
        # closed inbox; late attach attempts get a clean refusal instead
        current = lookup_inbox(self.name)
        if current is self.inbox:
            unregister_inbox(self.name)
        self.inbox.close()
        self.drop_connections()
