"""Coordinator-brokered collective group: rendezvous, generations, buckets.

:class:`CollectiveGroup` is the map_fun-facing handle for cross-host
synchronous training (the ROADMAP's ParameterServer/MWMS replacement at
cluster scope): the coordinator's rendezvous assigns **rank / world-size /
peer endpoints** and a monotone **generation** (the ``form`` reduce kind in
``coordinator.py``); the tensor plane then runs rank-to-rank over each
node's existing data-plane port (``transport.py``), and every gradient
exchange is a bucketed ring all-reduce (``ops.py``).

Elastic rejoin — the generation barrier
---------------------------------------

A peer death poisons the in-flight round: every member observes
:class:`CollectiveAborted` within milliseconds (broken-connection cascade,
see ``transport.py``) instead of deadlocking.  Recovery is then symmetric
for survivors and the supervised replacement:

1. everyone calls :meth:`reform` — a fresh coordinator rendezvous at a
   bumped generation (the **generation barrier**: nothing proceeds until
   the full world, including the restarted slot, stands at it; the
   coordinator's incarnation fencing keeps the dead predecessor out);
2. everyone calls :meth:`sync_state` — the member that voted the highest
   step (a survivor holding live state, or everyone's checkpoint step
   after a full restart) broadcasts its state tree, and the group resumes
   from that step in lockstep.

Stale traffic from the aborted generation — late kernel-buffer flushes, a
fenced zombie's chunks — carries the old generation stamp and is dropped.

Gradient buckets
----------------

:meth:`all_reduce_tree` (and the :func:`grad_fn` hook it powers, consumed
by ``parallel.dp.make_train_step(cross_host_grad_fn=...)``) packs pytree
leaves into ``TOS_COLLECTIVE_BUCKET_BYTES`` buckets per dtype and flushes
each bucket to the comm thread AS IT FILLS: bucket *k*'s ring all-reduce
runs concurrently with the host-side device_get/pack of bucket *k+1*, so
communication overlaps the tail of backprop instead of serializing after
it.

Gray failures — stragglers, quorum eviction, degraded worlds
------------------------------------------------------------

Deaths are the easy case; a *slow-but-alive* member (GC pause, one stolen
core, a degraded NIC) used to stall every round for the full collective
timeout and then thrash: reform re-admitted it at full world and the next
round stalled again.  The gray-failure path (ISSUE 15):

1. **Detection** — the transport keeps per-peer rolling contribution
   timings; a wait running ``TOS_COLLECTIVE_SUSPECT_FACTOR`` past the
   rolling baseline files a ``suspect`` vote with the coordinator
   (relative, so uniform slowness never flags anyone; abort attribution —
   the vote names the peer being waited on).
2. **Quorum eviction** — the coordinator resolves transitive blame (a
   member that is itself complaining about its upstream is a pipeline
   victim, not the straggler) and at ``TOS_COLLECTIVE_EVICT_QUORUM``
   survivor votes EVICTS: the member's incarnation is fenced and the
   process parks in probation (``TOS_COLLECTIVE_PROBATION_SECS``) instead
   of being respawned into the group.
3. **Degraded-world continuation** — :meth:`form` rendezvouses at the
   *effective* world (nominal minus evicted, coordinator-adjudicated), so
   survivors resume at W-1 well inside one collective timeout;
   :meth:`check_grow` notices a readmitted member and the next
   :meth:`reform` grows the world back at a later generation barrier.
4. **Hard peer-plane fencing** — the old known limitation (a fenced-but-
   alive zombie could keep moving bytes on the peer plane until the next
   reform) is closed: survivors' inboxes reject frames by (generation,
   membership), refuse attaches from non-members, and actively sever an
   evicted peer's attach connections at reconfigure.
"""

from __future__ import annotations

import concurrent.futures
import logging
import time

import numpy as np

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu.collective import ops as cops
from tensorflowonspark_tpu.collective.transport import (
    CollectiveAborted,
    PeerTransport,
)
from tensorflowonspark_tpu.coordinator import CoordinatorClient, CoordinatorFenced
from tensorflowonspark_tpu.telemetry import trace as ttrace
from tensorflowonspark_tpu.utils.envtune import env_float, env_int, env_str

logger = logging.getLogger(__name__)


def _plan_buckets(leaves: list, bucket_bytes: int) -> list[list[int]]:
    """Greedy leaf->bucket assignment: consecutive same-dtype leaves pack
    into buckets of at most ``bucket_bytes`` (an oversized leaf is its own
    bucket — ring chunking bounds its frames).  Consecutive-only on
    purpose: packing preserves tree order, so unpacking is pure slicing."""
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i, leaf in enumerate(leaves):
        # shape/dtype attributes only: np.asarray here would force a
        # device->host transfer during PLANNING, before any overlap begins
        dtype = np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype))
        shape = tuple(getattr(leaf, "shape", ()))
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if cur and (dtype != cur_dtype or cur_bytes + nbytes > bucket_bytes):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_dtype = dtype
    if cur:
        buckets.append(cur)
    return buckets


class CollectiveGroup:
    """One node's membership in a named cluster-wide collective group.

    Construct via :meth:`NodeContext.collective_group` inside a map_fun (or
    directly with explicit endpoints — the bench does), then :meth:`form`
    before the first collective.  All collectives are SPMD calls: every
    member must make the same sequence of calls with compatible arrays.
    Thread contract: the constructing thread owns the public API; the
    internal comm executor serializes all peer I/O.
    """

    def __init__(self, coordinator_addr: tuple[str, int], authkey: bytes,
                 executor_id: int, world: int, host: str, data_port: int,
                 name: str = "train", incarnation: int = 0,
                 timeout: float | None = None,
                 bucket_bytes: int | None = None, detect: bool = True):
        if world < 1:
            raise ValueError("collective group needs world >= 1")
        self.name = name
        self.executor_id = int(executor_id)
        # NOMINAL world: the full membership this group was sized for.
        # After a gray-failure eviction the group runs DEGRADED — the
        # effective world (len(self._members), coordinator-adjudicated at
        # each form) may be smaller until the evicted member grows back in.
        self.world = int(world)
        self.incarnation = int(incarnation)
        self._host = host
        self._data_port = int(data_port)
        self._timeout = (env_float("TOS_COLLECTIVE_TIMEOUT", 120.0)
                         if timeout is None else float(timeout))
        self._algo = env_str("TOS_COLLECTIVE_ALGO", "ring")
        self._bucket_bytes = (env_int("TOS_COLLECTIVE_BUCKET_BYTES", 4 << 20)
                              if bucket_bytes is None else int(bucket_bytes))
        # Dedicated control-plane connection: formation rendezvous can block
        # through a whole restart window and must never wedge the node's
        # main client (heartbeats already have their own).
        self._coordinator_addr = coordinator_addr
        self._authkey = authkey
        self._client = CoordinatorClient(coordinator_addr, authkey=authkey)
        self._client.set_identity(self.executor_id, self.incarnation)
        self._tp = PeerTransport(name, authkey, self._timeout, detect=detect)
        # straggler detection: the transport measures, this group reports.
        # The vote gets its OWN lazy connection (bounded dial + call): the
        # main client's lock can be held across a minutes-long blocking
        # barrier, and a suspicion that cannot be filed is an eviction
        # that never happens.
        self._tp.set_suspect_callback(self._report_suspect)
        self._sus_client: CoordinatorClient | None = None
        self._grow_checked = 0.0
        # ONE comm thread: serializes all peer I/O (sends never interleave)
        # and is the overlap engine — bucket k reduces here while the caller
        # packs bucket k+1.
        self._exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"collective-{name}")
        self.rank = -1
        self.generation = 0
        self.agreed_step = 0
        self._root_rank = 0
        self._members: list[dict] = []
        self._seq = 0
        self._closed = False

    # -- formation / the generation barrier -----------------------------------

    def form(self, resume_step: int = 0, timeout: float | None = None) -> int:
        """Rendezvous with every member at a fresh generation; returns the
        group's agreed resume step (the max of all members' votes — a
        survivor's live step, or the checkpoint step after a cold start).

        Retries through coordinator-side aborts: a rendezvous generation
        poisoned by a death declaration (or by one member timing out while
        the restarted slot is still booting) is simply re-entered until the
        full world stands at the barrier or ``timeout`` expires.  A
        coordinator CRASH mid-formation rides the same loop: the client
        reconnects with backoff against the journal-recovered server (or a
        ``CoordinatorRestarted``/epoch-fence reply) and re-enters — the
        generation barrier is also the control-plane failover barrier.
        """
        if self._closed:
            raise CollectiveAborted(f"collective group {self.name!r} is closed")
        budget = self._timeout if timeout is None else float(timeout)
        deadline = time.monotonic() + budget
        t0 = time.monotonic()
        last_err: Exception | None = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise CollectiveAborted(
                    f"collective group {self.name!r} did not form within "
                    f"{budget:.0f}s (world {self.world}): {last_err}")
            # Degraded-world rendezvous: form at the coordinator-adjudicated
            # EFFECTIVE world (nominal minus evicted members), re-queried
            # every attempt — an eviction or readmission landing mid-retry
            # is picked up at the next pass.  Re-stamped each attempt: a
            # readmission hands this client its bumped incarnation on the
            # reply, and the next join must carry it.
            count = self._effective_world() or self.world
            me = {"eid": self.executor_id, "host": self._host,
                  "port": self._data_port, "gen": self.generation + 1,
                  "step": int(resume_step),
                  "incarnation": self._client.incarnation}
            try:
                result = self._client.collective_form(
                    f"cg.{self.name}.form", me, count=count,
                    timeout=min(10.0, max(1.0, remaining)))
                break
            except CoordinatorFenced as e:
                # EVICTED (gray failure) or genuinely fenced: ride out the
                # probation — the coordinator readmits this process on a
                # heartbeat once probation expires, the reply hands every
                # client the bumped incarnation, and the next join passes.
                # A dead slot's zombie never readmits and times out here.
                last_err = e
                time.sleep(0.5)
            except (RuntimeError, ConnectionError) as e:
                # peer-abort / slice timeout / death-declaration abort /
                # coordinator failover (CoordinatorRestarted, or the
                # reconnect itself still failing while the control plane
                # restores): re-enter the barrier — the restarted slot may
                # still be riding out its supervisor backoff, and a
                # recovering coordinator its own
                last_err = e
                time.sleep(0.2)
        members = result["members"]
        ranks = [int(m["eid"]) for m in members]
        if self.executor_id not in ranks:
            raise CollectiveAborted(
                f"formation of {self.name!r} completed without this node "
                f"(executor {self.executor_id} not in {ranks})")
        # a readmitted member adopted its bumped incarnation on the wire;
        # the group-level view follows so peers/telemetry see the truth
        self.incarnation = max(self.incarnation, self._client.incarnation)
        self.rank = ranks.index(self.executor_id)
        self.generation = int(result["generation"])
        self.agreed_step = int(result["step"])
        # state root: the lowest rank among the highest-step voters — the
        # member whose state tree sync_state broadcasts
        steps = [int(m.get("step", 0)) for m in members]
        self._root_rank = steps.index(max(steps))
        self._members = members
        self._seq = 0  # SPMD op counter restarts with the generation
        self._tp.configure(self.generation, self.rank, members)
        telemetry.gauge("collective.generation").set(self.generation)
        telemetry.counter("collective.formations_total").inc()
        telemetry.histogram("collective.form_secs").observe(
            time.monotonic() - t0)
        ttrace.event("collective_form", group=self.name,
                     generation=self.generation, rank=self.rank,
                     world=len(members), nominal_world=self.world,
                     step=self.agreed_step)
        if len(members) < self.world:
            telemetry.gauge("collective.degraded_world").set(len(members))
            logger.warning(
                "collective group %r formed DEGRADED: %d/%d members "
                "(evicted slots excluded), generation %d",
                self.name, len(members), self.world, self.generation)
        else:
            telemetry.gauge("collective.degraded_world").set(0)
        logger.info("collective group %r formed: generation %d, rank %d/%d, "
                    "agreed step %d", self.name, self.generation, self.rank,
                    len(members), self.agreed_step)
        return self.agreed_step

    def reform(self, resume_step: int = 0,
               timeout: float | None = None) -> int:
        """Re-form after an aborted round (peer death / timeout): poison the
        current generation, DRAIN the comm thread, and stand at the next
        generation barrier.  Survivors pass their live step; a restarted
        node passes its checkpoint step (0 when it has none) —
        :meth:`sync_state` then levels everyone.

        The drain matters: a straggler bucket flight still running on the
        comm thread would otherwise race the reconfigure — its sends would
        pick up the NEW generation and rank table, and with ``_seq`` reset
        at formation its stale chunks could collide with a fresh round's
        ``(generation, seq, tag)`` keys.  Poisoning first makes the
        straggler fail within milliseconds, so the drain is cheap."""
        self._tp.poison_generation()
        sentinel = self._exec.submit(lambda: None)
        try:
            # single comm worker: this resolves only after every previously
            # submitted flight finished (poisoned, so promptly)
            sentinel.result(timeout=self._timeout + 30.0)
        except concurrent.futures.TimeoutError:
            raise CollectiveAborted(
                "comm thread did not drain after poisoning the aborted "
                "generation; cannot safely re-form") from None
        telemetry.counter("collective.reforms_total").inc()
        return self.form(resume_step=resume_step, timeout=timeout)

    # -- gray-failure detection / degraded worlds ------------------------------

    @property
    def effective_world(self) -> int:
        """Members in the CURRENT formation (may be below the nominal
        ``world`` while an evicted member sits in probation)."""
        return len(self._members) if self._members else self.world

    def _effective_world(self) -> int | None:
        """Coordinator-adjudicated formation count: nominal world minus the
        group's evicted members.  None when the query cannot answer (e.g.
        this client is itself fenced — the form attempt will say so)."""
        try:
            resp = self._client.collective_world(self.name, self.world)
        except (RuntimeError, OSError, ValueError):
            # transient control-plane faults (incl. a post-reconnect resend
            # failing with a raw OSError, or a torn frame's ValueError) are
            # ridden out by the caller's retry loop, never propagated into
            # a training step
            return None
        eff = resp.get("effective")
        return int(eff) if eff is not None else None

    def _suspect_channel(self) -> CoordinatorClient:
        """Lazy dedicated connection for suspicion votes, every phase
        bounded (single dial attempt, call timeout): the comm thread files
        these mid-recv, and neither a busy main client nor a blackholed
        coordinator may wedge it."""
        if self._sus_client is None:
            client = CoordinatorClient(
                self._coordinator_addr, authkey=self._authkey,
                connect_timeout=5.0, connect_attempts=1, call_timeout=10.0)
            client.set_identity(self.executor_id, self._client.incarnation)
            self._sus_client = client
        return self._sus_client

    def _report_suspect(self, src_rank: int, wait_secs: float) -> bool:
        """Transport callback: file a suspicion vote against the peer this
        node has been waiting on (abort attribution included — the vote
        names the rank, the coordinator resolves transitive blame).  True
        when quorum evicted a member of the CURRENT formation: the caller
        aborts the round now and re-forms at the degraded world."""
        members = self._tp.member_eids()
        if not 0 <= src_rank < len(members):
            return False
        suspect_eid = members[src_rank]
        try:
            resp = self._suspect_channel().suspect(self.name, suspect_eid,
                                                   wait_secs)
        except (RuntimeError, OSError, ValueError):
            # a failed vote never poisons a healthy round; drop the channel
            # so the next report dials fresh
            sus, self._sus_client = self._sus_client, None
            if sus is not None:
                try:
                    sus.close()
                except OSError:  # toslint: allow-silent(best-effort teardown of a failed suspicion channel)
                    pass
            return False
        telemetry.counter("collective.suspects_total").inc()
        ttrace.event("suspect", group=self.name, executor=self.executor_id,
                     peer=suspect_eid, wait_secs=round(wait_secs, 2))
        logger.warning("collective group %r: rank %d (executor %d) running "
                       "%.1fs behind; suspicion filed with the coordinator",
                       self.name, src_rank, suspect_eid, wait_secs)
        evicted = {int(e) for e in resp.get("evicted") or ()}
        return bool(evicted & set(members))

    def check_grow(self, min_interval: float = 1.0) -> bool:
        """Cheap grow-back poll (rate-limited to one control round-trip per
        ``min_interval``): True when a previously evicted member has been
        readmitted and a :meth:`reform` would stand a LARGER world at the
        next generation barrier.  Call it at step boundaries; on True,
        ``reform`` + ``sync_state`` level the rejoiner."""
        now = time.monotonic()
        if now - self._grow_checked < min_interval:
            return False
        self._grow_checked = now
        eff = self._effective_world()
        return bool(eff is not None and self._members
                    and eff > len(self._members))

    # -- collectives -----------------------------------------------------------

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _run(self, label: str, seq: int, fn):
        """Execute one collective op on the comm thread and account for it;
        a :class:`CollectiveAborted` tears the peer channels down so the
        abort cascades to every member before their timeouts expire."""
        t0 = time.monotonic()
        fut = self._exec.submit(fn)
        try:
            # backstop only: the op's own recv/socket timeouts fire first
            out = fut.result(timeout=2.0 * self._timeout + 30.0)
        except concurrent.futures.TimeoutError:
            self._abort_round(label, seq)
            raise CollectiveAborted(
                f"collective {label} (seq {seq}) wedged past "
                f"{2.0 * self._timeout + 30.0:.0f}s") from None
        except Exception:
            # ANY failure poisons the round — not just CollectiveAborted: a
            # programming error (shape mismatch in the accumulate, a bad
            # dtype) on one member must still cascade the abort to its
            # peers, or they sit out the full collective timeout blind
            self._abort_round(label, seq)
            raise
        dur = time.monotonic() - t0
        telemetry.counter("collective.rounds_total").inc()
        telemetry.histogram(f"collective.{label}_secs").observe(dur)
        ttrace.record_span("collective.round", ttrace.sample(), None,
                           t0, dur, {"op": label, "seq": seq,
                                     "gen": self.generation})
        return out

    def _abort_round(self, label: str, seq: int) -> None:
        """Poison the current generation (local waiters + peer cascade) and
        meter/record the abort."""
        self._tp.poison_generation()
        telemetry.counter("collective.aborts_total").inc()
        ttrace.event("collective_abort", group=self.name,
                     generation=self.generation, op=label, seq=seq)

    def all_reduce(self, arr, average: bool = False,
                   algo: str | None = None) -> np.ndarray:
        """Element-wise sum (or mean) of ``arr`` across the group."""
        seq = self._next_seq()
        algo = algo or self._algo
        bb = self._bucket_bytes
        return self._run("all_reduce", seq,
                         lambda: cops.all_reduce(self._tp, arr, seq=seq,
                                                 bucket_bytes=bb, algo=algo,
                                                 average=average))

    def reduce_scatter(self, arr, average: bool = False) -> tuple[int, np.ndarray]:
        seq = self._next_seq()
        bb = self._bucket_bytes
        return self._run("reduce_scatter", seq,
                         lambda: cops.reduce_scatter(self._tp, arr, seq=seq,
                                                     bucket_bytes=bb,
                                                     average=average))

    def all_gather(self, arr) -> list[np.ndarray]:
        seq = self._next_seq()
        return self._run("all_gather", seq,
                         lambda: cops.all_gather(self._tp, arr, seq=seq))

    def broadcast(self, arr=None, root: int = 0) -> np.ndarray:
        seq = self._next_seq()
        bb = self._bucket_bytes
        return self._run("broadcast", seq,
                         lambda: cops.broadcast(self._tp, arr, seq=seq,
                                                root=root, bucket_bytes=bb))

    def sparse_all_to_all(self, parts: list) -> list:
        """Personalized exchange of per-destination ``(ids, values)`` CSR
        pairs (the embedding tier's lookup request/response legs); returns
        the received pairs indexed by source rank.  Same comm thread, same
        generation fencing, same abort cascade as the dense ops."""
        seq = self._next_seq()
        return self._run("sparse_all_to_all", seq,
                         lambda: cops.sparse_all_to_all(self._tp, parts,
                                                        seq=seq))

    def sparse_reduce_scatter(self, ids, rows, bounds) -> tuple:
        """Scatter (ids, rows) gradient contributions back to the ranks
        owning them under the shard plan's ``bounds``; returns this rank's
        exact-summed ``(uniq_ids, rows)`` — see ``ops.sparse_reduce_scatter``."""
        seq = self._next_seq()
        return self._run("sparse_reduce_scatter", seq,
                         lambda: cops.sparse_reduce_scatter(
                             self._tp, ids, rows, bounds, seq=seq))

    def barrier(self, timeout: float | None = None) -> None:
        """Control-plane barrier scoped to this group's EFFECTIVE world
        (generation-stamped name, so a stale member can never satisfy a
        live one — and a degraded formation never waits on its evicted
        member)."""
        self._client.barrier(
            f"cg.{self.name}.g{self.generation}.b{self._next_seq()}",
            self.executor_id,
            timeout=self._timeout if timeout is None else timeout,
            count=self.effective_world)

    # -- gradient buckets (the dp.make_train_step hook) ------------------------

    def all_reduce_tree(self, tree, average: bool = True,
                        bucket_bytes: int | None = None,
                        algo: str | None = None):
        """Bucketed cross-host all-reduce of a pytree (gradients).

        Leaves pack into per-dtype buckets of ``bucket_bytes``; each bucket
        is submitted to the comm thread AS IT IS PACKED, so bucket *k*'s
        ring all-reduce overlaps the host conversion (device_get) of bucket
        *k+1* — the communication/backprop overlap of the sync-training
        design, at host granularity.  Returns a tree of numpy arrays with
        the input structure (the jitted apply step re-places them).
        """
        import jax

        leaves, treedef = jax.tree.flatten(tree)
        if not leaves:
            return tree
        bb = self._bucket_bytes if bucket_bytes is None else int(bucket_bytes)
        algo = algo or self._algo
        buckets = _plan_buckets(leaves, bb)
        t0 = time.monotonic()
        flights = []
        for bucket in buckets:
            # host materialization (device->host for jax leaves) happens
            # HERE, on the caller's thread, while previous buckets reduce
            # on the comm thread
            arrs = [np.ascontiguousarray(np.asarray(leaves[i]).reshape(-1))
                    for i in bucket]
            packed = arrs[0] if len(arrs) == 1 else np.concatenate(arrs)
            seq = self._next_seq()
            fut = self._exec.submit(cops.all_reduce, self._tp, packed,
                                    seq=seq, bucket_bytes=bb, algo=algo,
                                    average=average)
            flights.append((bucket, fut))
        out_leaves: list = list(leaves)
        try:
            for bucket, fut in flights:
                packed = fut.result(timeout=2.0 * self._timeout + 30.0)
                off = 0
                for i in bucket:
                    shape = tuple(getattr(leaves[i], "shape", ()))
                    n = int(np.prod(shape, dtype=np.int64))
                    out_leaves[i] = np.asarray(packed[off:off + n]).reshape(shape)
                    off += n
        except Exception as e:  # noqa: BLE001 - classified + re-raised below
            # Poison FIRST (unblocks a bucket flight still running on the
            # comm thread within milliseconds), then reap every flight —
            # none may still be alive when a reform reconfigures ranks/seq,
            # or its stale chunks could collide with the next round's keys.
            self._abort_round("all_reduce_tree", self._seq)
            for _, fut in flights:
                fut.cancel()
                if not fut.cancelled():
                    try:
                        fut.result(timeout=self._timeout + 30.0)
                    except Exception:  # noqa: BLE001  # toslint: allow-silent(reaping poisoned flights; the primary error is re-raised below)
                        pass
            if isinstance(e, CollectiveAborted):
                raise
            if isinstance(e, concurrent.futures.TimeoutError):
                raise CollectiveAborted(
                    f"bucketed all-reduce wedged: {e}") from e
            raise
        dur = time.monotonic() - t0
        telemetry.counter("collective.rounds_total").inc()
        telemetry.histogram("collective.all_reduce_secs").observe(dur)
        ttrace.record_span("collective.round", ttrace.sample(), None, t0,
                           dur, {"op": "all_reduce_tree",
                                 "buckets": len(buckets),
                                 "gen": self.generation})
        return jax.tree.unflatten(treedef, out_leaves)

    def grad_fn(self, average: bool = True, bucket_bytes: int | None = None,
                algo: str | None = None):
        """The ``cross_host_grad_fn`` hook for
        :func:`tensorflowonspark_tpu.parallel.dp.make_train_step`: averages
        the per-host gradient tree across the group each step."""
        def fn(grads):
            return self.all_reduce_tree(grads, average=average,
                                        bucket_bytes=bucket_bytes, algo=algo)
        return fn

    # -- post-reform state resync ----------------------------------------------

    def broadcast_tree(self, tree, root: int | None = None):
        """Broadcast a whole pytree from ``root`` (bucketed like
        :meth:`all_reduce_tree`); non-root members' leaf VALUES are ignored
        — only the tree structure (shapes/dtypes) must match."""
        import jax

        root = self._root_rank if root is None else int(root)
        leaves, treedef = jax.tree.flatten(tree)
        if not leaves or self.effective_world == 1:
            return tree
        buckets = _plan_buckets(leaves, self._bucket_bytes)
        out_leaves: list = list(leaves)
        for bucket in buckets:
            seq = self._next_seq()
            if self.rank == root:
                arrs = [np.ascontiguousarray(
                    np.asarray(leaves[i]).reshape(-1)) for i in bucket]
                packed = arrs[0] if len(arrs) == 1 else np.concatenate(arrs)
            else:
                packed = None
            got = self._run("broadcast", seq,
                            lambda p=packed, s=seq: cops.broadcast(
                                self._tp, p, seq=s, root=root,
                                bucket_bytes=self._bucket_bytes))
            off = 0
            for i in bucket:
                shape = tuple(getattr(leaves[i], "shape", ()))
                n = int(np.prod(shape, dtype=np.int64))
                out_leaves[i] = np.asarray(got).reshape(-1)[
                    off:off + n].reshape(shape)
                off += n
        return jax.tree.unflatten(treedef, out_leaves)

    def sync_state(self, tree, step: int):
        """Level every member onto the agreed state after :meth:`form` /
        :meth:`reform`: the highest-step voter broadcasts its state tree and
        everyone adopts ``(its_tree, agreed_step)``.  A member already at
        the agreed step keeps its own values bit-identical (it either IS
        the root or receives the root's identical state)."""
        if self.effective_world == 1:
            return tree, int(step)
        synced = self.broadcast_tree(tree, root=self._root_rank)
        if int(step) != self.agreed_step:
            ttrace.event("collective_resync", group=self.name,
                         generation=self.generation,
                         from_step=int(step), to_step=self.agreed_step)
            logger.info("collective group %r: resynced rank %d from step %d "
                        "to step %d", self.name, self.rank, int(step),
                        self.agreed_step)
        return synced, self.agreed_step

    # -- lifecycle -------------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._exec.shutdown(wait=False)
        self._tp.close()
        try:
            self._client.close()
        except OSError:  # toslint: allow-silent(best-effort teardown of the dedicated control-plane connection)
            pass
        if self._sus_client is not None:
            try:
                self._sus_client.close()
            except OSError:  # toslint: allow-silent(best-effort teardown of the suspicion channel)
                pass
