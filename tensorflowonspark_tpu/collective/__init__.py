"""Cross-host collective communication over the cluster wire (ISSUE 12).

The subsystem behind ``cluster.train(..., mode="sync")``: coordinator-
brokered group formation with generation fencing (``group.py``), ring /
naive collective algorithms on numpy arrays (``ops.py``), and the peer
transport that rides each node's existing zero-copy data-plane port
(``transport.py``).  See the README "Synchronous training" section for
the map_fun-level walkthrough.
"""

from tensorflowonspark_tpu.collective.group import CollectiveGroup
from tensorflowonspark_tpu.collective.transport import CollectiveAborted

__all__ = ["CollectiveAborted", "CollectiveGroup"]
