"""Cross-host collective communication over the cluster wire (ISSUE 12).

The subsystem behind ``cluster.train(..., mode="sync")``: coordinator-
brokered group formation with generation fencing (``group.py``), ring /
naive dense collective algorithms plus the sparse (CSR) all-to-all /
reduce-scatter of the embedding tier on numpy arrays (``ops.py``), and the
peer transport that rides each node's existing zero-copy data-plane port
(``transport.py``).  See the README "Synchronous training" and "Sharded
embeddings" sections for the map_fun-level walkthroughs.
"""

from tensorflowonspark_tpu.collective.group import CollectiveGroup
from tensorflowonspark_tpu.collective.transport import (
    CollectiveAborted,
    pack_csr,
    unpack_csr,
)

__all__ = ["CollectiveAborted", "CollectiveGroup", "pack_csr", "unpack_csr"]
