"""Streaming inference map_funs — the RDD→device→RDD scoring path.

Reference (SURVEY.md §3.3): ``TFCluster.inference(dataRDD)`` streamed
partitions through each node's queues into the user map_fun, which emitted
exactly one result per input item via ``tf_feed.batch_results``.  The
examples all hand-wrote that loop; here it ships as a framework map_fun
driven by an exported bundle (config 5, Inception-v3 streaming inference,
BASELINE.json:11).  ``TPUModel.transform`` (pipeline.py) rides the same
loop for executor-side DataFrame scoring (reference ``pipeline._run_model``,
``pipeline.py:~500-700``).

TPU notes: the feed batch is padded to a static shape before the jitted
apply (one compile, no tail recompiles) and unpadded before emission so the
exactly-count invariant holds.
"""

from __future__ import annotations

import numpy as np


def _arg(args, name, default=None):
    if isinstance(args, dict):
        return args.get(name, default)
    return getattr(args, name, default)


def _stack_feature_column(values: list) -> np.ndarray:
    """Stack one column's rows, deciding the dtype PER COLUMN.

    A column whose every row is a wide integer (>= 32-bit) keeps its
    integer dtype — LM-style bundles feed token ids straight into
    embedding lookups, and a silent float32 cast corrupts any id above
    2**24.  Everything else — inexact rows, narrow integers (uint8
    pixels: the cast is lossless below 2**24 and existing image pipelines
    feed float32-compiled convs), or MIXED int/float rows (JSON-decoded
    data where 0 and 0.5 decode to different types) — normalizes to
    float32, the single-array contract the jitted apply fns compiled
    against.  Deciding per column (not per row) is what keeps a mixed
    column from promoting to float64 under numpy's stack rules.
    """
    arrays = [np.asarray(v) for v in values]
    if all(a.dtype.kind in "iu" and a.dtype.itemsize >= 4 for a in arrays):
        return np.stack(arrays)
    return np.stack([a if a.dtype == np.float32 else a.astype(np.float32)
                     for a in arrays])


def rows_to_features(rows: list, input_mapping: dict | None) -> np.ndarray:
    """Stack mapped feature columns into one batch array.

    Row dicts with a multi-column ``input_mapping`` are concatenated on the
    trailing feature axis in mapping order (each column flattened to
    ``[B, -1]`` first) — the single-array contract jitted apply fns expose;
    mixing integer and float columns there promotes via numpy's usual rules.
    A single mapped column keeps its natural shape (images stay ``[B,H,W,C]``)
    AND its wide-integer dtype (token ids stay ids — see
    ``_stack_feature_column``).  Non-dict rows are stacked directly.
    """
    if isinstance(rows[0], dict):
        if input_mapping:
            cols = list(input_mapping)
            missing = [c for c in cols if c not in rows[0]]
            if missing:
                raise KeyError(f"input_mapping columns {missing} not in row "
                               f"(have {sorted(rows[0])})")
        elif "features" in rows[0]:
            cols = ["features"]
        elif "image" in rows[0]:
            cols = ["image"]
        else:
            raise ValueError(
                f"cannot pick a feature column from {sorted(rows[0])}; set input_mapping"
            )
        arrays = [_stack_feature_column([r[c] for r in rows]) for c in cols]
        if len(arrays) == 1:
            return arrays[0]
        # multi-column concatenation is a dense float feature matrix by
        # construction (an id column flattened into it cannot feed an
        # embedding anyway), so integer columns cast to float32 here —
        # letting numpy promotion run would yield float64 batches the
        # jitted apply fns never compiled for
        return np.concatenate(
            [(a if a.dtype == np.float32
              else a.astype(np.float32)).reshape(a.shape[0], -1)
             for a in arrays], axis=-1)
    return _stack_feature_column(rows)


def _local_rows(arr) -> np.ndarray:
    """This process's rows of a batch-sharded global array, in order.

    The output of an SPMD forward keeps the batch-dim sharding of its input,
    so the addressable shards on this process are exactly the rows this
    host's feed contributed (contiguous, ``make_array_from_process_local_data``
    layout); concatenating them in index order reconstructs the local batch.
    Shards are deduplicated by batch offset: non-batch mesh axes (tp, ...)
    replicate each batch block across several devices, and concatenating
    every copy would silently duplicate rows.
    """
    unique = {}
    for s in arr.addressable_shards:
        unique.setdefault(s.index[0].start or 0, s)
    return np.concatenate(
        [np.asarray(unique[k].data) for k in sorted(unique)], axis=0)


def sharded_bundle_inference_loop(args, ctx) -> None:
    """Model-parallel STREAMING inference (beyond-reference capability).

    ``bundle_inference_loop`` is task-parallel: every node holds the whole
    model and scores its own partitions independently — the reference's only
    mode.  This variant serves models too large (or too sharded) for that:
    the data nodes form ONE mesh (single- or multi-process via
    ``jax_distributed``), params are sharded over it, every global step is
    one SPMD forward over the assembled global batch, and each host emits
    predictions for its OWN streamed rows only (extracted from its
    addressable output shards), preserving the ordered exactly-count
    contract end-to-end.

    Args: ``export_dir`` (bundle), ``batch_size`` (PER-HOST), optional
    ``mesh_axes`` (default ``{"fsdp": -1}`` — params sharded over every
    device; pass e.g. ``{"dp": 2, "fsdp": 2}`` to trade replication for
    bandwidth), ``postprocess``/``input_mapping`` as in
    ``bundle_inference_loop``.

    Driver contract: call ``cluster.inference(..., eof_when_done=True)`` —
    a host whose share of partitions ran out must learn it is done while
    peers are still scoring (its consensus votes/filler rounds gate their
    SPMD steps) — and give every data node at least one partition.
    """
    import jax

    from tensorflowonspark_tpu.checkpoint import load_bundle_cached
    from tensorflowonspark_tpu.models.registry import build_apply
    from tensorflowonspark_tpu.parallel import dp as dplib
    from tensorflowonspark_tpu.parallel import mesh as meshlib

    export_dir = _arg(args, "export_dir")
    if not export_dir:
        raise ValueError("sharded_bundle_inference_loop requires args.export_dir")
    batch_size = int(_arg(args, "batch_size", 64) or 64)
    postprocess = _arg(args, "postprocess")
    input_mapping = _arg(args, "input_mapping")
    mesh_axes = dict(_arg(args, "mesh_axes") or {"fsdp": -1})

    variables, _config, apply_fn = load_bundle_cached(export_dir, build_apply)
    mesh = ctx.make_mesh(**mesh_axes)
    gvars = meshlib.shard_tree(mesh, variables)  # fsdp-sharded; small leaves replicated

    def scored(v, x):
        out = apply_fn(v, x)
        # pin the batch-dim sharding: a replicated output would make every
        # host read the whole global batch and emit the WRONG rows
        return jax.lax.with_sharding_constraint(
            out, meshlib.batch_sharding(mesh, extra_dims=out.ndim - 1))

    jit_scored = jax.jit(scored)
    feed = ctx.get_data_feed(train_mode=False)
    for batch, n in dplib.make_batch_iterator(
            feed, batch_size, lambda items: rows_to_features(items, input_mapping),
            mesh=mesh, ctx=ctx):
        out = jit_scored(gvars, batch)
        if not n:
            continue  # filler round: joined the collective, nothing to emit
        preds = _local_rows(out)[:n]
        if postprocess == "argmax":
            feed.batch_results([int(p) for p in preds.argmax(axis=-1)])
        else:
            feed.batch_results(list(preds))


def bundle_inference_loop(args, ctx) -> None:
    """map_fun: score the stream with the bundle at ``args.export_dir``.

    Emits one prediction (np.ndarray of logits/scores) per input item, in
    order.  Optional args: ``batch_size`` (default 64), ``postprocess``
    ("argmax" to emit int class ids instead of logit vectors),
    ``input_mapping`` (row-dict column selection, see ``rows_to_features``).
    """
    from tensorflowonspark_tpu.checkpoint import load_bundle_cached
    from tensorflowonspark_tpu.models.registry import build_apply

    export_dir = _arg(args, "export_dir")
    if not export_dir:
        raise ValueError("bundle_inference_loop requires args.export_dir")
    batch_size = int(_arg(args, "batch_size", 64) or 64)
    postprocess = _arg(args, "postprocess")
    input_mapping = _arg(args, "input_mapping")

    variables, config, apply_fn = load_bundle_cached(export_dir, build_apply)
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        items = feed.next_batch(batch_size)
        if not items:
            continue
        n = len(items)
        padded = list(items) + [items[-1]] * (batch_size - n)
        x = rows_to_features(padded, input_mapping)
        out = apply_fn(variables, x)
        if isinstance(out, dict):
            # multi-output model: one {output name -> row value} dict per
            # item, so output_mapping (pipeline.merge_prediction_rows) can
            # route each named output to its own column
            if postprocess == "argmax":
                raise ValueError("postprocess='argmax' needs a single-output "
                                 "model; this bundle emits named outputs "
                                 f"{sorted(out)}")
            cols = {k: np.asarray(v)[:n] for k, v in out.items()}
            results = [{k: v[i] for k, v in cols.items()} for i in range(n)]
        else:
            preds = np.asarray(out)[:n]
            if postprocess == "argmax":
                results = [int(p) for p in preds.argmax(axis=-1)]
            else:
                results = list(preds)
        feed.batch_results(results)
