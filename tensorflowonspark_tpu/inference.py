"""Streaming inference map_funs — the RDD→device→RDD scoring path.

Reference (SURVEY.md §3.3): ``TFCluster.inference(dataRDD)`` streamed
partitions through each node's queues into the user map_fun, which emitted
exactly one result per input item via ``tf_feed.batch_results``.  The
examples all hand-wrote that loop; here it ships as a framework map_fun
driven by an exported bundle (config 5, Inception-v3 streaming inference,
BASELINE.json:11).  ``TPUModel.transform`` (pipeline.py) rides the same
loop for executor-side DataFrame scoring (reference ``pipeline._run_model``,
``pipeline.py:~500-700``).

TPU notes: the feed batch is padded to a static shape before the jitted
apply (one compile, no tail recompiles) and unpadded before emission so the
exactly-count invariant holds.
"""

from __future__ import annotations

import numpy as np


def _arg(args, name, default=None):
    if isinstance(args, dict):
        return args.get(name, default)
    return getattr(args, name, default)


def rows_to_features(rows: list, input_mapping: dict | None) -> np.ndarray:
    """Stack mapped feature columns into one batch array.

    Row dicts with a multi-column ``input_mapping`` are concatenated on the
    trailing feature axis in mapping order (each column flattened to
    ``[B, -1]`` first) — the single-array contract jitted apply fns expose.
    A single mapped column keeps its natural shape (images stay ``[B,H,W,C]``).
    Non-dict rows are stacked directly.
    """
    if isinstance(rows[0], dict):
        if input_mapping:
            cols = list(input_mapping)
            missing = [c for c in cols if c not in rows[0]]
            if missing:
                raise KeyError(f"input_mapping columns {missing} not in row "
                               f"(have {sorted(rows[0])})")
        elif "features" in rows[0]:
            cols = ["features"]
        elif "image" in rows[0]:
            cols = ["image"]
        else:
            raise ValueError(
                f"cannot pick a feature column from {sorted(rows[0])}; set input_mapping"
            )
        arrays = [np.stack([np.asarray(r[c], np.float32) for r in rows]) for c in cols]
        if len(arrays) == 1:
            return arrays[0]
        return np.concatenate([a.reshape(a.shape[0], -1) for a in arrays], axis=-1)
    return np.stack([np.asarray(r, np.float32) for r in rows])


def bundle_inference_loop(args, ctx) -> None:
    """map_fun: score the stream with the bundle at ``args.export_dir``.

    Emits one prediction (np.ndarray of logits/scores) per input item, in
    order.  Optional args: ``batch_size`` (default 64), ``postprocess``
    ("argmax" to emit int class ids instead of logit vectors),
    ``input_mapping`` (row-dict column selection, see ``rows_to_features``).
    """
    from tensorflowonspark_tpu.checkpoint import load_bundle_cached
    from tensorflowonspark_tpu.models.registry import build_apply

    export_dir = _arg(args, "export_dir")
    if not export_dir:
        raise ValueError("bundle_inference_loop requires args.export_dir")
    batch_size = int(_arg(args, "batch_size", 64) or 64)
    postprocess = _arg(args, "postprocess")
    input_mapping = _arg(args, "input_mapping")

    variables, config, apply_fn = load_bundle_cached(export_dir, build_apply)
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        items = feed.next_batch(batch_size)
        if not items:
            continue
        n = len(items)
        padded = list(items) + [items[-1]] * (batch_size - n)
        x = rows_to_features(padded, input_mapping)
        preds = np.asarray(apply_fn(variables, x))[:n]
        if postprocess == "argmax":
            results = [int(p) for p in preds.argmax(axis=-1)]
        else:
            results = list(preds)
        feed.batch_results(results)
