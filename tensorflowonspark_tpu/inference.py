"""Streaming inference map_funs — the RDD→device→RDD scoring path.

Reference (SURVEY.md §3.3): ``TFCluster.inference(dataRDD)`` streamed
partitions through each node's queues into the user map_fun, which emitted
exactly one result per input item via ``tf_feed.batch_results``.  The
examples all hand-wrote that loop; here it ships as a framework map_fun
driven by an exported bundle (config 5, Inception-v3 streaming inference,
BASELINE.json:11).

TPU notes: the feed batch is padded to a static shape before the jitted
apply (one compile, no tail recompiles) and unpadded before emission so the
exactly-count invariant holds.
"""

from __future__ import annotations

import numpy as np


def _arg(args, name, default=None):
    if isinstance(args, dict):
        return args.get(name, default)
    return getattr(args, name, default)


def bundle_inference_loop(args, ctx) -> None:
    """map_fun: score the stream with the bundle at ``args.export_dir``.

    Emits one prediction (np.ndarray of logits/scores) per input item, in
    order.  Optional args: ``batch_size`` (default 64), ``postprocess``
    ("argmax" to emit int class ids instead of logit vectors).
    """
    from tensorflowonspark_tpu.checkpoint import load_bundle_cached
    from tensorflowonspark_tpu.models.registry import build_apply

    export_dir = _arg(args, "export_dir")
    if not export_dir:
        raise ValueError("bundle_inference_loop requires args.export_dir")
    batch_size = int(_arg(args, "batch_size", 64) or 64)
    postprocess = _arg(args, "postprocess")

    variables, config, apply_fn = load_bundle_cached(export_dir, build_apply)
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        items = feed.next_batch(batch_size)
        if not items:
            continue
        n = len(items)
        padded = list(items) + [items[-1]] * (batch_size - n)
        x = np.stack([np.asarray(i, np.float32) for i in padded])
        preds = np.asarray(apply_fn(variables, x))[:n]
        if postprocess == "argmax":
            results = [int(p) for p in preds.argmax(axis=-1)]
        else:
            results = list(preds)
        feed.batch_results(results)
