"""In-node streaming data plane: queues + the user-facing ``DataFeed``.

Replaces the reference's ``TFManager`` (``tensorflowonspark/TFManager.py:~1-90``,
multiprocessing manager queues) and ``TFNode.DataFeed``
(``tensorflowonspark/TFNode.py:~250-430``).  Design delta (SURVEY.md §3.2):
the reference forked the user ``map_fun`` into a background process because
Spark needed its task slot back, paying a JVM→Python pickle plus a
manager-proxy hop per sample.  Here the node process is ours, so ``map_fun``
runs in the node's main thread and the feed is a plain in-process bounded
queue filled by the ``DataServer`` socket thread — no cross-process hop on
the hot path.

Semantics preserved from the reference (these are load-bearing, see
SURVEY.md §4 "queue/timeout edge cases"):

- ``next_batch(n)`` returns *up to* ``n`` items; an ``EndPartition`` marker
  ends the batch early (partial batch) so per-partition result counts line up
  for inference (``TFNode.py:~280-340``).
- An ``EndOfFeed`` sentinel sets ``done_feeding``; subsequent ``should_stop()``
  is True.  Delta from the reference, which pushed a bare ``None`` from
  ``TFSparkNode.shutdown``: here ``None`` is ordinary user data (samples with
  optional fields must survive the feed) and only the explicit marker ends it.
- ``terminate()`` sets state ``'terminating'`` and drains remaining input so
  pending upstream feed calls unblock fast (``TFNode.py:~400-430``).
- ``batch_results`` pushes to the output queue consumed by the inference
  collector (``TFNode.py:~350-380``).
"""

from __future__ import annotations

import queue
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_lock
from typing import Any, Iterable, Sequence

from tensorflowonspark_tpu import faultinject, telemetry
from tensorflowonspark_tpu.marker import EndOfFeed, EndPartition, Marker, ResultChunk
from tensorflowonspark_tpu.telemetry import trace as ttrace
from time import monotonic as _monotonic
from time import sleep as _sleep


class FeedQueues:
    """Named bounded queues + shared state dict for one node process.

    Parity with ``TFManager.start(authkey, queues, mode)``; 'local' vs
    'remote' modes are gone because there is no second Python process.
    """

    def __init__(self, qnames: Sequence[str] = ("input", "output", "error"), capacity: int = 1024):
        self._queues: dict[str, queue.Queue] = {name: queue.Queue(maxsize=capacity) for name in qnames}
        self._state: dict[str, Any] = {"state": "running"}
        # Cumulative partitions fully CONSUMED (EndPartition popped by the
        # map_fun) per queue — the consumption watermark the data server
        # reports back to the driver, so the partition ledger knows which
        # buffered-but-unconsumed partitions die with this process.  Keyed
        # markers dedupe: an at-least-once re-feed can place two
        # EndPartitions for ONE logical partition in this queue (reply lost
        # after the server queued the first marker), and double-counting
        # would over-advance the driver's watermark past still-buffered work.
        self._consumed: dict[str, int] = {name: 0 for name in qnames}
        self._consumed_keys: dict[str, set] = {name: set() for name in qnames}
        self._lock = tos_named_lock("feeding._lock")

    def get_queue(self, qname: str) -> queue.Queue:
        try:
            return self._queues[qname]
        except KeyError:
            raise KeyError(f"unknown queue {qname!r}; have {sorted(self._queues)}") from None

    def note_partition_consumed(self, qname: str, key=None) -> None:
        with self._lock:
            if key is not None:
                seen = self._consumed_keys.setdefault(qname, set())
                if key in seen:
                    return  # re-fed duplicate of a partition already counted
                seen.add(key)
            self._consumed[qname] = self._consumed.get(qname, 0) + 1
        telemetry.counter("feed.partitions_consumed").inc()

    def partitions_consumed(self, qname: str) -> int:
        with self._lock:
            return self._consumed.get(qname, 0)

    def set(self, key: str, value: Any) -> None:
        with self._lock:
            self._state[key] = value

    def compare_and_set(self, key: str, expected: Any, value: Any) -> bool:
        """Atomic state transition: set only when the current value matches
        ``expected``.  The park/unpark ladder uses this so a self-fence can
        never clobber the 'terminating' fast-drain state (stop beats park),
        and an unpark never resurrects a feed that terminated meanwhile."""
        with self._lock:
            if self._state.get(key) != expected:
                return False
            self._state[key] = value
            return True

    def get(self, key: str) -> Any:
        with self._lock:
            return self._state.get(key)


def batch_to_columns(batch: list, input_mapping: dict) -> dict:
    """Reshape a row batch into the ``{name: [values...]}`` columnar dict
    the reference's tensor-name ``input_mapping`` produced — shared by the
    driver-streamed ``DataFeed`` and the DIRECT-mode ``ingest.IngestFeed``
    so the two feed sources present identical batches to map_funs."""
    names = list(input_mapping.values())
    cols: dict[str, list] = {name: [] for name in names}
    for item in batch:
        values = item if isinstance(item, (list, tuple)) else (item,)
        for name, v in zip(names, values):
            cols[name].append(v)
    return cols


class IteratorFeed:
    """Adapt a plain Python iterator to the DataFeed consumption protocol
    (``next_batch``/``should_stop``), so direct-input-mode code (framework
    reads files itself) can reuse the same batch/consensus machinery as the
    streaming mode (``parallel.dp.make_batch_iterator``)."""

    def __init__(self, iterable):
        self._it = iter(iterable)
        self.done_feeding = False

    def next_batch(self, batch_size: int) -> list:
        batch: list = []
        while len(batch) < batch_size:
            try:
                batch.append(next(self._it))
            except StopIteration:
                self.done_feeding = True
                break
        return batch

    def should_stop(self) -> bool:
        return self.done_feeding


class DataFeed:
    """User-facing feed API inside ``map_fun`` (reference ``TFNode.DataFeed``).

    ``input_mapping``: optional ordered mapping {column → name}.  When given,
    ``next_batch`` returns ``{name: [values...]}`` columnar dicts (matching
    the reference's tensor-name mapping behaviour); otherwise a flat list of
    items.
    """

    def __init__(
        self,
        queues: FeedQueues,
        train_mode: bool = True,
        qname_in: str = "input",
        qname_out: str = "output",
        input_mapping: dict[str, str] | None = None,
        stop_event: threading.Event | None = None,
        poll_interval: float = 0.25,
    ):
        self.queues = queues
        self.train_mode = train_mode
        self.qname_in = qname_in
        self.qname_out = qname_out
        self.input_mapping = input_mapping
        self.done_feeding = False
        # Liveness: a bare q.get() would wedge map_fun forever if the driver
        # dies between partitions (zombie-free design goal, SURVEY.md §7.3-5).
        # next_batch polls at poll_interval and treats a set stop_event as
        # end-of-feed.
        self.stop_event = stop_event
        self.poll_interval = poll_interval
        # Markers of partitions whose CLOSING batch has been built but not
        # yet returned to (and processed by) the map_fun.  Counting them
        # consumed at EndPartition-pop time would let the watermark race
        # ahead of the map_fun: a death between the pop and the map_fun's
        # processing of that final batch would advance the driver's ledger
        # past a partition whose tail items were never seen — silent loss,
        # where the contract is duplicates-allowed-loss-never.  Reported on
        # the NEXT next_batch call instead (the map_fun coming back for more
        # is the proof the previous batch was handed over); the watermark
        # only ever lags, which can over-requeue but never drop.
        self._closed_unreported: list = []
        # rolling feed-queue occupancy (the autoscaling signal
        # cluster.stats() serves per node); set at batch boundaries
        self._occupancy = telemetry.gauge("feed.queue_depth")
        # partition-consume tracing: the first data item after the previous
        # EndPartition anchors the span; the marker's trace ctx (stamped by
        # a sampled driver partition / serving round) parents it.  The last
        # popped marker's ctx is exposed as ``last_trace`` so the consumer
        # (serving_loop) can hang its compute span on the same trace.
        self._part_t0: float | None = None
        self.last_trace = None
        # full-batch marker lookahead (see next_batch): a non-marker item
        # popped by the lookahead is consumed FIRST on the next call
        self._pending = None

    # -- consuming -----------------------------------------------------------

    def next_batch(self, batch_size: int) -> list | dict:
        """Pop up to ``batch_size`` items; partial on EndPartition/end-of-feed.

        Reference hot loop ``TFNode.py:~280-340``.
        """
        # Self-fence (ISSUE 13): "parked" means this node lost its
        # coordinator past TOS_COORDINATOR_GRACE_SECS — a replacement may
        # already own the slot, so taking NEW work risks split-brain.  Hold
        # here (checked once per batch, off the per-item hot path) until
        # the heartbeat loop re-admits us or gives up (stop_event).
        while self.queues.get("state") == "parked":
            if self.stop_event is not None and self.stop_event.is_set():
                break
            _sleep(self.poll_interval)
        for key in self._closed_unreported:
            self.queues.note_partition_consumed(self.qname_in, key)
        self._closed_unreported = []
        q = self.queues.get_queue(self.qname_in)
        batch: list = []
        while len(batch) < batch_size:
            if self._pending is not None:
                item, self._pending = self._pending, None
            else:
                try:
                    # fast path: drain already-buffered items without the
                    # timed get's condition-wait machinery — at zero-copy
                    # feed rates the queue is rarely empty and the per-item
                    # overhead shows
                    item = q.get_nowait()
                except queue.Empty:
                    if self.stop_event is not None and self.stop_event.is_set():
                        self.done_feeding = True
                        break
                    try:
                        item = q.get(timeout=self.poll_interval)
                    except queue.Empty:
                        # starvation signal: the consumer wanted data and
                        # the feed had none for a whole poll interval —
                        # the rate of this counter (vs feed.batches) is
                        # the "trainers starve while decode lags" evidence
                        # the ingest-tier autoscaling reads
                        telemetry.counter("feed.starved_polls").inc()
                        continue
            if isinstance(item, EndPartition):
                # the marker is FIFO-last for its partition: popping it means
                # every item of that partition left the queue
                self._note_partition_trace(item)
                if batch:
                    # the batch closing this partition still has to reach the
                    # map_fun — defer the consumption report (see __init__)
                    self._closed_unreported.append(getattr(item, "key", None))
                    break  # partial batch closes out the partition
                # empty close: every item of this partition was in batches
                # returned on earlier calls, all fully processed by now
                self.queues.note_partition_consumed(self.qname_in,
                                                    getattr(item, "key", None))
                continue  # keep waiting for real data
            if isinstance(item, EndOfFeed):
                self.done_feeding = True
                break
            if isinstance(item, Marker):
                continue
            if self._part_t0 is None:
                self._part_t0 = _monotonic()
            batch.append(item)
        if len(batch) >= batch_size:
            # marker lookahead: an exactly-full batch whose EndPartition is
            # already queued closes its partition NOW (same deferred-report
            # semantics as the partial-batch path) — without this, the
            # marker (and its trace ctx) would only pop on the NEXT call,
            # attributing a serving round's consume span to the wrong round
            nxt = None
            try:
                nxt = q.get_nowait()
            except queue.Empty:  # toslint: allow-silent(no marker buffered yet; handled below)
                if ttrace.enabled():
                    # the producer may be mid-enqueue (items drained faster
                    # than it could append the marker): a bounded wait
                    # usually catches it; if not, drop the stale ctx so the
                    # consumer's compute span goes unattributed instead of
                    # onto the PREVIOUS round's trace
                    try:
                        nxt = q.get(timeout=0.002)
                    except queue.Empty:  # toslint: allow-silent(marker genuinely late; next call pops it)
                        self.last_trace = None
            if isinstance(nxt, EndPartition):
                self._note_partition_trace(nxt)
                self._closed_unreported.append(getattr(nxt, "key", None))
            elif nxt is not None:
                self._pending = nxt
        if batch:
            self._occupancy.set(q.qsize())
            telemetry.counter("feed.batches").inc()
            telemetry.counter("feed.rows_consumed").inc(len(batch))
            # Chaos hook (no-op unless TOS_FAULTINJECT armed a `kill`): a
            # consumed batch is the deterministic clock for "die after N
            # batches" — the most brutal mid-epoch death available.
            faultinject.batch_consumed()
        if self.input_mapping:
            return self._to_columns(batch)
        return batch

    def _to_columns(self, batch: list) -> dict:
        return batch_to_columns(batch, self.input_mapping)

    def _note_partition_trace(self, item: EndPartition) -> None:
        """Close out a popped EndPartition's trace: records the node-side
        partition-consume span (first queued item seen -> marker popped)
        under the driver's partition/round span and publishes the ctx as
        ``last_trace`` for the consumer's own compute span."""
        ctx = getattr(item, "trace", None)
        self.last_trace = ctx
        t0, self._part_t0 = self._part_t0, None
        if ctx is not None:
            now = _monotonic()
            ttrace.record_child("feed.partition_consume", ctx,
                                t0 if t0 is not None else now,
                                now - t0 if t0 is not None else 0.0)

    # -- producing results (inference path) ----------------------------------

    def batch_results(self, results: Iterable[Any], chunk: bool = False) -> None:
        """Emit one result per input item.  ``chunk=True`` ships the whole
        batch as a single :class:`ResultChunk` queue item — one put and one
        ``collect`` round-trip instead of per-item queue traffic; the data
        server flattens it transparently, so collectors see identical
        per-item results either way (serving hot path)."""
        q = self.queues.get_queue(self.qname_out)
        if chunk:
            q.put(ResultChunk(results))
            return
        for r in results:
            q.put(r)

    # -- lifecycle -----------------------------------------------------------

    def should_stop(self) -> bool:
        return self.done_feeding

    def terminate(self) -> None:
        """Stop consuming: mark terminating and fast-drain remaining input."""
        self.done_feeding = True
        self.queues.set("state", "terminating")
        q = self.queues.get_queue(self.qname_in)
        while True:
            try:
                q.get(block=True, timeout=0.05)
            except queue.Empty:
                return
