"""In-node streaming data plane: queues + the user-facing ``DataFeed``.

Replaces the reference's ``TFManager`` (``tensorflowonspark/TFManager.py:~1-90``,
multiprocessing manager queues) and ``TFNode.DataFeed``
(``tensorflowonspark/TFNode.py:~250-430``).  Design delta (SURVEY.md §3.2):
the reference forked the user ``map_fun`` into a background process because
Spark needed its task slot back, paying a JVM→Python pickle plus a
manager-proxy hop per sample.  Here the node process is ours, so ``map_fun``
runs in the node's main thread and the feed is a plain in-process bounded
queue filled by the ``DataServer`` socket thread — no cross-process hop on
the hot path.

Semantics preserved from the reference (these are load-bearing, see
SURVEY.md §4 "queue/timeout edge cases"):

- ``next_batch(n)`` returns *up to* ``n`` items; an ``EndPartition`` marker
  ends the batch early (partial batch) so per-partition result counts line up
  for inference (``TFNode.py:~280-340``).
- An ``EndOfFeed`` sentinel sets ``done_feeding``; subsequent ``should_stop()``
  is True.  Delta from the reference, which pushed a bare ``None`` from
  ``TFSparkNode.shutdown``: here ``None`` is ordinary user data (samples with
  optional fields must survive the feed) and only the explicit marker ends it.
- ``terminate()`` sets state ``'terminating'`` and drains remaining input so
  pending upstream feed calls unblock fast (``TFNode.py:~400-430``).
- ``batch_results`` pushes to the output queue consumed by the inference
  collector (``TFNode.py:~350-380``).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Sequence

from tensorflowonspark_tpu.marker import EndOfFeed, EndPartition, Marker


class FeedQueues:
    """Named bounded queues + shared state dict for one node process.

    Parity with ``TFManager.start(authkey, queues, mode)``; 'local' vs
    'remote' modes are gone because there is no second Python process.
    """

    def __init__(self, qnames: Sequence[str] = ("input", "output", "error"), capacity: int = 1024):
        self._queues: dict[str, queue.Queue] = {name: queue.Queue(maxsize=capacity) for name in qnames}
        self._state: dict[str, Any] = {"state": "running"}
        self._lock = threading.Lock()

    def get_queue(self, qname: str) -> queue.Queue:
        try:
            return self._queues[qname]
        except KeyError:
            raise KeyError(f"unknown queue {qname!r}; have {sorted(self._queues)}") from None

    def set(self, key: str, value: Any) -> None:
        with self._lock:
            self._state[key] = value

    def get(self, key: str) -> Any:
        with self._lock:
            return self._state.get(key)


class IteratorFeed:
    """Adapt a plain Python iterator to the DataFeed consumption protocol
    (``next_batch``/``should_stop``), so direct-input-mode code (framework
    reads files itself) can reuse the same batch/consensus machinery as the
    streaming mode (``parallel.dp.make_batch_iterator``)."""

    def __init__(self, iterable):
        self._it = iter(iterable)
        self.done_feeding = False

    def next_batch(self, batch_size: int) -> list:
        batch: list = []
        while len(batch) < batch_size:
            try:
                batch.append(next(self._it))
            except StopIteration:
                self.done_feeding = True
                break
        return batch

    def should_stop(self) -> bool:
        return self.done_feeding


class DataFeed:
    """User-facing feed API inside ``map_fun`` (reference ``TFNode.DataFeed``).

    ``input_mapping``: optional ordered mapping {column → name}.  When given,
    ``next_batch`` returns ``{name: [values...]}`` columnar dicts (matching
    the reference's tensor-name mapping behaviour); otherwise a flat list of
    items.
    """

    def __init__(
        self,
        queues: FeedQueues,
        train_mode: bool = True,
        qname_in: str = "input",
        qname_out: str = "output",
        input_mapping: dict[str, str] | None = None,
        stop_event: threading.Event | None = None,
        poll_interval: float = 0.25,
    ):
        self.queues = queues
        self.train_mode = train_mode
        self.qname_in = qname_in
        self.qname_out = qname_out
        self.input_mapping = input_mapping
        self.done_feeding = False
        # Liveness: a bare q.get() would wedge map_fun forever if the driver
        # dies between partitions (zombie-free design goal, SURVEY.md §7.3-5).
        # next_batch polls at poll_interval and treats a set stop_event as
        # end-of-feed.
        self.stop_event = stop_event
        self.poll_interval = poll_interval

    # -- consuming -----------------------------------------------------------

    def next_batch(self, batch_size: int) -> list | dict:
        """Pop up to ``batch_size`` items; partial on EndPartition/end-of-feed.

        Reference hot loop ``TFNode.py:~280-340``.
        """
        q = self.queues.get_queue(self.qname_in)
        batch: list = []
        while len(batch) < batch_size:
            try:
                item = q.get(timeout=self.poll_interval)
            except queue.Empty:
                if self.stop_event is not None and self.stop_event.is_set():
                    self.done_feeding = True
                    break
                continue
            if isinstance(item, EndPartition):
                if batch:
                    break  # partial batch closes out the partition
                continue  # empty partition: keep waiting for real data
            if isinstance(item, EndOfFeed):
                self.done_feeding = True
                break
            if isinstance(item, Marker):
                continue
            batch.append(item)
        if self.input_mapping:
            return self._to_columns(batch)
        return batch

    def _to_columns(self, batch: list) -> dict:
        names = list(self.input_mapping.values())
        cols: dict[str, list] = {name: [] for name in names}
        for item in batch:
            values = item if isinstance(item, (list, tuple)) else (item,)
            for name, v in zip(names, values):
                cols[name].append(v)
        return cols

    # -- producing results (inference path) ----------------------------------

    def batch_results(self, results: Iterable[Any]) -> None:
        q = self.queues.get_queue(self.qname_out)
        for r in results:
            q.put(r)

    # -- lifecycle -----------------------------------------------------------

    def should_stop(self) -> bool:
        return self.done_feeding

    def terminate(self) -> None:
        """Stop consuming: mark terminating and fast-drain remaining input."""
        self.done_feeding = True
        self.queues.set("state", "terminating")
        q = self.queues.get_queue(self.qname_in)
        while True:
            try:
                q.get(block=True, timeout=0.05)
            except queue.Empty:
                return
