"""Profiling/tracing — JAX profiler traces viewable in TensorBoard.

Reference (SURVEY.md §5.1): no in-repo profiler; observability is the
TensorBoard subprocess TFoS spawns and whatever users write in ``map_fun``.
TPU build keeps that surface and backs it with the JAX profiler: traces
written under ``<log_dir>/plugins/profile`` appear in TensorBoard's profile
plugin next to the scalars ``summary.py`` writes.

Surfaces:
- ``trace(log_dir)`` — context manager around a region (e.g. N train steps);
- ``profile_steps(log_dir, step_iter, warmup, steps)`` — trace a step-loop
  window, the standard "skip compile, profile steady state" recipe;
- ``annotate(name)`` — named sub-region (shows as a track in the viewer);
- ``server(port)`` — on-demand profiling server for ``tensorboard capture``.
"""

from __future__ import annotations

import contextlib
import logging
import os

logger = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False):
    """Trace everything inside the block into ``log_dir`` (TensorBoard
    profile plugin format).  Safe on CPU-only test hosts."""
    import jax

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named trace region: ``with annotate('train_step'): ...``."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def profile_steps(log_dir: str, step_fn, *, warmup: int = 2, steps: int = 5):
    """Run ``step_fn()`` ``warmup`` times untraced (compile + cache), then
    ``steps`` times inside a trace.  Returns the last step's result."""
    result = None
    for _ in range(warmup):
        result = step_fn()
    with trace(log_dir):
        for i in range(steps):
            with annotate(f"step_{i}"):
                result = step_fn()
    return result


def server(port: int = 9012):
    """Start the on-demand profiler server (``tensorboard capture`` target).

    Returns the server object (keep a reference; there is no stop API in
    jax's public surface — it lives for the process)."""
    import jax

    logger.info("starting jax profiler server on port %d", port)
    return jax.profiler.start_server(port)
