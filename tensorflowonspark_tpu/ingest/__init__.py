"""tensorflowonspark_tpu.ingest — node-side direct ingestion (InputMode.DIRECT).

The ``InputMode.TENSORFLOW`` half of the reference, rebuilt per the tf.data
paper's input-pipeline design (PAPERS.md): the driver's partition ledger
assigns TFRecord *shard paths* as work items — keeping at-least-once
re-feed, elastic restart recovery, and incarnation fencing exactly as in
streaming mode — and every node reads, CRC-verifies, decodes, and
prefetches its shards itself, so aggregate feed bandwidth scales with the
node count instead of capping at one driver core.

Pieces:

- :mod:`~tensorflowonspark_tpu.ingest.shards` — driver-side shard
  enumeration (dir / glob / URI -> ledger partitions of paths);
- :mod:`~tensorflowonspark_tpu.ingest.readers` — the
  :class:`ReaderPipeline`: parallel-interleaved shard readers with bounded
  decode queues, occupancy-autotuned parallelism, and prefetch helpers
  (:func:`prefetch_iterator`, :func:`device_prefetch`);
- :mod:`~tensorflowonspark_tpu.ingest.feed` — :class:`IngestFeed`, the
  DIRECT-mode ``DataFeed`` twin a map_fun gets from ``ctx.get_data_feed()``.

- :mod:`~tensorflowonspark_tpu.ingest.service` — the DISAGGREGATED tier:
  standalone data-service workers (``role="ingest"``,
  ``cluster.run(ingest_workers=N)``) that claim the ledger's shard items,
  decode on their own cores with a cross-epoch :class:`ChunkCache`, and
  stream packed chunks to trainers over the zero-copy wire — the trainers'
  :class:`IngestFeed` then acts as a pure consumer.

Knobs: ``TOS_INGEST_READERS`` (reader-pool ceiling), ``TOS_INGEST_PREFETCH``
(decoded-chunk prefetch depth), ``TOS_INGEST_AUTOTUNE`` (occupancy-driven
pool sizing), ``TOS_INGEST_ZEROCOPY`` (memoryview record views — 0 restores
bytes copies, ``debug`` makes retained views fail loudly),
``TOS_INGEST_SPAN_BYTES`` (sub-shard split granularity; 0 keeps shards
whole), ``TOS_INGEST_WORKERS`` (data-service tier size),
``TOS_INGEST_CACHE_BYTES`` (cross-epoch chunk-cache budget; 0 disables),
``TOS_INGEST_SHUFFLE`` (global shuffle across the pool; 0 pins workers to
trainers).
"""

from tensorflowonspark_tpu.ingest.feed import IngestFeed  # noqa: F401
from tensorflowonspark_tpu.ingest.readers import (  # noqa: F401
    ReaderPipeline,
    ShardDone,
    ShardReadError,
    device_prefetch,
    prefetch_iterator,
)
from tensorflowonspark_tpu.ingest.service import (  # noqa: F401
    ChunkCache,
    IngestService,
    TrainerForwarder,
    ingest_worker_main,
)
from tensorflowonspark_tpu.ingest.shards import (  # noqa: F401
    ShardSpan,
    enumerate_shards,
    shards_as_partitioned,
    split_shards,
    work_item_key,
)
